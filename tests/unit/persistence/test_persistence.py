"""Persistence tests — replacement for the reference's
``tests/unit/server/test_model_manager.py:38-83`` and ``test_fault_tolerance.py:56-212``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.core.exceptions import CheckpointError, ModelManagerError, NanoFedError
from nanofed_tpu.models import get_model
from nanofed_tpu.persistence import (
    CheckpointMetadata,
    FileStateStore,
    ModelManager,
    SimpleRecoveryStrategy,
    is_recoverable,
    load_pytree_npz,
    save_pytree_npz,
)


@pytest.fixture
def params():
    return get_model("mlp", in_features=4, hidden=8, num_classes=3).init(jax.random.key(0))


class TestSerialization:
    def test_npz_round_trip_exact(self, params, tmp_path):
        p = tmp_path / "ckpt.npz"
        save_pytree_npz(p, params)
        restored = load_pytree_npz(p, like=params)
        assert jax.tree.structure(restored) == jax.tree.structure(params)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_load_without_template_gives_nested_dict(self, params, tmp_path):
        p = tmp_path / "ckpt.npz"
        save_pytree_npz(p, {"layer": {"w": jnp.ones((2, 2)), "b": jnp.zeros(2)}})
        d = load_pytree_npz(p)
        assert set(d) == {"layer"}
        assert set(d["layer"]) == {"w", "b"}

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(CheckpointError):
            load_pytree_npz(tmp_path / "nope.npz")

    def test_shape_mismatch_raises(self, tmp_path):
        p = tmp_path / "ckpt.npz"
        save_pytree_npz(p, {"w": jnp.ones((2, 2))})
        with pytest.raises(CheckpointError):
            load_pytree_npz(p, like={"w": jnp.ones((3, 3))})


class TestModelManager:
    def test_save_load_round_trip(self, params, tmp_path):
        mm = ModelManager(tmp_path)
        v = mm.save_model(params, metadata={"round": 3, "metrics": {"loss": 0.5}})
        assert v.version_id.startswith("model_v_")
        assert v.round_number == 3
        restored, version = mm.load_model(like=params)
        assert version.version_id == v.version_id
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_load_latest_and_specific(self, params, tmp_path):
        mm = ModelManager(tmp_path)
        v1 = mm.save_model(params, metadata={"round": 0})
        bigger = jax.tree.map(lambda x: x + 1.0, params)
        v2 = mm.save_model(bigger, metadata={"round": 1})
        latest, version = mm.load_model(like=params)
        assert version.version_id == v2.version_id
        first, _ = mm.load_model(version_id=v1.version_id, like=params)
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(latest)[0]),
            np.asarray(jax.tree.leaves(first)[0]) + 1.0,
        )

    def test_list_versions_ordered(self, params, tmp_path):
        mm = ModelManager(tmp_path)
        ids = [mm.save_model(params, metadata={"round": i}).version_id for i in range(3)]
        assert [v.version_id for v in mm.list_versions()] == ids

    def test_counter_survives_new_manager(self, params, tmp_path):
        ModelManager(tmp_path).save_model(params)
        v2 = ModelManager(tmp_path).save_model(params)
        assert v2.version_id.endswith("_0002")

    def test_load_empty_raises(self, tmp_path):
        with pytest.raises(ModelManagerError):
            ModelManager(tmp_path).load_model()


class TestFileStateStore:
    def test_checkpoint_restore_round_trip(self, params, tmp_path):
        store = FileStateStore(tmp_path)
        opt_state = {"momentum": jax.tree.map(jnp.zeros_like, params)}
        store.checkpoint(2, params, server_state=opt_state, metrics={"loss": 0.1})
        restored = store.restore_latest()
        assert restored is not None
        assert restored.round_number == 2
        assert restored.metadata.metrics["loss"] == 0.1
        assert jax.tree.structure(restored.params) == jax.tree.structure(
            jax.tree.map(np.asarray, params)
        )
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_restore_latest_skips_failed(self, params, tmp_path):
        store = FileStateStore(tmp_path)
        store.checkpoint(0, params, status="COMPLETED")
        store.checkpoint(1, params, status="FAILED")
        restored = store.restore_latest()
        assert restored.round_number == 0

    def test_restore_latest_empty_is_none(self, tmp_path):
        assert FileStateStore(tmp_path).restore_latest() is None

    def test_torn_checkpoint_ignored(self, params, tmp_path):
        store = FileStateStore(tmp_path)
        store.checkpoint(0, params)
        # Simulate a crash mid-write of round 1: state without metadata.
        d = store.base_dir / "round_1"
        d.mkdir()
        (d / "state.pkl").write_bytes(b"garbage")
        assert store.restore_latest().round_number == 0

    def test_prune_keeps_last_k(self, params, tmp_path):
        store = FileStateStore(tmp_path, keep_last=2)
        for r in range(5):
            store.checkpoint(r, params)
        rounds = [m.round_number for m in store.list_checkpoints()]
        assert rounds == [3, 4]

    def test_prune_protects_last_completed(self, params, tmp_path):
        # FAILED rounds filling the keep budget must not evict the only recovery point.
        store = FileStateStore(tmp_path, keep_last=2)
        store.checkpoint(0, params, status="COMPLETED")
        store.checkpoint(1, params, status="FAILED")
        store.checkpoint(2, params, status="FAILED")
        assert store.restore_latest() is not None
        assert store.restore_latest().round_number == 0
        # A newer COMPLETED checkpoint releases the old one for pruning.
        store.checkpoint(3, params, status="COMPLETED")
        store.checkpoint(4, params, status="FAILED")
        store.checkpoint(5, params, status="FAILED")
        rounds = [m.round_number for m in store.list_checkpoints()]
        assert 3 in rounds and 0 not in rounds
        assert store.restore_latest().round_number == 3

    def test_metadata_round_trip(self):
        m = CheckpointMetadata(round_number=7, status="FAILED", timestamp="t", metrics={"a": 1})
        assert CheckpointMetadata.from_dict(m.to_dict()) == m


class TestRecoveryPolicy:
    def test_recoverable_exceptions(self):
        assert is_recoverable(TimeoutError())
        assert is_recoverable(ConnectionError())
        assert is_recoverable(RuntimeError())
        assert not is_recoverable(ValueError())
        assert not is_recoverable(NanoFedError("deterministic bug"))

    def test_strategy_respects_max_retries(self):
        s = SimpleRecoveryStrategy(max_retries=2)
        assert s.should_recover(TimeoutError(), attempt=0)
        assert s.should_recover(TimeoutError(), attempt=1)
        assert not s.should_recover(TimeoutError(), attempt=2)
        assert not s.should_recover(ValueError(), attempt=0)


class TestReviewRegressions:
    """Pin down fixes from code review: malformed configs, FAILED status, retry budget."""

    def test_malformed_config_skipped_in_listing(self, params, tmp_path):
        mm = ModelManager(tmp_path)
        v = mm.save_model(params)
        (mm.configs_dir / "model_v_x_0099.json").write_text("{}")  # valid JSON, no keys
        assert [x.version_id for x in mm.list_versions()] == [v.version_id]
        restored, version = mm.load_model(like=params)
        assert version.version_id == v.version_id

    def test_failed_round_checkpoint_status(self, params, tmp_path):
        store = FileStateStore(tmp_path)
        store.checkpoint(0, params, status="COMPLETED")
        store.checkpoint(1, params, status="FAILED")
        metas = {m.round_number: m.status for m in store.list_checkpoints()}
        assert metas == {0: "COMPLETED", 1: "FAILED"}
