"""Crash-durability of the atomic checkpoint publish (persistence.serialization).

``tmp.replace(path)`` alone survives a PROCESS crash (the rename is atomic)
but not a HOST crash: without an fsync of the file before the rename the new
name can point at pages still in the page cache, and without an fsync of the
parent directory after it the rename itself can be lost — the exact failure
``host_crash`` injects the moment after "checkpoint written".  These tests
drive the publish through an injected os-level fault double and assert the
ordering contract: fsync(file) BEFORE replace, fsync(parent dir) AFTER, and a
failed file-fsync never publishes a path the marker protocol would then trust.
"""

import os

import numpy as np
import pytest

from nanofed_tpu.persistence.serialization import (
    load_pytree_npz,
    load_state_pickle,
    save_pytree_npz,
    save_state_pickle,
)

TREE = {"layer": {"w": np.ones((2, 2), dtype=np.float32)}}


class FsyncRecorder:
    """Fault double for the os layer: records every fsync (file fds vs
    directory fds) and the rename, so ordering is assertable; optionally
    raises on the file fsync to simulate the dying-disk path."""

    def __init__(self, monkeypatch, fail_file_fsync=False):
        import pathlib

        self.calls = []
        self.fail_file_fsync = fail_file_fsync
        self._real_fsync = os.fsync
        real_replace = pathlib.Path.replace
        rec = self

        def patched_replace(path_self, target):
            rec.calls.append("replace")
            return real_replace(path_self, target)

        monkeypatch.setattr(os, "fsync", self._fsync)
        # pathlib binds os.replace at class-creation time; intercept the
        # Path method (the seam the publish actually calls).
        monkeypatch.setattr(pathlib.Path, "replace", patched_replace)

    def _fsync(self, fd):
        import stat

        is_dir = stat.S_ISDIR(os.fstat(fd).st_mode)
        self.calls.append("fsync_dir" if is_dir else "fsync_file")
        if self.fail_file_fsync and not is_dir:
            raise OSError(28, "No space left on device")
        return self._real_fsync(fd)



@pytest.mark.parametrize("save,load,name", [
    (save_state_pickle, load_state_pickle, "state.pkl"),
    (save_pytree_npz, load_pytree_npz, "params.npz"),
])
def test_publish_fsyncs_file_before_and_dir_after_rename(
    tmp_path, monkeypatch, save, load, name
):
    rec = FsyncRecorder(monkeypatch)
    path = tmp_path / name
    save(path, TREE)
    assert "fsync_file" in rec.calls and "fsync_dir" in rec.calls
    assert rec.calls.index("fsync_file") < rec.calls.index("replace")
    assert rec.calls.index("replace") < rec.calls.index("fsync_dir")
    loaded = load(path)
    np.testing.assert_array_equal(
        np.asarray(loaded["layer"]["w"]), TREE["layer"]["w"]
    )


def test_failed_file_fsync_never_publishes(tmp_path, monkeypatch):
    # If the data cannot be made durable, the checkpoint must not appear at
    # its final name: a commit marker written next would otherwise vouch for
    # state that a host crash can still lose.
    FsyncRecorder(monkeypatch, fail_file_fsync=True)
    path = tmp_path / "state.pkl"
    with pytest.raises(OSError, match="No space left"):
        save_state_pickle(path, TREE)
    assert not path.exists()


def test_failed_dir_fsync_degrades_without_error(tmp_path, monkeypatch):
    # Directory fds reject fsync on some filesystems; the publish must not
    # fail there — it degrades to pre-fsync durability.
    real_fsync = os.fsync

    def flaky(fd):
        import stat

        if stat.S_ISDIR(os.fstat(fd).st_mode):
            raise OSError(22, "Invalid argument")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", flaky)
    path = tmp_path / "state.pkl"
    save_state_pickle(path, TREE)
    assert path.exists()
