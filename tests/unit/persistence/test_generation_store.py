"""persistence.generation_store: the multi-host commit-by-all recovery rule
and the at-most-one-block loss guarantee it exists to provide."""

import json

import numpy as np
import pytest

from nanofed_tpu.core.exceptions import CheckpointError
from nanofed_tpu.persistence import GenerationStore


PARAMS = {"dense": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
STATE = {"momentum": np.zeros(3, dtype=np.float32)}


def _commit(base, host, gen, rnd, hosts, scale=1.0):
    store = GenerationStore(base, host=host)
    params = {"dense": {"w": PARAMS["dense"]["w"] * scale}}
    store.commit(gen, rnd, params, STATE, hosts=hosts)
    return store


def test_generation_complete_only_when_all_participants_committed(tmp_path):
    _commit(tmp_path, 0, 1, 2, hosts=[0, 1])
    store = GenerationStore(tmp_path)
    assert not store.is_complete(1)  # host 1 still writing
    assert store.latest_complete() is None
    _commit(tmp_path, 1, 1, 2, hosts=[0, 1])
    assert store.is_complete(1)
    rec = store.latest_complete()
    assert rec.generation == 1 and rec.round_number == 2
    assert rec.hosts == (0, 1)
    np.testing.assert_array_equal(rec.params["dense"]["w"], PARAMS["dense"]["w"])


def test_recovery_skips_torn_newest_generation(tmp_path):
    # Gen 1 complete; gen 2 torn (one host died mid-boundary): recovery must
    # take gen 1 — resuming a half-committed generation would fork the model.
    for h in (0, 1):
        _commit(tmp_path, h, 1, 2, hosts=[0, 1])
    _commit(tmp_path, 0, 2, 4, hosts=[0, 1])
    rec = GenerationStore(tmp_path, host=1).latest_complete()
    assert rec.generation == 1 and rec.round_number == 2


def test_at_most_one_block_loss(tmp_path):
    # The guarantee, end to end: block size B, failure at round r — recovery
    # resumes at most B rounds back, whatever r is.
    B = 3
    for fail_round in range(1, 10):
        base = tmp_path / f"case_{fail_round}"
        completed_boundaries = fail_round // B  # commits that happened
        for g in range(1, completed_boundaries + 1):
            for h in (0, 1):
                _commit(base, h, g, g * B, hosts=[0, 1])
        rec = GenerationStore(base).latest_complete()
        resumed = rec.round_number if rec else 0
        assert 0 <= fail_round - resumed < B + 1
        assert fail_round - resumed == fail_round % B


def test_restore_prefers_own_shard_but_any_survivor_works(tmp_path):
    _commit(tmp_path, 0, 1, 2, hosts=[0, 1], scale=1.0)
    _commit(tmp_path, 1, 1, 2, hosts=[0, 1], scale=1.0)
    # A read-only reader (the supervisor) and a surviving host both restore.
    assert GenerationStore(tmp_path).latest_complete().generation == 1
    assert GenerationStore(tmp_path, host=1).latest_complete().generation == 1
    # A rejoining host that never wrote gen 1 restores from a peer's file.
    assert GenerationStore(tmp_path, host=7).latest_complete().generation == 1


def test_shrunk_participant_set_is_a_legal_recovery_point(tmp_path):
    # Full mesh commits gen 1; host 0 dies; the SHRUNK set commits gen 2
    # with hosts=[1].  Recovery resumes gen 2 — the elastic-reshape case.
    for h in (0, 1):
        _commit(tmp_path, h, 1, 2, hosts=[0, 1])
    _commit(tmp_path, 1, 2, 4, hosts=[1])
    rec = GenerationStore(tmp_path).latest_complete()
    assert rec.generation == 2 and rec.hosts == (1,)


def test_disagreeing_participant_sets_are_not_complete(tmp_path):
    # Two hosts committed the same generation under DIFFERENT participant
    # sets: a torn reshape.  Not a recovery point.
    _commit(tmp_path, 0, 1, 2, hosts=[0, 1])
    _commit(tmp_path, 1, 1, 2, hosts=[1])
    store = GenerationStore(tmp_path)
    assert not store.is_complete(1)
    assert store.latest_complete() is None


def test_marker_without_state_file_is_incomplete(tmp_path):
    _commit(tmp_path, 0, 1, 2, hosts=[0])
    (tmp_path / "generations" / "gen_1" / "host_0.state.pkl").unlink()
    assert not GenerationStore(tmp_path).is_complete(1)


def test_writer_validation(tmp_path):
    with pytest.raises(CheckpointError, match="read-only"):
        GenerationStore(tmp_path).commit(1, 2, PARAMS, STATE, hosts=[0])
    with pytest.raises(CheckpointError, match="generation"):
        GenerationStore(tmp_path, host=0).commit(-1, 2, PARAMS, STATE, hosts=[0])


def test_marker_is_json_an_operator_can_read(tmp_path):
    _commit(tmp_path, 0, 3, 6, hosts=[0, 2])
    marker = json.loads(
        (tmp_path / "generations" / "gen_3" / "host_0.commit.json").read_text()
    )
    assert marker == {"host": 0, "generation": 3, "round": 6, "hosts": [0, 2]}
