"""Codec round-trip tests on ADAPTER-SHAPED pytrees (ISSUE 15 satellite).

The existing codec regression tests cover dense MLP/CNN shapes; adapter trees
are a different animal — many tiny ``[d, r]``/``[r, d]`` leaves next to one
large embedding-sized leaf, nested one level deeper (``.../kernel/A``) — and
the q8/topk encoders do per-leaf scale/top-k selection, so the shape mix is
exactly where a per-leaf bug would hide.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.adapters import AdapterSpec, init_adapters
from nanofed_tpu.communication.codec import (
    decode_delta_q8,
    decode_delta_topk8,
    decode_params,
    encode_delta_q8,
    encode_delta_topk8,
    encode_params,
)
from nanofed_tpu.models import get_model
from nanofed_tpu.utils.trees import tree_flatten_with_names

RANK = 4
WIDTH, VOCAB = 128, 1024


@pytest.fixture(scope="module")
def adapter_tree():
    """Adapter-shaped delta: many small [d, r]/[r, d] pairs next to one large
    unembedding-sized leaf (the head adapter's [r, vocab] B) — sized so
    payload claims are not drowned by per-entry npz container overhead, which
    a toy-width tree cannot amortize."""
    model = get_model(
        "transformer_lm", vocab=VOCAB, seq_len=8, width=WIDTH, depth=2, heads=4
    )
    base = model.init(jax.random.key(0))
    spec = AdapterSpec(rank=RANK)
    ad = init_adapters(spec, base, rng=0)
    # Real-valued (non-zero-B) deltas, deterministic:
    rng = np.random.default_rng(42)
    return jax.tree.map(
        lambda x: np.asarray(x) + rng.normal(0, 0.01, x.shape).astype(np.float32),
        ad,
    )


def test_adapter_tree_shape_mix(adapter_tree):
    """Precondition of this file's claim: small A/B leaves AND a large one."""
    sizes = sorted(int(np.prod(x.shape)) for x in jax.tree.leaves(adapter_tree))
    assert sizes[0] <= WIDTH * RANK
    assert sizes[-1] >= VOCAB * RANK  # the head adapter's [r, vocab] B
    assert len(sizes) > 10


def test_plain_npz_round_trip(adapter_tree):
    out = decode_params(encode_params(adapter_tree), like=adapter_tree)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(adapter_tree)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_q8_round_trip_bounded_per_leaf(adapter_tree):
    """Per-leaf absmax scaling: every leaf's reconstruction error is bounded by
    ITS OWN scale step — a tiny A leaf next to the big head leaf must not
    inherit the big leaf's quantization grid."""
    payload = encode_delta_q8(adapter_tree, seed=0)
    out = decode_delta_q8(payload, like=adapter_tree)
    for (name, want), (_, got) in zip(
        tree_flatten_with_names(adapter_tree)[0], tree_flatten_with_names(out)[0]
    ):
        step = float(np.max(np.abs(want))) / 127.0
        np.testing.assert_allclose(
            np.asarray(got), want, atol=step + 1e-9, err_msg=name
        )


def test_q8_round_trip_bf16_template(adapter_tree):
    bf16 = jax.tree.map(lambda x: jnp.asarray(x, jnp.bfloat16), adapter_tree)
    out = decode_delta_q8(encode_delta_q8(adapter_tree, seed=0), like=bf16)
    for got, want in zip(jax.tree.leaves(out), jax.tree.leaves(bf16)):
        assert np.asarray(got).dtype == jnp.bfloat16
        step = float(np.max(np.abs(np.asarray(want, np.float32)))) / 127.0
        # one q8 step + bf16's ~8-bit mantissa (1/256 relative) of slack
        bf16_ulp = float(np.max(np.abs(np.asarray(want, np.float32)))) / 128.0
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32),
            np.asarray(want, dtype=np.float32),
            atol=step + bf16_ulp + 1e-6,
        )


def test_topk8_round_trip_and_payload_drop(adapter_tree):
    payload = encode_delta_topk8(adapter_tree, fraction=0.25, seed=0)
    out = decode_delta_topk8(payload, like=adapter_tree)
    # Dense reconstruction: zeros off the shipped coordinates, every leaf
    # present, template dtypes/shapes respected.
    for got, want in zip(jax.tree.leaves(out), jax.tree.leaves(adapter_tree)):
        assert np.asarray(got).shape == want.shape
        nz = np.asarray(got) != 0
        # at least the per-leaf minimum of 1 coordinate shipped
        assert nz.sum() >= 1
    # The bytes win needs a SPARSE fraction: at 5% kept, 5 bytes/coordinate
    # (uint32 idx + int8 val) beats q8's 1 byte on every coordinate; at 25%
    # on tiny A/B leaves the index overhead can exceed the saving.
    sparse = encode_delta_topk8(adapter_tree, fraction=0.05, seed=0)
    assert len(sparse) < len(encode_delta_q8(adapter_tree, seed=0))


def test_topk8_keeps_each_leafs_own_top_coordinates(adapter_tree):
    """Selection is PER LEAF: a tiny A matrix still ships its locally-largest
    coordinate even though the big head leaf dwarfs it globally."""
    payload = encode_delta_topk8(adapter_tree, fraction=0.05, seed=0)
    out = decode_delta_topk8(payload, like=adapter_tree)
    for (name, want), (_, got) in zip(
        tree_flatten_with_names(adapter_tree)[0], tree_flatten_with_names(out)[0]
    ):
        got = np.asarray(got).ravel()
        top_idx = int(np.argmax(np.abs(want.ravel())))
        assert got[top_idx] != 0.0, f"{name}: locally-largest coordinate dropped"


def test_encode_params_gathers_2d_mesh_sharded_adapter_leaves(adapter_tree):
    """Model-sharded adapter leaves off a 2-D clients x model mesh encode
    correctly: jax.device_get performs the one well-defined gather (the
    encode_params contract, extended to adapter trees)."""
    from nanofed_tpu.parallel.mesh import make_mesh, shard_params

    mesh = make_mesh(shape=(4, 2))
    sharded = shard_params(adapter_tree, mesh)
    # Precondition: at least one leaf actually lives sharded over `model`.
    assert any(
        not leaf.sharding.is_fully_replicated for leaf in jax.tree.leaves(sharded)
    )
    out = decode_params(encode_params(sharded), like=adapter_tree)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(adapter_tree)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_q8_on_2d_mesh_sharded_delta(adapter_tree):
    """The q8 encoder's host pull must assemble sharded leaves whole before
    quantizing — a per-shard absmax would change the scale."""
    from nanofed_tpu.parallel.mesh import make_mesh, shard_params

    mesh = make_mesh(shape=(4, 2))
    sharded = shard_params(adapter_tree, mesh)
    p_host = encode_delta_q8(adapter_tree, seed=0)
    p_dev = encode_delta_q8(jax.device_get(sharded), seed=0)
    got_host = decode_delta_q8(p_host, like=adapter_tree)
    got_dev = decode_delta_q8(p_dev, like=adapter_tree)
    for a, b in zip(jax.tree.leaves(got_host), jax.tree.leaves(got_dev)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
