"""Server-side secure-aggregation endpoint edge cases (aiohttp test client, no
sockets): enrollment gating, roster lifecycle, malformed masked payloads."""

import asyncio
import base64

import jax
import jax.numpy as jnp
import numpy as np
from aiohttp.test_utils import TestClient, TestServer

from nanofed_tpu.communication.http_server import (
    HEADER_CLIENT,
    HEADER_ROUND,
    HEADER_SECAGG,
    HTTPServer,
)


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _make_server() -> HTTPServer:
    return HTTPServer(port=0)


async def _with_client(fn):
    server = _make_server()
    client = TestClient(TestServer(server._app))
    await client.start_server()
    try:
        return await fn(server, client)
    finally:
        await client.close()


PK = base64.b64encode(bytes(32)).decode()


def test_register_requires_open_enrollment():
    async def scenario(server, client):
        resp = await client.post(
            "/secagg/register",
            json={"public_key": PK, "num_samples": 10.0},
            headers={HEADER_CLIENT: "c1"},
        )
        assert resp.status == 403  # not open
        await server.open_secagg(2)
        resp = await client.post(
            "/secagg/register",
            json={"public_key": PK, "num_samples": 10.0},
            headers={HEADER_CLIENT: "c1"},
        )
        assert resp.status == 200

    _run(_with_client(scenario))


def test_cohort_full_and_reregistration():
    async def scenario(server, client):
        await server.open_secagg(1)
        for cid, want in [("c1", 200), ("c2", 403), ("c1", 200)]:  # re-register ok
            resp = await client.post(
                "/secagg/register",
                json={"public_key": PK, "num_samples": 5.0},
                headers={HEADER_CLIENT: cid},
            )
            assert resp.status == want, cid

    _run(_with_client(scenario))


def test_bad_registrations_rejected():
    async def scenario(server, client):
        await server.open_secagg(3)
        bad = [
            {"public_key": base64.b64encode(b"short").decode(), "num_samples": 5.0},
            {"public_key": PK, "num_samples": 0.0},
            {"public_key": PK, "num_samples": -3.0},
            {"public_key": PK, "num_samples": "nope"},
            {"num_samples": 5.0},
        ]
        for body in bad:
            resp = await client.post(
                "/secagg/register", json=body, headers={HEADER_CLIENT: "c1"}
            )
            assert resp.status == 400, body

    _run(_with_client(scenario))


def test_roster_completion_and_weights():
    async def scenario(server, client):
        await server.open_secagg(2)
        resp = await client.get("/secagg/roster")
        payload = await resp.json()
        assert payload["complete"] is False and payload["enrolled"] == 0
        for cid, n in [("b", 30.0), ("a", 10.0)]:
            await client.post(
                "/secagg/register",
                json={"public_key": PK, "num_samples": n},
                headers={HEADER_CLIENT: cid},
            )
        payload = await (await client.get("/secagg/roster")).json()
        assert payload["complete"] is True
        assert payload["client_order"] == ["a", "b"]  # canonical sorted order
        assert abs(payload["weights"]["a"] - 0.25) < 1e-9
        assert abs(payload["weights"]["b"] - 0.75) < 1e-9

    _run(_with_client(scenario))


def test_masked_payload_structural_validation():
    params = {"w": jnp.zeros((3, 2)), "b": jnp.zeros((2,))}

    async def scenario(server, client):
        await server.open_secagg(1)
        await client.post(
            "/secagg/register",
            json={"public_key": PK, "num_samples": 5.0},
            headers={HEADER_CLIENT: "c1"},
        )
        await server.publish_model(params, 0)

        import io

        def masked_body(size, dtype=np.uint32):
            buf = io.BytesIO()
            np.savez_compressed(buf, masked=np.zeros(size, dtype))
            return buf.getvalue()

        headers = {HEADER_CLIENT: "c1", HEADER_ROUND: "0", HEADER_SECAGG: "masked"}
        # Wrong length (model has 8 params), wrong dtype, non-npz garbage, unenrolled.
        assert (await client.post("/update", data=masked_body(7), headers=headers)).status == 400
        assert (await client.post(
            "/update", data=masked_body(8, np.float32), headers=headers)).status == 400
        assert (await client.post("/update", data=b"junk", headers=headers)).status == 400
        assert (await client.post(
            "/update", data=masked_body(8),
            headers={**headers, HEADER_CLIENT: "intruder"})).status == 403
        # Correct one accepted and buffered.
        assert (await client.post("/update", data=masked_body(8), headers=headers)).status == 200
        assert server.num_masked_updates() == 1
        drained = await server.drain_masked_updates()
        assert set(drained) == {"c1"} and drained["c1"].dtype == np.uint32
        assert server.num_masked_updates() == 0

    _run(_with_client(scenario))


def test_publish_model_clears_stale_masked_updates():
    params = {"w": jnp.zeros((4,))}

    async def scenario(server, client):
        await server.open_secagg(1)
        await client.post(
            "/secagg/register",
            json={"public_key": PK, "num_samples": 5.0},
            headers={HEADER_CLIENT: "c1"},
        )
        await server.publish_model(params, 0)
        import io

        buf = io.BytesIO()
        np.savez_compressed(buf, masked=np.zeros(4, np.uint32))
        headers = {HEADER_CLIENT: "c1", HEADER_ROUND: "0", HEADER_SECAGG: "masked"}
        assert (await client.post("/update", data=buf.getvalue(), headers=headers)).status == 200
        assert server.num_masked_updates() == 1
        # Next round's publish drops the stale round-0 vector (its masks are bound to
        # round 0 and would not cancel in round 1).
        await server.publish_model(params, 1)
        assert server.num_masked_updates() == 0

    _run(_with_client(scenario))
