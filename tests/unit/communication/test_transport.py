"""Transport/session split: tenant routing, 404s, per-tenant admission
scoping, and cross-tenant dedup isolation (aiohttp test client, no sockets).

The isolation claims here are the wire half of the multi-tenant service's
contract: an unknown tenant is a 404 at the TRANSPORT, a 429 is scoped to the
over-quota tenant's session only, and idempotency-key windows live per
session so the same (client, key) pair never collides across tenants."""

import asyncio

import numpy as np
from aiohttp.test_utils import TestClient, TestServer

from nanofed_tpu.communication.codec import encode_params
from nanofed_tpu.communication.http_server import (
    HEADER_CLIENT,
    HEADER_ROUND,
    HEADER_SUBMIT,
    HTTPServer,
)
from nanofed_tpu.communication.transport import (
    HEADER_TENANT,
    HTTPTransport,
    tenant_base_url,
)
from nanofed_tpu.observability.registry import MetricsRegistry


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _params():
    return {"w": np.ones((4, 2), np.float32), "b": np.zeros((2,), np.float32)}


async def _two_tenant_client(fn, *, a_kwargs=None, b_kwargs=None):
    """A shared transport hosting tenants 'a' and 'b' (each with its own
    registry), driven through one aiohttp test client."""
    transport = HTTPTransport(port=0, registry=MetricsRegistry())
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    a = HTTPServer(transport=transport, tenant="a", registry=reg_a,
                   **(a_kwargs or {}))
    b = HTTPServer(transport=transport, tenant="b", registry=reg_b,
                   **(b_kwargs or {}))
    client = TestClient(TestServer(transport.app))
    await client.start_server()
    try:
        return await fn(transport, a, b, client)
    finally:
        await client.close()


def test_unknown_tenant_404_path_and_header():
    async def scenario(transport, a, b, client):
        resp = await client.get("/t/ghost/status")
        assert resp.status == 404
        body = await resp.json()
        assert "unknown tenant" in body["message"]
        resp = await client.get("/status", headers={HEADER_TENANT: "ghost"})
        assert resp.status == 404
        # No default session on a tenant-only transport: anonymous requests
        # are told how to address a tenant, not silently routed anywhere.
        resp = await client.get("/status")
        assert resp.status == 404
        assert transport.metrics_registry.counter(
            "nanofed_unknown_tenant_total"
        ).value() == 3.0

    _run(_two_tenant_client(scenario))


def test_tenant_routing_path_and_header_hit_the_same_session():
    async def scenario(transport, a, b, client):
        await a.publish_model(_params(), 3)
        await b.publish_model(_params(), 7)
        via_path = await (await client.get("/t/a/status")).json()
        via_header = await (
            await client.get("/status", headers={HEADER_TENANT: "a"})
        ).json()
        assert via_path["round"] == via_header["round"] == 3
        assert (await (await client.get("/t/b/status")).json())["round"] == 7

    _run(_two_tenant_client(scenario))


def test_method_mismatch_is_405_inside_the_tenant():
    async def scenario(transport, a, b, client):
        resp = await client.get("/t/a/update")  # update is POST-only
        assert resp.status == 405
        resp = await client.post("/t/a/nosuch")
        assert resp.status == 404

    _run(_two_tenant_client(scenario))


def test_head_on_get_endpoints_keeps_router_parity():
    """The pre-split aiohttp router auto-served HEAD on GET routes
    (load-balancer health probes HEAD /status); dispatch must too."""

    async def scenario(transport, a, b, client):
        resp = await client.head("/t/a/status")
        assert resp.status == 200
        assert await resp.read() == b""  # protocol layer suppresses the body
        resp = await client.head("/t/a/update")  # POST-only stays 405
        assert resp.status == 405

    _run(_two_tenant_client(scenario))


def test_429_scoped_to_the_saturated_tenant_same_tick():
    """Tenant A at max_inflight=0 sheds every submit with 429 while tenant
    B's submit — fired in the same event-loop gather — is accepted."""

    async def scenario(transport, a, b, client):
        params = _params()
        await a.publish_model(params, 0)
        await b.publish_model(params, 0)
        body = encode_params(params)
        headers = {HEADER_CLIENT: "c1", HEADER_ROUND: "0",
                   HEADER_SUBMIT: "k1"}
        resp_a, resp_b = await asyncio.gather(
            client.post("/t/a/update", data=body, headers=headers),
            client.post("/t/b/update", data=body, headers=headers),
        )
        assert resp_a.status == 429
        assert resp_a.headers["Retry-After"]
        assert resp_b.status == 200
        # The 429 landed in A's registry ONLY.
        assert a.metrics_registry.counter(
            "nanofed_http_429_total", labels=("endpoint",)
        ).value(endpoint="update") == 1.0
        assert b.metrics_registry.counter(
            "nanofed_http_429_total", labels=("endpoint",)
        ).value(endpoint="update") == 0.0

    _run(_two_tenant_client(scenario, a_kwargs={"max_inflight": 0}))


def test_submit_key_windows_never_collide_across_tenants():
    """The SAME (client id, idempotency key) pair submitted to two tenants is
    a fresh accept on each — and only a true re-submit to the SAME tenant
    dedupes."""

    async def scenario(transport, a, b, client):
        params = _params()
        await a.publish_model(params, 0)
        await b.publish_model(params, 0)
        body = encode_params(params)
        headers = {HEADER_CLIENT: "c1", HEADER_ROUND: "0",
                   HEADER_SUBMIT: "shared-key"}
        first_a = await client.post("/t/a/update", data=body, headers=headers)
        assert first_a.status == 200
        assert not (await first_a.json()).get("duplicate")
        # Same client, same key, OTHER tenant: a fresh logical submit there.
        first_b = await client.post("/t/b/update", data=body, headers=headers)
        assert first_b.status == 200
        assert not (await first_b.json()).get("duplicate")
        # Same tenant again: NOW it is the retry-storm duplicate.
        retry_a = await client.post("/t/a/update", data=body, headers=headers)
        assert retry_a.status == 200
        assert (await retry_a.json()).get("duplicate") is True
        assert a.num_updates() == 1
        assert b.num_updates() == 1

    _run(_two_tenant_client(scenario))


def test_default_session_preserves_single_tenant_wire_shape():
    """A plain HTTPServer (no shared transport) answers unprefixed paths
    exactly as before the split — and its _app stays test-client mountable."""

    async def scenario():
        server = HTTPServer(port=0)
        client = TestClient(TestServer(server._app))
        await client.start_server()
        try:
            await server.publish_model(_params(), 5)
            status = await (await client.get("/status")).json()
            assert status["round"] == 5
            resp = await client.get("/model")
            assert resp.status == 200
            assert resp.headers[HEADER_ROUND] == "5"
        finally:
            await client.close()

    _run(scenario())


def test_shared_session_refuses_direct_start():
    async def scenario(transport, a, b, client):
        try:
            await a.start()
        except RuntimeError as e:
            assert "shared transport" in str(e)
        else:
            raise AssertionError("start() on a shared session must refuse")

    _run(_two_tenant_client(scenario))


def test_remove_session_turns_tenant_into_404():
    async def scenario(transport, a, b, client):
        assert (await client.get("/t/a/test")).status == 200
        transport.remove_session("a")
        assert (await client.get("/t/a/test")).status == 404
        assert (await client.get("/t/b/test")).status == 200

    _run(_two_tenant_client(scenario))


def test_tenant_base_url():
    assert tenant_base_url("http://h:1/", "x") == "http://h:1/t/x"


def test_duplicate_tenant_mount_refused():
    transport = HTTPTransport(port=0, registry=MetricsRegistry())
    HTTPServer(transport=transport, tenant="a", registry=MetricsRegistry())
    try:
        HTTPServer(transport=transport, tenant="a",
                   registry=MetricsRegistry())
    except ValueError as e:
        assert "already mounted" in str(e)
    else:
        raise AssertionError("mounting a live tenant name twice must refuse")
