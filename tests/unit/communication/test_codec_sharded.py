"""Regression: ``encode_params`` on COMMITTED device-sharded params.

Model-sharded leaves (2-D ``clients x model`` mesh) must gather through
``jax.device_get`` before the numpy conversion — a bare ``np.asarray`` on a
sharded ``jax.Array`` can raise or silently assemble per-shard copies
depending on layout.  The payload must round-trip to the exact host values.
"""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.communication.codec import decode_params, encode_params
from nanofed_tpu.parallel import make_mesh, param_sharding, shard_params


def _params():
    rng = np.random.default_rng(0)
    return {
        "fc1": {
            "kernel": rng.normal(size=(8, 16)).astype(np.float32),
            "bias": rng.normal(size=(16,)).astype(np.float32),
        },
        "odd": rng.normal(size=(3,)).astype(np.float32),  # non-divisible: replicated
    }


def test_encode_params_gathers_model_sharded_leaves(devices):
    host = _params()
    mesh = make_mesh(devices[:2], shape=(1, 2))
    placed = shard_params(host, mesh)
    # Preconditions: the interesting leaves really are committed device-sharded.
    assert not placed["fc1"]["kernel"].sharding.is_fully_replicated
    assert len(placed["fc1"]["kernel"].sharding.device_set) == 2

    payload = encode_params(placed)
    decoded = decode_params(payload, like=host)
    for got, want in zip(jax.tree.leaves(decoded), jax.tree.leaves(host)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_encode_params_sharded_equals_replicated_payload_values(devices):
    """The wire bytes decode to identical values whether the params were
    host arrays, mesh-replicated, or model-sharded."""
    host = _params()
    mesh2d = make_mesh(devices[:4], shape=(2, 2))
    variants = {
        "host": host,
        "replicated": jax.device_put(host, param_sharding(make_mesh(devices[:4]), host)),
        "sharded": shard_params(host, mesh2d),
    }
    decoded = {
        name: decode_params(encode_params(tree), like=host)
        for name, tree in variants.items()
    }
    for name in ("replicated", "sharded"):
        for got, want in zip(
            jax.tree.leaves(decoded[name]), jax.tree.leaves(decoded["host"])
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_encode_params_still_accepts_plain_host_trees():
    host = _params()
    decoded = decode_params(encode_params(host), like=host)
    for got, want in zip(jax.tree.leaves(decoded), jax.tree.leaves(host)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_encode_params_bfloat16_sharded_roundtrip(devices):
    """dtype-tagged leaves survive the gather path too (the checkpoint layout
    tags bf16 leaves; device_get must not silently upcast)."""
    host = {"w": jnp.arange(16, dtype=jnp.bfloat16).reshape(4, 4)}
    mesh = make_mesh(devices[:2], shape=(1, 2))
    placed = shard_params(host, mesh)
    decoded = decode_params(encode_params(placed), like=host)
    assert decoded["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(decoded["w"], np.float32), np.asarray(host["w"], np.float32)
    )
