"""Retry policy (communication.retry) + idempotent submit keys on the wire.

Covers the pure backoff arithmetic, and — over a real localhost server — the
exactly-once contract the idempotency keys buy: N identical retries of one
logical submit (the storm a lost ACK produces) fold into the round AT MOST
once, including in the topk8 error-feedback path where a double-fold would
silently double-count the client's delta (ISSUE 6 satellite)."""

import asyncio
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    RETRYABLE_STATUSES,
    RetryPolicy,
    parse_retry_after,
)
from nanofed_tpu.faults import ChaosSchedule, FaultEvent, FaultPlan
from nanofed_tpu.models import get_model
from nanofed_tpu.observability.registry import MetricsRegistry

PORT = 18950


# ---------------------------------------------------------------------------
# Pure policy arithmetic
# ---------------------------------------------------------------------------


def test_policy_validation():
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError, match="base_backoff_s"):
        RetryPolicy(base_backoff_s=2.0, max_backoff_s=1.0)
    with pytest.raises(ValueError, match="multiplier"):
        RetryPolicy(multiplier=0.5)
    with pytest.raises(ValueError, match="jitter_fraction"):
        RetryPolicy(jitter_fraction=1.5)
    with pytest.raises(ValueError, match="budget_s"):
        RetryPolicy(budget_s=0)


def test_backoff_grows_exponentially_and_caps():
    policy = RetryPolicy(base_backoff_s=0.1, max_backoff_s=1.0, multiplier=2.0,
                         jitter_fraction=0.0)
    rng = random.Random(0)
    delays = [policy.backoff_s(a, rng) for a in range(1, 7)]
    assert delays[:4] == pytest.approx([0.1, 0.2, 0.4, 0.8])
    assert delays[4] == delays[5] == 1.0  # capped


def test_jitter_is_seeded_and_bounded():
    policy = RetryPolicy(base_backoff_s=1.0, max_backoff_s=1.0,
                         jitter_fraction=0.5, seed=42)
    a = [policy.backoff_s(1, policy.rng_for("c1")) for _ in range(3)]
    b = [policy.backoff_s(1, policy.rng_for("c1")) for _ in range(3)]
    assert a == b  # deterministic per (seed, client)
    assert a != [policy.backoff_s(1, policy.rng_for("c2")) for _ in range(3)]
    rng = policy.rng_for("c1")
    for _ in range(50):
        d = policy.backoff_s(1, rng)
        assert 0.5 <= d <= 1.0  # jitter shaves at most jitter_fraction


def test_retry_after_is_a_floor_under_the_backoff():
    policy = RetryPolicy(base_backoff_s=0.1, jitter_fraction=0.0)
    rng = random.Random(0)
    assert policy.backoff_s(1, rng, retry_after_s=2.0) == 2.0
    assert policy.backoff_s(1, rng, retry_after_s=0.01) == pytest.approx(0.1)


def test_parse_retry_after():
    assert parse_retry_after("3") == 3.0
    assert parse_retry_after("0.25") == 0.25
    assert parse_retry_after(None) is None
    assert parse_retry_after("Wed, 21 Oct 2026") is None
    assert parse_retry_after("-1") is None


def test_retryable_statuses_are_transient_only():
    assert 429 in RETRYABLE_STATUSES and 503 in RETRYABLE_STATUSES
    # Protocol rejections are final: retrying a stale round / bad signature
    # verbatim cannot succeed, and topk8 must fold instead.
    assert 400 not in RETRYABLE_STATUSES and 403 not in RETRYABLE_STATUSES


# ---------------------------------------------------------------------------
# Exactly-once on the wire (idempotent submit keys)
# ---------------------------------------------------------------------------


def _linear_params():
    model = get_model("linear", in_features=4, num_classes=2)
    return model.init(jax.random.key(0))


def test_lost_ack_retry_folds_exactly_once():
    """ack_drop severs the connection AFTER the server buffers the update; the
    client's retry (same idempotency key) must be answered as a duplicate, and
    — the FedBuff double-count case — a duplicate arriving after the buffer
    was DRAINED must not re-enter it."""
    params = _linear_params()
    trained = jax.tree.map(lambda p: p + 1.0, params)
    registry = MetricsRegistry()
    schedule = ChaosSchedule(
        FaultPlan(seed=1, events=(
            FaultEvent(kind="ack_drop", round=0, client="c1", count=1),
        )),
        registry=registry,
    )
    port = PORT + 1

    async def main():
        server = HTTPServer(port=port, staleness_window=2, chaos=schedule,
                            registry=registry)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                registry=registry,
                retry=RetryPolicy(max_attempts=4, base_backoff_s=0.01, seed=0),
            ) as c:
                await c.fetch_global_model(like=params)
                # Attempt 1 is buffered but its ACK is severed; the retry gets
                # the duplicate answer — the LOGICAL submit succeeds.
                assert await c.submit_update(trained, {"loss": 0.1})
                assert server.num_updates() == 1
                taken = await server.take_updates(1)
                assert [u.client_id for u in taken] == ["c1"]
                assert server.num_updates() == 0
                # The storm continues after the drain (retries can straggle in
                # long after aggregation): still deduped, never re-buffered.
                for _ in range(3):
                    assert await c.resend_last_update()
                assert server.num_updates() == 0
        finally:
            await server.stop()

    asyncio.run(main())
    text = registry.render_prometheus()
    assert 'nanofed_faults_injected_total{kind="ack_drop"} 1' in text
    # The client retried at least once, and the server answered duplicates.
    assert 'nanofed_client_retries_total' in text
    assert 'result="duplicate"' in text


def test_topk8_retry_storm_folds_delta_exactly_once():
    """The ISSUE 6 satellite: topk8 error feedback under a retry storm.  One
    logical submit, its ACK lost, N identical retries — the server must hold
    exactly ONE copy of the reconstructed update, and the client must commit
    its staged residual exactly once (``_pending_base`` cleared, residual =
    quantization tail, NOT the whole delta)."""
    params = _linear_params()
    delta = jax.tree.map(lambda p: 0.01 * jnp.ones_like(p), params)
    trained = jax.tree.map(jnp.add, params, delta)
    registry = MetricsRegistry()
    schedule = ChaosSchedule(
        FaultPlan(seed=2, events=(
            FaultEvent(kind="ack_drop", round=0, client="c1", count=2),
        )),
        registry=registry,
    )
    port = PORT + 2

    async def main():
        server = HTTPServer(port=port, chaos=schedule, registry=registry)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                update_encoding="topk8-delta", topk_fraction=1.0,
                registry=registry,
                retry=RetryPolicy(max_attempts=5, base_backoff_s=0.01, seed=0),
            ) as c:
                await c.fetch_global_model(like=params)
                assert await c.submit_update(trained, {"loss": 0.1})
                # Residual committed ONCE: pending base cleared, and what
                # remains is only the quantization tail (tiny), not the delta.
                assert c._pending_base is None
                for r, d in zip(jax.tree.leaves(c._residual),
                                jax.tree.leaves(delta)):
                    assert float(np.abs(np.asarray(r)).max()) \
                        < 0.1 * float(np.abs(np.asarray(d)).max())
                # Extra duplicates beyond the policy's own retries.
                for _ in range(4):
                    assert await c.resend_last_update()
            updates = await server.drain_updates()
            assert len(updates) == 1
            # The single buffered copy IS the client's signed reconstruction.
            for got, want in zip(jax.tree.leaves(updates[0].params),
                                 jax.tree.leaves(trained)):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=1e-3
                )
        finally:
            await server.stop()

    asyncio.run(main())
    assert schedule.counts() == {"ack_drop": 2}


def test_topk8_out_of_order_stale_then_duplicate():
    """Out-of-order composition: a FINAL rejection (stale round — retrying it
    verbatim can never succeed, so the policy must NOT retry) folds the whole
    delta into the residual with ``_pending_base`` pinned; a then-identical
    re-submit for the NEW round measures zero post-fold training, so the mass
    is carried exactly once."""
    params = _linear_params()
    trained = jax.tree.map(lambda p: p + 0.02 * jnp.ones_like(p), params)
    registry = MetricsRegistry()
    port = PORT + 3

    async def main():
        server = HTTPServer(port=port, registry=registry)
        await server.start()
        try:
            await server.publish_model(params, round_number=5)
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                update_encoding="topk8-delta", topk_fraction=1.0,
                registry=registry,
                retry=RetryPolicy(max_attempts=5, base_backoff_s=0.01, seed=0),
            ) as c:
                await c.fetch_global_model(like=params)
                # Clock-skewed straggler: submits for a round long gone.
                c.current_round = 3
                assert not await c.submit_update(trained, {"loss": 0.1})
                assert server.num_updates() == 0
                # Whole delta folded; the fold's base is pinned.
                assert c._pending_base is not None
                # Re-sync and retry on the CURRENT round: the submit carries
                # residual + zero post-fold training = the same mass, once.
                c.current_round = 5
                assert await c.submit_update(trained, {"loss": 0.1})
                assert c._pending_base is None
            (update,) = await server.drain_updates()
            for got, want in zip(jax.tree.leaves(update.params),
                                 jax.tree.leaves(trained)):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=1e-3
                )
        finally:
            await server.stop()

    asyncio.run(main())
    # 400-stale is FINAL: the retry counter must show zero http_400 retries.
    assert 'reason="http_400"' not in registry.render_prometheus()


def test_admission_control_429_then_retry_succeeds():
    """max_inflight=0 sheds every submit with 429 + Retry-After; lifting the
    cap lets the client's retry through — the load-shedding handshake end to
    end, with the 429 counter visible in the registry."""
    params = _linear_params()
    registry = MetricsRegistry()
    port = PORT + 4

    async def main():
        server = HTTPServer(port=port, max_inflight=0, retry_after_s=0.02,
                            registry=registry)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            url = f"http://127.0.0.1:{port}"
            # No retry policy: the 429 surfaces as a failed submit.
            async with HTTPClient(url, "c1", timeout_s=10,
                                  registry=registry) as c:
                await c.fetch_global_model(like=params)
                assert not await c.submit_update(params, {"loss": 0.1})
            assert server.num_updates() == 0
            # With a policy: first attempt sheds, cap lifts, retry lands.
            async with HTTPClient(
                url, "c2", timeout_s=10, registry=registry,
                retry=RetryPolicy(max_attempts=4, base_backoff_s=0.05, seed=0),
            ) as c:
                await c.fetch_global_model(like=params)
                async def lift_cap():
                    await asyncio.sleep(0.01)
                    server.max_inflight = None
                lifted = asyncio.create_task(lift_cap())
                assert await c.submit_update(params, {"loss": 0.1})
                await lifted
            assert server.num_updates() == 1
        finally:
            await server.stop()

    asyncio.run(main())
    text = registry.render_prometheus()
    assert 'nanofed_http_429_total{endpoint="update"} 2' in text
    assert 'nanofed_client_retries_total{endpoint="update",reason="http_429"} 1' \
        in text


def test_admission_control_covers_masked_submits():
    """The secagg masked path must hit the same 429 gate as plain submits —
    its bodies hold the identical read/decode resources."""
    params = _linear_params()
    registry = MetricsRegistry()
    port = PORT + 5

    async def main():
        server = HTTPServer(port=port, max_inflight=0, registry=registry)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                                  registry=registry) as c:
                assert not await c.submit_masked_update(
                    np.zeros(4, np.uint32), {"num_samples": 1.0}
                )
            assert server.num_masked_updates() == 0
        finally:
            await server.stop()

    asyncio.run(main())
    text = registry.render_prometheus()
    assert 'nanofed_http_429_total{endpoint="update"} 1' in text
    assert 'nanofed_updates_total{kind="masked",result="admission_reject"} 1' in text


def test_submit_fingerprint_binds_dedupe_to_the_signature():
    """Crypto-free pin of the dedupe-authentication rule: on a signing server
    the (key, fingerprint) pair must only match when the duplicate carries the
    ACCEPTED attempt's exact signature header; unsigned servers use an empty
    fingerprint (no authentication exists anywhere there)."""
    from types import SimpleNamespace

    from nanofed_tpu.communication.http_server import HEADER_SIGNATURE

    signing = HTTPServer(port=1, require_signatures=True,
                         registry=MetricsRegistry())
    signed = SimpleNamespace(headers={HEADER_SIGNATURE: "c2lnbmF0dXJl"})
    unsigned = SimpleNamespace(headers={})
    fp = signing._submit_fingerprint(signed)
    signing._record_submit_locked("victim", "victim:0:1", fp)
    assert signing._duplicate_submit("victim", "victim:0:1", fp)
    # A prober guessing the predictable key without the signature: no match.
    assert not signing._duplicate_submit(
        "victim", "victim:0:1", signing._submit_fingerprint(unsigned)
    )
    # Unsigned servers: fingerprint is empty either way, plain key dedupe.
    plain = HTTPServer(port=1, registry=MetricsRegistry())
    assert plain._submit_fingerprint(signed) == ""
    plain._record_submit_locked("c1", "c1:0:1", "")
    assert plain._duplicate_submit("c1", "c1:0:1", plain._submit_fingerprint(unsigned))


def test_signed_server_duplicate_fast_path_stays_authenticated():
    """An unauthenticated prober guessing the (predictable) submit key must
    NOT get a success-shaped duplicate-200 from a require_signatures server —
    the dedupe fast path matches on the accepted attempt's signature
    fingerprint, which only the legitimate client can reproduce."""
    pytest.importorskip("cryptography")
    from nanofed_tpu.security import SecurityManager

    params = _linear_params()
    registry = MetricsRegistry()
    signer = SecurityManager(key_size=2048)
    port = PORT + 6

    async def main():
        server = HTTPServer(
            port=port, registry=registry,
            client_keys={"victim": signer.get_public_key()},
            require_signatures=True,
        )
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            url = f"http://127.0.0.1:{port}"
            async with HTTPClient(url, "victim", timeout_s=10, registry=registry,
                                  security_manager=signer) as c:
                assert await c.submit_update(params, {"loss": 0.1})
                # The legitimate retry (same bytes, same signature) dedupes.
                assert await c.resend_last_update()
            # The prober replays the victim's submit key WITHOUT the signature:
            # it must fall through dedupe and die at the signature gate.
            async with HTTPClient(url, "victim", timeout_s=10,
                                  registry=registry) as prober:
                prober.current_round = 0
                prober._submit_seq = 0  # forge key "victim:0:1"
                assert not await prober.submit_update(params, {"loss": 0.1})
            assert server.num_updates() == 1
        finally:
            await server.stop()

    asyncio.run(main())
    text = registry.render_prometheus()
    assert 'result="duplicate"' in text
    assert 'result="bad_signature"' in text


# ---------------------------------------------------------------------------
# Retry storms x batched device-resident ingest (ISSUE 7 satellite)
# ---------------------------------------------------------------------------


def test_ingest_dedupe_within_batch_and_across_drain_boundary():
    """Batched ingest must preserve the idempotent-submit contract exactly:
    a lost-ACK retry storm folds into the DEVICE buffer at most once (one
    slot, not N), and duplicates straggling in AFTER a batched drain are
    answered duplicate-200 without re-entering the next batch."""
    from nanofed_tpu.ingest import IngestConfig
    from nanofed_tpu.ingest.pipeline import flatten_params

    params = _linear_params()
    trained = jax.tree.map(lambda p: p + 1.0, params)
    registry = MetricsRegistry()
    schedule = ChaosSchedule(
        FaultPlan(seed=7, events=(
            FaultEvent(kind="ack_drop", round=0, client="c1", count=1),
        )),
        registry=registry,
    )
    port = PORT + 7

    async def main():
        server = HTTPServer(port=port, chaos=schedule, registry=registry,
                            ingest=IngestConfig(capacity=4, batch_size=2))
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                registry=registry,
                retry=RetryPolicy(max_attempts=4, base_backoff_s=0.01, seed=0),
            ) as c:
                await c.fetch_global_model(like=params)
                # Attempt 1 lands in the buffer, its ACK is severed; the
                # retry (same key) must dedupe WITHIN the batch: one slot.
                assert await c.submit_update(trained, {"num_samples": 4.0})
                assert server.num_updates() == 1
                # Batched drain consumes the slot; the aggregate is exactly
                # base + delta (one fold of the single client's update).
                new_flat, metas = await server.drain_ingest_fedavg()
                assert [m.client_id for m in metas] == ["c1"]
                np.testing.assert_allclose(
                    np.asarray(new_flat), flatten_params(trained),
                    rtol=1e-5, atol=1e-5,
                )
                assert server.num_updates() == 0
                # ACROSS the drain boundary: the storm's stragglers are still
                # deduped against the submit-key window — never re-buffered.
                for _ in range(3):
                    assert await c.resend_last_update()
                assert server.num_updates() == 0
        finally:
            await server.stop()

    asyncio.run(main())
    text = registry.render_prometheus()
    assert 'nanofed_faults_injected_total{kind="ack_drop"} 1' in text
    assert 'result="duplicate"' in text
    # Exactly one slot was ever written for the whole storm.
    assert 'nanofed_ingest_offers_total{result="accepted"} 1' in text


def test_topk8_buffer_full_429_folds_delta_exactly_once():
    """Buffer-full backpressure composes with topk8 error feedback: a client
    whose retries ALL bounce off a full ingest buffer (429s — the key is
    never recorded) folds its whole delta into the residual EXACTLY once,
    and the post-drain re-submit carries that mass once — no loss, no
    double-count."""
    from nanofed_tpu.ingest import IngestConfig
    from nanofed_tpu.ingest.pipeline import flatten_params

    params = _linear_params()
    delta = jax.tree.map(lambda p: 0.02 * jnp.ones_like(p), params)
    trained = jax.tree.map(jnp.add, params, delta)
    filler = jax.tree.map(lambda p: p + 0.5, params)
    registry = MetricsRegistry()
    port = PORT + 8

    async def main():
        server = HTTPServer(port=port, registry=registry, retry_after_s=0.01,
                            ingest=IngestConfig(capacity=1))
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            url = f"http://127.0.0.1:{port}"
            async with HTTPClient(url, "filler", timeout_s=10,
                                  registry=registry) as f:
                await f.fetch_global_model(like=params)
                assert await f.submit_update(filler, {"num_samples": 1.0})
            assert server.num_updates() == 1  # buffer now FULL
            async with HTTPClient(
                url, "c1", timeout_s=10, registry=registry,
                update_encoding="topk8-delta", topk_fraction=1.0,
                retry=RetryPolicy(max_attempts=3, base_backoff_s=0.01, seed=0),
            ) as c:
                await c.fetch_global_model(like=params)
                # Every attempt answers 429 (full): the LOGICAL submit fails,
                # and the whole delta folds into the residual exactly once.
                assert not await c.submit_update(trained, {"num_samples": 1.0})
                assert c._pending_base is not None
                for r, d in zip(jax.tree.leaves(c._residual),
                                jax.tree.leaves(delta)):
                    np.testing.assert_allclose(np.asarray(r), np.asarray(d),
                                               atol=1e-3)
                # The buffer still holds ONLY the filler (the key was never
                # recorded, nothing was half-buffered).
                assert server.num_updates() == 1
                # Drain frees capacity; the re-submit measures zero post-fold
                # training + the residual = the same mass, carried ONCE.
                await server.drain_ingest_fedavg()
                assert await c.submit_update(trained, {"num_samples": 1.0})
                assert c._pending_base is None
                new_flat, metas = await server.drain_ingest_fedavg()
                assert [m.client_id for m in metas] == ["c1"]
                np.testing.assert_allclose(
                    np.asarray(new_flat), flatten_params(trained),
                    rtol=1e-3, atol=1e-3,
                )
        finally:
            await server.stop()

    asyncio.run(main())
    text = registry.render_prometheus()
    # The full-buffer shed rode the admission-control surface: 429 + counter.
    assert 'nanofed_http_429_total{endpoint="update"}' in text
    assert 'result="ingest_full"' in text
