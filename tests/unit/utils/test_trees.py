"""Pytree arithmetic unit tests (analog of the reference's exact-value aggregator tests,
``tests/unit/server/aggregator/test_fedavg.py:21-76``)."""

import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.utils import trees


def _tree(a, b):
    return {"w": jnp.asarray(a, jnp.float32), "b": {"x": jnp.asarray(b, jnp.float32)}}


def test_global_norm_exact():
    t = _tree([3.0], [4.0])
    assert float(trees.tree_global_norm(t)) == pytest.approx(5.0)


def test_clip_by_global_norm_scales_down():
    t = _tree([3.0], [4.0])
    clipped, norm = trees.tree_clip_by_global_norm(t, 1.0)
    assert float(norm) == pytest.approx(5.0)
    assert float(trees.tree_global_norm(clipped)) == pytest.approx(1.0, rel=1e-5)


def test_clip_by_global_norm_noop_below_threshold():
    t = _tree([0.3], [0.4])
    clipped, _ = trees.tree_clip_by_global_norm(t, 10.0)
    np.testing.assert_allclose(clipped["w"], t["w"])


def test_weighted_mean_exact():
    # Two "clients" with weights 1 and 2: mean = (1*a + 2*b) / 3.
    stacked = {"w": jnp.asarray([[3.0], [6.0]])}
    out = trees.tree_weighted_mean(stacked, jnp.asarray([1.0, 2.0]))
    assert float(out["w"][0]) == pytest.approx((3.0 + 12.0) / 3.0)


def test_weighted_mean_ignores_zero_weight_clients():
    stacked = {"w": jnp.asarray([[1.0], [999.0]])}
    out = trees.tree_weighted_mean(stacked, jnp.asarray([1.0, 0.0]))
    assert float(out["w"][0]) == pytest.approx(1.0)


def test_weighted_mean_all_zero_weights_is_finite():
    stacked = {"w": jnp.asarray([[1.0], [2.0]])}
    out = trees.tree_weighted_mean(stacked, jnp.asarray([0.0, 0.0]))
    assert np.isfinite(np.asarray(out["w"])).all()


def test_ravel_roundtrip():
    t = _tree([[1.0, 2.0], [3.0, 4.0]], [5.0])
    vec, unravel = trees.tree_ravel(t)
    assert vec.shape == (5,)
    t2 = unravel(vec)
    np.testing.assert_allclose(t2["b"]["x"], t["b"]["x"])
    np.testing.assert_allclose(t2["w"], t["w"])


def test_flatten_with_names():
    t = _tree([1.0], [2.0])
    named, _ = trees.tree_flatten_with_names(t)
    names = [n for n, _ in named]
    assert names == ["b/x", "w"]


def test_where_selects_trees():
    a, b = _tree([1.0], [1.0]), _tree([2.0], [2.0])
    out = trees.tree_where(jnp.asarray(True), a, b)
    assert float(out["w"][0]) == 1.0
    out = trees.tree_where(jnp.asarray(False), a, b)
    assert float(out["w"][0]) == 2.0


def test_size_and_cast():
    t = _tree([[1.0, 2.0]], [3.0])
    assert trees.tree_size(t) == 3
    c = trees.tree_cast(t, jnp.bfloat16)
    assert all(leaf.dtype == jnp.bfloat16 for leaf in jax_leaves(c))


def jax_leaves(t):
    import jax

    return jax.tree.leaves(t)
