"""Injectable clocks (utils.clock): the real clock's contract and the virtual
clock's determinism guarantees — deadline ordering, load-independence, and the
"time only moves when everyone is parked" rule the deflaked async federation
test relies on."""

import asyncio
import time

import pytest

from nanofed_tpu.utils.clock import SYSTEM_CLOCK, Clock, VirtualClock


def test_real_clock_monotonic_and_sleeps():
    clock = Clock()
    t0 = clock.time()  # off-loop: time.monotonic fallback
    assert clock.time() >= t0

    async def main():
        start = clock.time()
        await clock.sleep(0.01)
        assert clock.time() - start >= 0.009

    asyncio.run(main())


def test_system_clock_is_a_clock():
    assert isinstance(SYSTEM_CLOCK, Clock)


def test_virtual_clock_expires_long_timeouts_without_real_waiting():
    """A 500-virtual-second wait completes in well under a real second — the
    property that makes round timeouts load-independent in tests."""
    clock = VirtualClock()

    async def main():
        await clock.sleep(500.0)
        return clock.time()

    real0 = time.perf_counter()
    virtual = asyncio.run(main())
    assert virtual >= 500.0
    assert time.perf_counter() - real0 < 5.0


def test_virtual_clock_wakes_sleepers_in_deadline_order():
    clock = VirtualClock()
    order = []

    async def sleeper(name, seconds):
        await clock.sleep(seconds)
        order.append((name, clock.time()))

    async def main():
        # Started slow-first so wake order must come from deadlines, not
        # task-creation order.
        await asyncio.gather(
            sleeper("slow", 3.0), sleeper("fast", 1.0), sleeper("mid", 2.0)
        )

    asyncio.run(main())
    assert [n for n, _ in order] == ["fast", "mid", "slow"]
    # Each woke at (or after) its own deadline.
    for (_, at), want in zip(order, (1.0, 2.0, 3.0)):
        assert at >= want


def test_virtual_clock_poll_loop_with_deadline():
    """The communication-layer idiom: a poll loop against clock.time()
    deadlines terminates by VIRTUAL timeout, never by host speed."""
    clock = VirtualClock()

    async def main():
        deadline = clock.time() + 10.0
        polls = 0
        while clock.time() < deadline:
            polls += 1
            await clock.sleep(0.5)
        return polls

    polls = asyncio.run(main())
    assert polls == 20


def test_virtual_clock_zero_sleep_is_a_yield():
    clock = VirtualClock()

    async def main():
        t = clock.time()
        await clock.sleep(0)
        assert clock.time() == t

    asyncio.run(main())


def test_virtual_clock_survives_multiple_event_loops():
    """One instance across sequential asyncio.run calls (the advancer task is
    per-loop and must be rebuilt)."""
    clock = VirtualClock()

    async def main():
        await clock.sleep(1.0)
        return clock.time()

    assert asyncio.run(main()) >= 1.0
    assert asyncio.run(main()) >= 2.0


def test_virtual_clock_cancelled_sleeper_does_not_jump_time():
    """A cancelled sleep's deadline is dead: advancing to it would spuriously
    expire every LIVE deadline computed from time() (round timeouts, retry
    budgets)."""
    clock = VirtualClock()

    async def main():
        long_wait = asyncio.create_task(clock.sleep(300.0))
        await asyncio.sleep(0)  # let it park
        long_wait.cancel()
        await asyncio.gather(long_wait, return_exceptions=True)
        await clock.sleep(1.0)
        return clock.time()

    assert asyncio.run(main()) < 300.0


def test_virtual_clock_manual_advance_and_validation():
    clock = VirtualClock(start=5.0)
    assert clock.time() == 5.0
    clock.advance(2.5)
    assert clock.time() == 7.5
    with pytest.raises(ValueError):
        clock.advance(-1.0)
    with pytest.raises(ValueError):
        VirtualClock(grace_yields=0)
