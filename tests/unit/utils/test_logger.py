"""Logger parity tests (analog: the reference's singleton/context behaviors,
``nanofed/utils/logger.py:59-88``)."""

import asyncio
import logging

from nanofed_tpu.utils import LogConfig, Logger, log_exec


def test_singleton():
    assert Logger() is Logger()


def test_context_stack_and_file_output(tmp_path):
    log_file = tmp_path / "out.log"
    log = Logger()
    log.configure(LogConfig(level=logging.DEBUG, console=False, file_path=log_file))
    with log.context("server"):
        with log.context("aggregator"):
            log.info("hello %d", 7)
    log.configure(LogConfig(console=False))  # detach file handler before reading
    text = log_file.read_text()
    assert "server.aggregator" in text
    assert "hello 7" in text


def test_log_exec_sync(tmp_path):
    log_file = tmp_path / "t.log"
    Logger().configure(LogConfig(level=logging.DEBUG, console=False, file_path=log_file))

    @log_exec
    def f(x):
        return x + 1

    assert f(1) == 2
    Logger().configure(LogConfig(console=False))
    assert "Completed" in log_file.read_text()


def test_log_exec_async(tmp_path):
    log_file = tmp_path / "t.log"
    Logger().configure(LogConfig(level=logging.DEBUG, console=False, file_path=log_file))

    @log_exec
    async def f(x):
        return x * 2

    assert asyncio.run(f(3)) == 6
    Logger().configure(LogConfig(console=False))
    assert "f in" in log_file.read_text()
