"""Profiling helpers: trace capture produces artifacts; device_time measures honestly."""

from pathlib import Path

import jax
import jax.numpy as jnp

from nanofed_tpu.utils.profiling import annotate, device_time, trace


def test_device_time_orders_and_excludes_compile():
    calls = []

    @jax.jit
    def f(x):
        return (x * 2).sum()

    x = jnp.ones((64,))
    stats = device_time(lambda: (calls.append(1), f(x))[1], reps=4)
    # warm-up + 4 timed reps
    assert len(calls) == 5
    assert 0 < stats["min_s"] <= stats["median_s"] <= stats["max_s"]


def test_trace_writes_artifacts(tmp_path):
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((32, 32))
    with trace(tmp_path):
        with annotate("span"):
            jax.block_until_ready(f(x))
    assert list(Path(tmp_path).rglob("*")), "no trace artifacts written"
