"""Unit coverage for bench.py's measurement-finalization arithmetic.

The driver records whatever JSON line bench.py prints last; these pin the
scale-handling rules (accelerator single-scale, CPU two-scale linearity audit,
degraded single-scale labeling) without a 20-minute measurement run — bench.py's
module level imports no jax, so this is pure-host arithmetic testing.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

from bench import finalize_measurements  # noqa: E402


def test_accelerator_single_full_scale():
    out = finalize_measurements(
        [(1, np.array([0.75, 0.73, 0.76]))], 200.55, {"metric": "m", "unit": "s"}
    )
    assert out["value"] == 0.75  # median
    assert out["vs_baseline"] == pytest.approx(267.4, abs=0.1)
    assert out["round_times_s"] == [0.75, 0.73, 0.76]
    assert "linearity_check" not in out
    assert "scale" not in out


def test_cpu_two_scale_extrapolates_from_larger_and_audits_linearity():
    # 1/200 rounds ~60s; 1/100 round ~121s -> per-unit nearly constant.
    out = finalize_measurements(
        [(200, np.array([60.0, 62.0])), (100, np.array([121.0]))],
        200.55, {"metric": "m", "unit": "s"},
    )
    # Headline from the LARGER workload (1/100): 121 * 100.
    assert out["value"] == 12100.0
    assert out["scale"] == 100
    lc = out["linearity_check"]
    assert lc["scales"] == [200, 100]
    # extrapolated: [median(60,62)*200=12200, 121*100=12100] -> ratio ~0.992
    assert lc["extrapolated_s"] == [12200.0, 12100.0]
    assert lc["ratio"] == pytest.approx(0.992, abs=0.001)
    # Per-scale round times are reported scaled (auditable spread).
    assert out["round_times_s"]["1/200"] == [12000.0, 12400.0]
    assert out["round_times_s"]["1/100"] == [12100.0]
    assert out["vs_baseline"] == 0.02  # round(200.55/12100, 2)


def test_single_cpu_scale_never_fakes_a_linearity_certificate():
    out = finalize_measurements(
        [(50, np.array([124.6, 125.1]))], 53.48, {"metric": "m", "unit": "s"}
    )
    assert out["value"] == pytest.approx(124.85 * 50)
    assert "linearity_check" not in out
    assert "NO cross-scale linearity check" in out["extrapolated"]


def test_nonlinear_scaling_is_visible_in_the_ratio():
    # Fixed overhead dominating at the small scale -> extrapolation from it would
    # overestimate; the ratio must expose the discrepancy, not hide it.
    out = finalize_measurements(
        [(400, np.array([30.0])), (200, np.array([33.0]))],
        53.48, {"metric": "m", "unit": "s"},
    )
    assert out["linearity_check"]["ratio"] == pytest.approx(6600.0 / 12000.0, abs=1e-3)
    # Headline still comes from the larger (less overhead-dominated) workload.
    assert out["value"] == 6600.0
