"""Unit coverage for bench.py's measurement-finalization arithmetic.

The driver records whatever JSON line bench.py prints last; these pin the
scale-handling rules (accelerator single-scale, CPU two-scale linearity audit,
degraded single-scale labeling) without a 20-minute measurement run — bench.py's
module level imports no jax, so this is pure-host arithmetic testing.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

from bench import (  # noqa: E402
    METRIC_FLAGSHIP,
    METRIC_PARITY,
    compact_summary,
    finalize_measurements,
    plan_accel_attempt,
    read_probe_cache,
    read_probe_record,
    write_probe_cache,
)


def test_accelerator_single_full_scale():
    out = finalize_measurements(
        [(1, np.array([0.75, 0.73, 0.76]))], 200.55, {"metric": "m", "unit": "s"}
    )
    assert out["value"] == 0.75  # median
    assert out["vs_baseline"] == pytest.approx(267.4, abs=0.1)
    assert out["round_times_s"] == [0.75, 0.73, 0.76]
    assert "linearity_check" not in out
    assert "scale" not in out


def test_cpu_two_scale_extrapolates_from_larger_and_audits_linearity():
    # 1/200 rounds ~60s; 1/100 round ~121s -> per-unit nearly constant.
    out = finalize_measurements(
        [(200, np.array([60.0, 62.0])), (100, np.array([121.0]))],
        200.55, {"metric": "m", "unit": "s"},
    )
    # Headline from the LARGER workload (1/100): 121 * 100.
    assert out["value"] == 12100.0
    assert out["scale"] == 100
    lc = out["linearity_check"]
    assert lc["scales"] == [200, 100]
    # extrapolated: [median(60,62)*200=12200, 121*100=12100] -> ratio ~0.992
    assert lc["extrapolated_s"] == [12200.0, 12100.0]
    assert lc["ratio"] == pytest.approx(0.992, abs=0.001)
    # Per-scale round times are reported scaled (auditable spread).
    assert out["round_times_s"]["1/200"] == [12000.0, 12400.0]
    assert out["round_times_s"]["1/100"] == [12100.0]
    assert out["vs_baseline"] == 0.02  # round(200.55/12100, 2)


def test_single_cpu_scale_never_fakes_a_linearity_certificate():
    out = finalize_measurements(
        [(50, np.array([124.6, 125.1]))], 53.48, {"metric": "m", "unit": "s"}
    )
    assert out["value"] == pytest.approx(124.85 * 50)
    assert "linearity_check" not in out
    assert "NO cross-scale linearity check" in out["extrapolated"]


def test_nonlinear_scaling_is_visible_in_the_ratio():
    # Fixed overhead dominating at the small scale -> extrapolation from it would
    # overestimate; the ratio must expose the discrepancy, not hide it.
    out = finalize_measurements(
        [(400, np.array([30.0])), (200, np.array([33.0]))],
        53.48, {"metric": "m", "unit": "s"},
    )
    assert out["linearity_check"]["ratio"] == pytest.approx(6600.0 / 12000.0, abs=1e-3)
    # Headline still comes from the larger (less overhead-dominated) workload.
    assert out["value"] == 6600.0


# --- round-5: the linearity check GATES the extrapolation (VERDICT r4 ask #3) ---


def test_failed_linearity_flags_headline_as_lower_bound():
    # Round-4's actual shape: per-unit cost grew 28.5% from 1/200 to 1/100.
    out = finalize_measurements(
        [(200, np.array([72.5, 72.3])), (100, np.array([186.4]))],
        200.55, {"metric": "m", "unit": "s"},
    )
    assert out["linearity_check"]["ratio"] > 1.10
    assert out["extrapolation_quality"] == "failed"
    v = out["linearity_check"]["verdict"]
    assert v.startswith("FAILED")
    assert "LOWER bound" in v and "super-linear" in v


def test_failed_linearity_sublinear_flags_upper_bound():
    out = finalize_measurements(
        [(400, np.array([30.0])), (200, np.array([33.0]))],
        53.48, {"metric": "m", "unit": "s"},
    )
    assert out["extrapolation_quality"] == "failed"
    assert "UPPER bound" in out["linearity_check"]["verdict"]
    assert "sub-linear" in out["linearity_check"]["verdict"]


def test_passing_linearity_is_labeled_ok():
    out = finalize_measurements(
        [(200, np.array([60.0, 62.0])), (100, np.array([121.0]))],
        200.55, {"metric": "m", "unit": "s"},
    )
    assert out["extrapolation_quality"] == "ok"
    assert out["linearity_check"]["verdict"].startswith("ok")


def test_single_scale_is_labeled_unaudited():
    out = finalize_measurements(
        [(50, np.array([124.6, 125.1]))], 53.48, {"metric": "m", "unit": "s"}
    )
    assert out["extrapolation_quality"] == "unaudited"


def test_accelerator_full_scale_needs_no_quality_label():
    out = finalize_measurements(
        [(1, np.array([0.75, 0.73, 0.76]))], 200.55, {"metric": "m", "unit": "s"}
    )
    assert "extrapolation_quality" not in out  # a measurement, not an extrapolation


# --- round-5: compact driver-facing summary line (VERDICT r4 ask #2) ---


def test_compact_summary_distills_both_metrics_and_stays_short():
    results = [
        {"metric": METRIC_PARITY, "value": 6254.25, "unit": "s",
         "vs_baseline": 0.01, "platform": "cpu", "extrapolation_quality": "ok",
         "round_times_s": {"1/50": [100.0] * 50, "1/25": [200.0] * 25},
         "accel_failure": [{"attempt": "accel-1", "stderr_tail": ["x" * 200] * 6}]},
        {"metric": METRIC_FLAGSHIP, "value": 18641.15, "unit": "s",
         "vs_baseline": 0.01, "platform": "cpu",
         "extrapolation_quality": "failed",
         "linearity_check": {"ratio": 1.285, "verdict": "FAILED: ..."},
         "accel_failure": [{"attempt": "probe", "stderr_tail": ["y" * 200] * 6}]},
    ]
    out = compact_summary(results)
    assert out["metric"] == METRIC_FLAGSHIP
    assert out["value"] == 18641.15
    assert out["vs_baseline"] == 0.01
    assert out["platform"] == "cpu"
    assert out["summary"] is True
    assert out["extrapolation_quality"] == "failed"
    assert out["parity"]["value"] == 6254.25
    assert out["parity"]["extrapolation_quality"] == "ok"
    # The whole point: short enough that the driver's tail buffer (which
    # truncated round-4's ~2.3 kB flagship line mid-JSON) can never cut it.
    import json

    assert len(json.dumps(out)) < 600


def test_compact_summary_carries_round_phase_digest():
    """The observability spans' phase summary rides the tail line as a compact
    phase -> total-seconds map (and the line stays tail-buffer safe)."""
    results = [
        {"metric": METRIC_FLAGSHIP, "value": 2.0, "unit": "s",
         "vs_baseline": 100.0, "platform": "tpu",
         "phases": {
             "prepare": {"count": 1, "total_s": 1.23456, "max_s": 1.2, "mean_s": 1.2},
             "compile": {"count": 1, "total_s": 10.5, "max_s": 10.5, "mean_s": 10.5},
             "round": {"count": 3, "total_s": 6.0, "max_s": 2.1, "mean_s": 2.0},
         }},
    ]
    out = compact_summary(results)
    assert out["phases"] == {"prepare": 1.235, "compile": 10.5, "round": 6.0}
    import json

    assert len(json.dumps(out)) < 600


def test_compact_summary_tpu_carries_mfu():
    results = [
        {"metric": METRIC_FLAGSHIP, "value": 0.9, "unit": "s",
         "vs_baseline": 222.8, "platform": "tpu", "est_mfu_pct": 5.84},
    ]
    out = compact_summary(results)
    assert out["est_mfu_pct"] == 5.84
    assert "parity" not in out  # absent metric is simply omitted


def test_compact_summary_carries_parity_error_too():
    # rc=3 from a parity-only failure must not leave a clean-looking summary.
    results = [
        {"metric": METRIC_PARITY, "value": -1.0, "unit": "s", "vs_baseline": 0.0,
         "error": "parity on all benchmark workers timed out"},
        {"metric": METRIC_FLAGSHIP, "value": 0.9, "unit": "s",
         "vs_baseline": 222.8, "platform": "tpu"},
    ]
    out = compact_summary(results)
    assert out["value"] == 0.9  # flagship headline intact
    assert "timed out" in out["parity"]["error"]


def test_compact_summary_survives_total_failure():
    # Both workers dead: error records only — the summary must still emit the
    # driver schema with value -1 rather than crash or omit fields.
    results = [
        {"metric": METRIC_FLAGSHIP, "value": -1.0, "unit": "s",
         "vs_baseline": 0.0, "error": "flagship on all benchmark workers timed out"},
    ]
    out = compact_summary(results)
    assert out["value"] == -1.0
    assert out["platform"] == "none"
    assert "error" in out

    out_empty = compact_summary([])
    assert out_empty["value"] == -1.0
    assert out_empty["metric"] == METRIC_FLAGSHIP


def test_probe_cache_roundtrip_and_ttl(tmp_path):
    """The persisted backend-probe verdict honors its TTL: a fresh 'wedged'
    verdict short-circuits the accel attempt, a stale one is ignored."""
    path = str(tmp_path / "probe.json")
    assert read_probe_cache(path=path) is None  # absent
    write_probe_cache("wedged", {"source": "pre-probe"}, path=path, now=1000.0)
    rec = read_probe_cache(path=path, ttl_s=1800.0, now=1500.0)
    assert rec["verdict"] == "wedged" and rec["source"] == "pre-probe"
    # Expired: 1800s TTL, written at t=1000, read at t=3000.
    assert read_probe_cache(path=path, ttl_s=1800.0, now=3000.0) is None
    write_probe_cache("ok", path=path, now=3000.0)
    assert read_probe_cache(path=path, ttl_s=1800.0, now=3100.0)["verdict"] == "ok"


def test_probe_cache_rejects_corrupt_records(tmp_path):
    path = tmp_path / "probe.json"
    path.write_text("{not json")
    assert read_probe_cache(path=str(path)) is None
    path.write_text('{"verdict": "maybe", "at_unix": 0}')
    assert read_probe_cache(path=str(path), now=1.0, ttl_s=10.0) is None
    path.write_text('{"verdict": "ok"}')  # missing timestamp
    assert read_probe_cache(path=str(path)) is None


def test_read_probe_record_ignores_ttl(tmp_path):
    """A stale verdict is still evidence for the attempt plan — read_probe_record
    returns it long after read_probe_cache has expired it."""
    path = str(tmp_path / "probe.json")
    write_probe_cache("wedged", path=path, now=1000.0)
    assert read_probe_cache(path=path, ttl_s=10.0, now=5000.0) is None
    rec = read_probe_record(path=path)
    assert rec is not None and rec["verdict"] == "wedged"


def test_plan_fresh_wedged_skips_accel_entirely():
    """BENCH_r05 fix: a fresh 'wedged' verdict must not spend ANY accel budget —
    no probe, no measurement; the CPU worker inherits the whole total."""
    rec = {"verdict": "wedged", "at_unix": 1000.0}
    assert plan_accel_attempt(rec, now=1500.0, ttl_s=1800.0) == "skip"


def test_plan_stale_wedged_costs_one_probe_not_the_full_budget():
    """A stale 'wedged' verdict re-opens the accelerator ONLY through a short
    probe — never straight into the full measurement budget."""
    rec = {"verdict": "wedged", "at_unix": 1000.0}
    assert plan_accel_attempt(rec, now=10_000.0, ttl_s=1800.0) == "probe"


def test_plan_fresh_ok_attempts_directly():
    rec = {"verdict": "ok", "at_unix": 1000.0}
    assert plan_accel_attempt(rec, now=1500.0, ttl_s=1800.0) == "attempt"


def test_plan_stale_ok_reprobes():
    rec = {"verdict": "ok", "at_unix": 1000.0}
    assert plan_accel_attempt(rec, now=10_000.0, ttl_s=1800.0) == "probe"


def test_plan_missing_or_corrupt_record_probes():
    assert plan_accel_attempt(None) == "probe"
    assert plan_accel_attempt({"verdict": "maybe", "at_unix": 0.0}) == "probe"
    assert plan_accel_attempt({"verdict": "ok"}) == "probe"  # no timestamp


# ---------------------------------------------------------------------------
# Un-losable record (ROADMAP item 5): provisional startup summary + CPU basis
# ---------------------------------------------------------------------------

from bench import cpu_fallback_basis, cpu_mesh_devices, provisional_summary  # noqa: E402


def _write_capture(path, results):
    import json

    path.write_text(json.dumps({"artifact": path.stem, "results": results}))


def test_provisional_summary_prefers_the_capture_summary_record(tmp_path):
    _write_capture(tmp_path / "bench_tpu_r05.json", [
        {"metric": METRIC_PARITY, "value": 0.31, "unit": "s"},
        {"metric": METRIC_FLAGSHIP, "value": 0.7378, "unit": "s",
         "vs_baseline": 271.81, "platform": "tpu", "summary": True},
    ])
    out = provisional_summary(str(tmp_path))
    assert out is not None
    assert out["metric"] == METRIC_FLAGSHIP
    assert out["value"] == 0.7378 and out["vs_baseline"] == 271.81
    assert out["provisional"] is True
    assert out["provisional_from"].endswith("bench_tpu_r05.json")
    # Driver-parseable: the schema fields the tail parser needs are all there.
    assert {"metric", "value", "unit", "vs_baseline"} <= set(out)


def test_provisional_summary_newest_parseable_capture_wins(tmp_path):
    import os
    import time as _t

    _write_capture(tmp_path / "bench_tpu_r03.json", [
        {"metric": METRIC_FLAGSHIP, "value": 1.5, "unit": "s", "summary": True},
    ])
    newer = tmp_path / "bench_tpu_r05.json"
    newer.write_text("{ corrupt")
    past = _t.time() - 60
    os.utime(tmp_path / "bench_tpu_r03.json", (past, past))
    # The newest capture is corrupt: fall back to the older parseable one
    # rather than returning nothing.
    out = provisional_summary(str(tmp_path))
    assert out["value"] == 1.5


def test_provisional_summary_without_summary_record_uses_flagship_line(tmp_path):
    _write_capture(tmp_path / "bench_tpu_r04.json", [
        {"metric": METRIC_FLAGSHIP, "value": 0.9, "unit": "s",
         "vs_baseline": 222.0, "platform": "tpu"},
    ])
    out = provisional_summary(str(tmp_path))
    assert out["value"] == 0.9 and out["vs_baseline"] == 222.0


def test_provisional_summary_absent_or_useless_captures_yield_none(tmp_path):
    assert provisional_summary(str(tmp_path)) is None  # empty dir
    _write_capture(tmp_path / "bench_tpu_r01.json", [
        {"metric": METRIC_FLAGSHIP, "value": None, "unit": "s"},
    ])
    assert provisional_summary(str(tmp_path)) is None  # no numeric value
    assert provisional_summary(str(tmp_path / "missing")) is None


def test_cpu_fallback_basis_states_the_mesh_and_cores():
    basis = cpu_fallback_basis(8, 8)
    assert basis["mesh_devices"] == 8 and basis["physical_cores"] == 8
    assert "multi-device virtual CPU mesh" in basis["note"]
    # The degenerate 1-core case is labeled, not hidden.
    one = cpu_fallback_basis(1, 1)
    assert one["mesh_devices"] == 1
    assert "1 XLA host device" in one["note"]


def test_cpu_mesh_devices_env_override_and_core_cap(monkeypatch):
    monkeypatch.setenv("NANOFED_BENCH_CPU_DEVICES", "4")
    assert cpu_mesh_devices() == 4
    monkeypatch.delenv("NANOFED_BENCH_CPU_DEVICES")
    import os

    assert cpu_mesh_devices() == max(1, min(8, os.cpu_count() or 1))
