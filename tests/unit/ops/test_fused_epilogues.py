"""Fused aggregation-epilogue kernels: parity against the unfused paths they replace
(interpret mode on the CPU mesh; the same code runs as real kernels on TPU).

The q8/topk epilogue must reproduce codec-level aggregation — the weighted FedAvg
mean of ``reconstruct_q8``'d client params — to float tolerance, and the validated
epilogue must match sanitize-then-reduce exactly, including NaN/inf rows.
"""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.communication.codec import (
    Q8_QUANT_TAG,
    Q8_SCALE_TAG,
    encode_delta_q8,
    encode_delta_topk8,
    decode_delta_topk8,
    reconstruct_q8,
)
from nanofed_tpu.ops import dequant_accumulate_flat, masked_weighted_mean_flat


def _unfused_reference(q, scales, weights, base):
    """The path the server runs today, as separate stages: dequantize the int8
    stack to a materialized float array, then weighted-mean-reduce onto the base."""
    dequant = q.astype(np.float32) * scales[:, None]  # the [C, P] intermediate
    return base + (weights / weights.sum()) @ dequant


class TestDequantAccumulate:
    def test_matches_unfused_dequant_then_reduce(self):
        rng = np.random.default_rng(0)
        c, p = 9, 1333  # C not a sublane multiple, P not a lane multiple
        q = rng.integers(-127, 128, size=(c, p), dtype=np.int8)
        scales = rng.uniform(1e-4, 1e-2, size=c).astype(np.float32)
        weights = rng.uniform(0.5, 2.0, size=c).astype(np.float32)
        base = rng.normal(size=p).astype(np.float32)
        got = dequant_accumulate_flat(
            jnp.asarray(q), jnp.asarray(scales), jnp.asarray(weights),
            jnp.asarray(base),
        )
        want = _unfused_reference(q, scales, weights, base)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    def test_explicit_denominator(self):
        # FedBuff-style pre-normalized coefficients: weights carry the staleness
        # discount, denom is the aggregated count, NOT sum(weights).
        rng = np.random.default_rng(1)
        c, p = 4, 640
        q = rng.integers(-127, 128, size=(c, p), dtype=np.int8)
        scales = np.full(c, 1e-3, np.float32)
        discounts = np.asarray([1.0, 0.7071, 0.5774, 0.5], np.float32)
        base = np.zeros(p, np.float32)
        got = dequant_accumulate_flat(
            jnp.asarray(q), jnp.asarray(scales), jnp.asarray(discounts),
            jnp.asarray(base), denom=jnp.float32(float(c)),
        )
        want = (discounts / c) @ (q.astype(np.float32) * scales[:, None])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    def test_zero_weights_return_base_unchanged(self):
        c, p = 3, 512
        q = np.full((c, p), 77, np.int8)
        got = dequant_accumulate_flat(
            jnp.asarray(q), jnp.full(c, 1.0, jnp.float32),
            jnp.zeros(c, jnp.float32), jnp.full(p, 2.5, jnp.float32),
        )
        np.testing.assert_allclose(np.asarray(got), 2.5, rtol=1e-6)

    def test_rejects_non_int8(self):
        import pytest

        with pytest.raises(TypeError, match="int8"):
            dequant_accumulate_flat(
                jnp.zeros((2, 128), jnp.float32), jnp.ones(2), jnp.ones(2),
                jnp.zeros(128),
            )

    def test_codec_level_q8_aggregation_parity(self):
        """End to end against the wire format: encoding each client's delta with
        ``encode_delta_q8`` and aggregating with the FUSED kernel must equal the
        weighted mean of the ``reconstruct_q8``'d params (the unfused server
        path), to float tolerance."""
        import io

        rng = np.random.default_rng(2)
        c = 5
        base_tree = {"w": rng.normal(size=(13, 7)).astype(np.float32),
                     "b": rng.normal(size=(19,)).astype(np.float32)}
        flat = lambda t: np.concatenate([np.ravel(t["w"]), np.ravel(t["b"])])
        weights = rng.uniform(1.0, 3.0, size=c).astype(np.float32)

        q_rows, scale_rows, unfused_params = [], [], []
        p_total = flat(base_tree).size
        for i in range(c):
            delta = {k: rng.normal(size=v.shape).astype(np.float32) * 0.1
                     for k, v in base_tree.items()}
            payload = encode_delta_q8(delta, seed=100 + i)
            # Unfused path: reconstruct full params per client (dequant + add).
            unfused_params.append(flat(reconstruct_q8(base_tree, payload)))
            # Fused path inputs: the raw int8 leaves + scales off the wire, in
            # checkpoint-flat (tree_flatten_with_names) leaf order.
            with np.load(io.BytesIO(payload)) as data:
                row = np.zeros(p_total, np.int8)
                scale_by_leaf = {}
                # leaf offsets must match flat()'s concatenation order: w then b
                offset_by_leaf = {"w": 0, "b": base_tree["w"].size}
                for key in data.files:
                    if key.endswith(Q8_QUANT_TAG):
                        name = key[: -len(Q8_QUANT_TAG)]
                        off = offset_by_leaf[name]
                        vals = data[key].ravel()
                        row[off: off + vals.size] = vals
                    elif key.endswith(Q8_SCALE_TAG):
                        scale_by_leaf[key[: -len(Q8_SCALE_TAG)]] = float(data[key])
            # Per-leaf scales differ; express the row in a single scale by
            # rescaling int8 counts into a shared float basis is lossy — instead
            # aggregate per leaf below.  Here both leaves share a scale only by
            # construction of this test when uniform; so run the kernel PER LEAF.
            q_rows.append((row, scale_by_leaf))
            scale_rows.append(scale_by_leaf)

        # Aggregate per leaf with the fused kernel (per-leaf scales are exactly
        # how the wire format defines them), concatenate, compare to the weighted
        # mean of unfused reconstructions.
        out = np.zeros(p_total, np.float32)
        for name, off, size in (("w", 0, base_tree["w"].size),
                                ("b", base_tree["w"].size, base_tree["b"].size)):
            q_stack = np.stack([row[off: off + size] for row, _ in q_rows])
            scales = np.asarray([s[name] for s in scale_rows], np.float32)
            out[off: off + size] = np.asarray(dequant_accumulate_flat(
                jnp.asarray(q_stack), jnp.asarray(scales), jnp.asarray(weights),
                jnp.asarray(flat(base_tree)[off: off + size]),
            ))
        want = (weights / weights.sum()) @ np.stack(unfused_params)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

    def test_topk8_dense_rows_aggregate(self):
        """The topk8 path decodes to DENSE int8-scaled rows (zeros off the shipped
        coordinates) — the same fused kernel aggregates them."""
        rng = np.random.default_rng(3)
        base = {"w": np.zeros((40,), np.float32)}
        weights = np.asarray([1.0, 1.0], np.float32)
        deltas = [
            {"w": rng.normal(size=(40,)).astype(np.float32)} for _ in range(2)
        ]
        dense = [
            np.ravel(decode_delta_topk8(
                encode_delta_topk8(d, fraction=0.2, seed=7 + i), like=base
            )["w"])
            for i, d in enumerate(deltas)
        ]
        want = np.mean(np.stack(dense), axis=0)
        # Re-quantize the decoded dense rows into a shared int8 basis per row
        # (scale = absmax/127) to drive the kernel; tolerance covers that round.
        q_rows, scales = [], []
        for row in dense:
            s = max(float(np.max(np.abs(row))), 1e-12) / 127.0
            q_rows.append(np.round(row / s).astype(np.int8))
            scales.append(s)
        got = dequant_accumulate_flat(
            jnp.asarray(np.stack(q_rows)), jnp.asarray(scales, jnp.float32),
            jnp.asarray(weights), jnp.zeros(40, jnp.float32),
        )
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-2)


class TestMaskedWeightedMean:
    def test_matches_sanitize_then_reduce(self):
        rng = np.random.default_rng(0)
        c, p = 6, 900
        x = rng.normal(size=(c, p)).astype(np.float32)
        # Poison one INVALID row with NaN/inf and one VALID row with a single inf
        # coordinate (finite-but-poisoned values must be zeroed, not averaged).
        x[2, :] = np.nan
        x[4, 10] = np.inf
        weights = rng.uniform(0.5, 2.0, size=c).astype(np.float32)
        valid = np.asarray([1, 1, 0, 1, 1, 0], np.float32)
        got = masked_weighted_mean_flat(
            jnp.asarray(x), jnp.asarray(weights), jnp.asarray(valid)
        )
        sanitized = np.where(np.isfinite(x), x, 0.0)
        w = weights * valid
        want = (w / w.sum()) @ sanitized
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    def test_all_invalid_degenerates_to_zeros(self):
        x = jnp.ones((3, 600), jnp.float32)
        got = masked_weighted_mean_flat(
            x, jnp.ones(3, jnp.float32), jnp.zeros(3, jnp.float32)
        )
        np.testing.assert_allclose(np.asarray(got), 0.0, atol=1e-9)

    def test_boolean_mask_accepted(self):
        x = jnp.stack([jnp.full((512,), 2.0), jnp.full((512,), 6.0)])
        got = masked_weighted_mean_flat(
            x, jnp.ones(2, jnp.float32), jnp.asarray([True, False])
        )
        np.testing.assert_allclose(np.asarray(got), 2.0, rtol=1e-6)

    def test_matches_unfused_weighted_mean_on_clean_input(self):
        from nanofed_tpu.ops import weighted_mean_flat

        rng = np.random.default_rng(5)
        c, p = 5, 1024
        x = jnp.asarray(rng.normal(size=(c, p)), jnp.float32)
        w = jnp.asarray(rng.uniform(0.5, 2.0, size=c), jnp.float32)
        got = masked_weighted_mean_flat(x, w, jnp.ones(c, jnp.float32))
        want = weighted_mean_flat(x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
