"""Pallas ops: parity against the XLA/numpy reference implementations (interpret mode on
the CPU mesh; the same code runs as real kernels on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.ops import (
    add_mask,
    dequantize_u32,
    quantize_u32,
    weighted_mean_flat,
    weighted_mean_tree,
)
from nanofed_tpu.security.secure_agg import dequantize as np_dequantize
from nanofed_tpu.security.secure_agg import quantize as np_quantize
from nanofed_tpu.utils.trees import tree_weighted_mean


class TestWeightedMean:
    def test_matches_tree_weighted_mean(self):
        rng = np.random.default_rng(0)
        c, p = 7, 1000  # P deliberately not a multiple of the tile
        x = jnp.asarray(rng.normal(size=(c, p)), jnp.float32)
        w = jnp.asarray(rng.uniform(0.5, 2.0, size=(c,)), jnp.float32)
        got = weighted_mean_flat(x, w)
        want = (x * w[:, None]).sum(0) / w.sum()
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_zero_weights_drop_clients(self):
        x = jnp.stack([jnp.full((600,), 1.0), jnp.full((600,), 5.0)])
        w = jnp.asarray([1.0, 0.0])
        np.testing.assert_allclose(np.asarray(weighted_mean_flat(x, w)), 1.0, rtol=1e-6)

    def test_tree_variant(self):
        rng = np.random.default_rng(1)
        c = 3
        stacked = {
            "a": jnp.asarray(rng.normal(size=(c, 5, 3)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(c, 17)), jnp.float32),
        }
        w = jnp.asarray([1.0, 2.0, 3.0])
        got = weighted_mean_tree(stacked, w)
        want = tree_weighted_mean(stacked, w)
        for g, x in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(x), rtol=1e-5, atol=1e-6)


class TestQuantize:
    def test_roundtrip_and_numpy_parity(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(777,)).astype(np.float32) * 10
        q = quantize_u32(jnp.asarray(x), frac_bits=16)
        back = dequantize_u32(q, frac_bits=16)
        np.testing.assert_allclose(np.asarray(back), x, atol=2**-16)
        # Same encoding as the host path (int32 range): modular equality.
        np.testing.assert_array_equal(np.asarray(q), np_quantize(x, 16))
        np.testing.assert_allclose(np_dequantize(np.asarray(q), 16), x, atol=2**-16)

    def test_modular_sum_exact(self):
        a = quantize_u32(jnp.asarray([-1.5, 2.0]), frac_bits=16)
        b = quantize_u32(jnp.asarray([2.25, -3.0]), frac_bits=16)
        out = dequantize_u32(a + b, frac_bits=16)
        np.testing.assert_allclose(np.asarray(out), [0.75, -1.0], atol=2**-15)


class TestMask:
    def test_pairwise_cancellation(self):
        rng = np.random.default_rng(0)
        xa = rng.normal(size=(600,)).astype(np.float32)
        xb = rng.normal(size=(600,)).astype(np.float32)
        qa = quantize_u32(jnp.asarray(xa))
        qb = quantize_u32(jnp.asarray(xb))
        seed = jnp.int32(12345)
        ma = add_mask(qa, seed, jnp.int32(+1))
        mb = add_mask(qb, seed, jnp.int32(-1))
        total = dequantize_u32(ma + mb)
        np.testing.assert_allclose(np.asarray(total), xa + xb, atol=2**-14)

    def test_mask_hides_and_differs_by_seed(self):
        q = quantize_u32(jnp.asarray(np.ones(600, np.float32)))
        m1 = add_mask(q, jnp.int32(1), jnp.int32(1))
        m2 = add_mask(q, jnp.int32(2), jnp.int32(1))
        assert np.mean(np.asarray(m1) == np.asarray(q)) < 0.01
        assert np.mean(np.asarray(m1) == np.asarray(m2)) < 0.01


class TestDPReduce:
    """Fused clip+mean (ops.dp_reduce) vs the straightforward clip-then-mean."""

    def _reference(self, x, w, clip):
        norms = np.linalg.norm(x, axis=1)
        coef = np.minimum(1.0, clip / np.maximum(norms, 1e-12))
        clipped = x * coef[:, None]
        return (w[:, None] * clipped).sum(axis=0) / max(w.sum(), 1e-12)

    def test_row_sq_norms(self):
        from nanofed_tpu.ops import row_sq_norms

        rng = np.random.default_rng(0)
        x = rng.normal(size=(7, 1300)).astype(np.float32)  # P not a tile multiple
        got = np.asarray(row_sq_norms(jnp.asarray(x)))
        np.testing.assert_allclose(got, (x.astype(np.float64) ** 2).sum(1), rtol=1e-5)

    def test_fused_matches_clip_then_mean(self):
        from nanofed_tpu.ops import dp_clipped_mean_flat

        rng = np.random.default_rng(1)
        x = rng.normal(size=(9, 700)).astype(np.float32) * 3.0
        w = np.ones(9, np.float32)
        got = np.asarray(dp_clipped_mean_flat(jnp.asarray(x), jnp.asarray(w), 1.0))
        np.testing.assert_allclose(got, self._reference(x, w, 1.0), rtol=2e-5, atol=1e-6)

    def test_fused_denominator_is_participant_sum(self):
        # All rows over the clip bound: result must be mean of clip-scaled rows over
        # sum(w), NOT over sum(w * coef) — the sensitivity-C/K contract.
        from nanofed_tpu.ops import dp_clipped_mean_flat

        x = np.full((4, 600), 10.0, np.float32)  # every norm >> clip
        w = np.ones(4, np.float32)
        got = np.asarray(dp_clipped_mean_flat(jnp.asarray(x), jnp.asarray(w), 1.0))
        np.testing.assert_allclose(got, self._reference(x, w, 1.0), rtol=2e-5)
        # Sanity: each row scaled to norm 1 -> mean row has norm ~1 (not ~4).
        assert abs(np.linalg.norm(got) - 1.0) < 1e-3

    def test_dropout_weight_zero_excluded(self):
        from nanofed_tpu.ops import dp_clipped_mean_flat

        rng = np.random.default_rng(2)
        x = rng.normal(size=(5, 640)).astype(np.float32)
        w = np.array([1, 0, 1, 1, 0], np.float32)
        got = np.asarray(dp_clipped_mean_flat(jnp.asarray(x), jnp.asarray(w), 0.5))
        np.testing.assert_allclose(got, self._reference(x, w, 0.5), rtol=2e-5, atol=1e-6)

    def test_tree_wrapper_matches_round_step_math(self):
        # central_dp_reduce_stacked == the materializing round-step DP reduce
        # (clip_deltas + psum_weighted_mean with uniform weights) on one device.
        from nanofed_tpu.ops import central_dp_reduce_stacked
        from nanofed_tpu.utils.trees import tree_clip_by_global_norm

        rng = np.random.default_rng(3)
        stacked = {
            "w": jnp.asarray(rng.normal(size=(6, 20, 10)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(6, 10)).astype(np.float32) * 5),
        }
        w = jnp.ones(6)
        clip = 0.7
        got = central_dp_reduce_stacked(stacked, w, clip)
        clipped = jax.vmap(lambda d: tree_clip_by_global_norm(d, clip)[0])(stacked)
        want = jax.tree.map(lambda leaf: (leaf * w[:, None, None] if leaf.ndim == 3
                                          else leaf * w[:, None]).sum(0) / w.sum(),
                            clipped)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)
