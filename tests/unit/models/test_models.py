"""Model zoo tests: shapes, parameter counts, determinism, dropout behavior.

Analog of the reference's model usage in trainer tests; the 1,199,882-param count pins
architectural parity with ``nanofed/models/mnist.py:6-28``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.models import get_model, list_models
from nanofed_tpu.utils import tree_size


def test_registry_contents():
    models = list_models()
    for required in ("mnist_cnn", "resnet8", "resnet18", "linear", "mlp"):
        assert required in models


def test_mnist_cnn_shapes_and_param_count(rng):
    m = get_model("mnist_cnn")
    params = m.init(rng)
    # Parity with the torch CNN: conv1 320, conv2 18496, fc1 1179776, fc2 1290.
    assert tree_size(params) == 1_199_882
    x = jnp.zeros((4, 28, 28, 1))
    out = m.apply(params, x)
    assert out.shape == (4, 10)
    # log_softmax head: rows are log-probabilities.
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), np.ones(4), rtol=1e-4)


def test_mnist_cnn_deterministic_eval(rng):
    m = get_model("mnist_cnn")
    params = m.init(rng)
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    np.testing.assert_array_equal(m.apply(params, x), m.apply(params, x))


def test_mnist_cnn_dropout_train_vs_eval(rng):
    m = get_model("mnist_cnn")
    params = m.init(rng)
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    out_eval = m.apply(params, x)
    out_train = m.apply(params, x, train=True, rng=jax.random.key(2))
    assert not np.allclose(np.asarray(out_eval), np.asarray(out_train))
    # Same dropout rng => identical output (pure function).
    out_train2 = m.apply(params, x, train=True, rng=jax.random.key(2))
    np.testing.assert_array_equal(out_train, out_train2)


@pytest.mark.parametrize(
    "name,kwargs,in_shape,n_out",
    [
        ("resnet8", {}, (2, 32, 32, 3), 10),
        ("resnet18", {"num_classes": 100}, (2, 32, 32, 3), 100),
    ],
)
def test_resnets_forward(rng, name, kwargs, in_shape, n_out):
    m = get_model(name, **kwargs)
    params = m.init(rng)
    out = m.apply(params, jnp.zeros(in_shape))
    assert out.shape == (in_shape[0], n_out)
    assert np.isfinite(np.asarray(out)).all()


def test_resnet8_param_scale(rng):
    params = get_model("resnet8").init(rng)
    n = tree_size(params)
    assert 70_000 < n < 90_000  # CIFAR ResNet-8 is ~78k params


def test_init_is_seed_deterministic():
    m = get_model("mlp", in_features=8, hidden=4, num_classes=2)
    p1 = m.init(jax.random.key(42))
    p2 = m.init(jax.random.key(42))
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(a, b)
