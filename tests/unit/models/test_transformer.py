"""Unit tests for the causal transformer LM (``models.transformer``) and its
synthetic token-stream workload (``data.synthetic_token_streams``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.data import synthetic_token_streams
from nanofed_tpu.models import get_model
from nanofed_tpu.models.transformer import (
    FLAGSHIP_CONFIGS,
    apply_sequence,
    flagship,
    init_transformer,
    stack_blocks,
    transformer_param_count,
    unstack_blocks,
)

VOCAB, SEQ, WIDTH, DEPTH, HEADS = 32, 8, 16, 2, 2


@pytest.fixture(scope="module")
def model():
    return get_model(
        "transformer_lm", vocab=VOCAB, seq_len=SEQ, width=WIDTH,
        depth=DEPTH, heads=HEADS,
    )


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def test_registry_and_metadata(model):
    assert model.name == "transformer_lm"
    assert model.token_stream is True
    assert model.input_shape == (SEQ,)
    assert model.num_classes == VOCAB


def test_param_count_matches_analytic(params):
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert n == transformer_param_count(VOCAB, SEQ, WIDTH, DEPTH)


def test_flagship_configs_build_abstract():
    # eval_shape only — the large config must never materialize in tests
    for name in FLAGSHIP_CONFIGS:
        m = flagship(name)
        abs_p = jax.eval_shape(lambda m=m: m.init(jax.random.key(0)))
        vocab, seq_len, width, depth, _ = FLAGSHIP_CONFIGS[name]
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(abs_p))
        assert n == transformer_param_count(vocab, seq_len, width, depth)


def test_apply_returns_last_position_log_probs(model, params):
    x = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, (4, SEQ)), jnp.int32
    )
    logp = model.apply(params, x)
    assert logp.shape == (4, VOCAB)
    np.testing.assert_allclose(np.exp(np.asarray(logp)).sum(-1), 1.0, atol=1e-5)
    full = apply_sequence(params, x, heads=HEADS)
    np.testing.assert_allclose(np.asarray(full[:, -1]), np.asarray(logp), atol=1e-6)


def test_causality(params):
    """Perturbing token t must not change any position < t — the causal mask
    is load-bearing, not decorative."""
    rng = np.random.default_rng(1)
    x = rng.integers(0, VOCAB, (2, SEQ)).astype(np.int32)
    full = apply_sequence(params, jnp.asarray(x), heads=HEADS)
    for t in (SEQ - 1, SEQ // 2):
        x2 = x.copy()
        x2[:, t] = (x2[:, t] + 1) % VOCAB
        full2 = apply_sequence(params, jnp.asarray(x2), heads=HEADS)
        np.testing.assert_allclose(
            np.asarray(full[:, :t]), np.asarray(full2[:, :t]), atol=1e-6
        )
        # ...and positions >= t DO change (the perturbation is visible forward)
        assert not np.allclose(np.asarray(full[:, t:]), np.asarray(full2[:, t:]))


def test_width_must_divide_heads():
    with pytest.raises(ValueError, match="divisible"):
        get_model("transformer_lm", width=10, heads=4)


def test_token_streams_shapes_and_determinism():
    ds = synthetic_token_streams(64, vocab=VOCAB, seq_len=SEQ, seed=3)
    assert ds.x.shape == (64, SEQ) and ds.x.dtype == np.int32
    assert ds.y.shape == (64,) and ds.y.dtype == np.int32
    assert ds.x.min() >= 0 and ds.x.max() < VOCAB
    assert ds.y.min() >= 0 and ds.y.max() < VOCAB
    ds2 = synthetic_token_streams(64, vocab=VOCAB, seq_len=SEQ, seed=3)
    np.testing.assert_array_equal(ds.x, ds2.x)
    np.testing.assert_array_equal(ds.y, ds2.y)


def test_token_streams_split_discipline():
    """Different sample seeds draw different sequences from the SAME chain —
    train/test describe one language (the split rule of
    synthetic_classification, carried over)."""
    a = synthetic_token_streams(16384, vocab=8, seq_len=4, seed=0)
    b = synthetic_token_streams(16384, vocab=8, seq_len=4, seed=1)
    assert not np.array_equal(a.x, b.x)

    # The bigram distribution of both splits matches the shared chain: compare
    # empirical next-token marginals conditioned on the last token.
    def cond(ds):
        out = np.zeros((8, 8))
        for last, nxt in zip(ds.x[:, -1], ds.y):
            out[last, nxt] += 1
        return out / np.maximum(out.sum(1, keepdims=True), 1)

    assert np.abs(cond(a) - cond(b)).max() < 0.15


def test_token_streams_learnable_structure():
    """The chain is peaked: the optimal conditional entropy is well below
    log(vocab), so an LM that learns transitions shows a real loss drop."""
    ds = synthetic_token_streams(8192, vocab=16, seq_len=4, seed=0)
    # Empirical conditional entropy H(y | last token), in nats:
    joint = np.zeros((16, 16))
    for last, nxt in zip(ds.x[:, -1], ds.y):
        joint[last, nxt] += 1
    p_last = joint.sum(1) / joint.sum()
    cond = joint / np.maximum(joint.sum(1, keepdims=True), 1)
    with np.errstate(divide="ignore", invalid="ignore"):
        h = -np.nansum(cond * np.where(cond > 0, np.log(cond), 0.0), axis=1)
    h_cond = float((p_last * h).sum())
    assert h_cond < 0.8 * np.log(16)


def test_token_streams_validation():
    with pytest.raises(ValueError):
        synthetic_token_streams(8, vocab=1)
    with pytest.raises(ValueError):
        synthetic_token_streams(8, seq_len=0)


class TestScanLayers:
    """scan_layers=True must be bit-compatible at init (same RNG splits,
    stacked) and numerically equivalent at apply (lax.scan over one block
    body instead of L unrolled blocks)."""

    @pytest.fixture(scope="class")
    def unrolled(self):
        return init_transformer(jax.random.key(7), VOCAB, SEQ, WIDTH, 3)

    @pytest.fixture(scope="class")
    def scanned(self):
        return init_transformer(
            jax.random.key(7), VOCAB, SEQ, WIDTH, 3, scan_layers=True
        )

    def test_stacked_leaves_are_exact_stacks(self, unrolled, scanned):
        for i in range(3):
            per_layer = jax.tree.map(lambda s, i=i: s[i], scanned["blocks"])
            flat_s = jax.tree.leaves(per_layer)
            flat_u = jax.tree.leaves(unrolled[f"block_{i}"])
            for s, u in zip(flat_s, flat_u):
                np.testing.assert_array_equal(np.asarray(s), np.asarray(u))

    def test_logits_parity(self, unrolled, scanned):
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, VOCAB, (4, SEQ)), jnp.int32
        )
        lu = apply_sequence(unrolled, x, heads=HEADS)
        ls = apply_sequence(scanned, x, heads=HEADS)
        np.testing.assert_allclose(np.asarray(lu), np.asarray(ls), atol=1e-5)

    def test_model_apply_parity(self):
        mu = get_model(
            "transformer_lm", vocab=VOCAB, seq_len=SEQ, width=WIDTH,
            depth=3, heads=HEADS,
        )
        ms = get_model(
            "transformer_lm_scan", vocab=VOCAB, seq_len=SEQ, width=WIDTH,
            depth=3, heads=HEADS,
        )
        assert ms.name == "transformer_lm_scan"
        pu = mu.init(jax.random.key(0))
        ps = ms.init(jax.random.key(0))
        x = jnp.asarray(
            np.random.default_rng(2).integers(0, VOCAB, (4, SEQ)), jnp.int32
        )
        np.testing.assert_allclose(
            np.asarray(mu.apply(pu, x)), np.asarray(ms.apply(ps, x)), atol=1e-5
        )

    def test_stack_unstack_round_trip(self, unrolled, scanned):
        stacked = stack_blocks(unrolled)
        for s, t in zip(jax.tree.leaves(stacked), jax.tree.leaves(scanned)):
            np.testing.assert_array_equal(np.asarray(s), np.asarray(t))
        back = unstack_blocks(scanned)
        for s, t in zip(jax.tree.leaves(back), jax.tree.leaves(unrolled)):
            np.testing.assert_array_equal(np.asarray(s), np.asarray(t))

    def test_stack_blocks_requires_unrolled(self, scanned):
        with pytest.raises(ValueError, match="no block_"):
            stack_blocks(scanned)

    def test_param_count_invariant(self, scanned):
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(scanned))
        assert n == transformer_param_count(VOCAB, SEQ, WIDTH, 3)

    def test_grad_parity(self, unrolled, scanned):
        """Training trajectories match: grads through the scan equal grads
        through the unrolled loop (up to stacking)."""
        x = jnp.asarray(
            np.random.default_rng(3).integers(0, VOCAB, (4, SEQ)), jnp.int32
        )
        y = jnp.asarray(
            np.random.default_rng(4).integers(0, VOCAB, (4,)), jnp.int32
        )

        def loss(p):
            logp = apply_sequence(p, x, heads=HEADS)[:, -1]
            return -jnp.mean(logp[jnp.arange(4), y])

        gu = jax.grad(loss)(unrolled)
        gs = jax.grad(loss)(scanned)
        np.testing.assert_allclose(
            np.asarray(gu["tok_emb"]), np.asarray(gs["tok_emb"]), atol=1e-5
        )
        gu_stacked = stack_blocks({**{k: v for k, v in gu.items()
                                      if k.startswith("block_")}})
        for a, b in zip(
            jax.tree.leaves(gu_stacked["blocks"]),
            jax.tree.leaves(gs["blocks"]),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_flagship_scan_passthrough(self):
        m = flagship("tiny", scan_layers=True)
        assert m.name == "transformer_lm_scan"
        abs_p = jax.eval_shape(lambda: m.init(jax.random.key(0)))
        vocab, seq_len, width, depth, _ = FLAGSHIP_CONFIGS["tiny"]
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(abs_p))
        assert n == transformer_param_count(vocab, seq_len, width, depth)
        # the stacked subtree exists with leading depth dim
        assert abs_p["blocks"]["attn"]["wq"]["kernel"].shape[0] == depth


def test_grad_fn_keeps_integer_inputs_integer(model, params):
    """bf16 mixed precision must not cast token ids (they index the embedding
    table) — regression for the make_grad_fn dtype guard."""
    from nanofed_tpu.trainer.local import make_grad_fn

    grad_fn = make_grad_fn(model.apply, compute_dtype="bfloat16")
    x = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, (4, SEQ)), jnp.int32
    )
    y = jnp.asarray(np.random.default_rng(1).integers(0, VOCAB, (4,)), jnp.int32)
    m = jnp.ones((4,), jnp.float32)
    grads, stats = grad_fn(params, x, y, m, jax.random.key(0))
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))
    assert float(stats.count) == 4.0
