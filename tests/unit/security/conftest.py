"""Shared secure-aggregation test scaffolding."""

from types import SimpleNamespace

import pytest


@pytest.fixture
def tolerant_cohort():
    """Factory for the dropout-tolerant cohort bootstrap (identity keys, per-round
    ephemeral mask keys, Shamir share distribution, opened inboxes) — the one place
    this scaffold lives, so a wire-protocol change is fixed once."""

    def build(order, threshold, context, rng=None):
        from nanofed_tpu.security.secure_agg import (
            ClientKeyPair,
            make_dropout_shares,
            open_share_inbox,
        )

        identity = {c: ClientKeyPair.generate() for c in order}
        idpks = {c: identity[c].public_bytes() for c in order}
        mask_keys = {c: ClientKeyPair.generate() for c in order}
        epks = {c: mask_keys[c].public_bytes() for c in order}
        self_seeds, outbox = {}, {}
        for c in order:
            self_seeds[c], outbox[c] = make_dropout_shares(
                identity[c], mask_keys[c], order, idpks, threshold,
                my_id=c, context=context, rng=rng,
            )
        held = {
            c: open_share_inbox(
                identity[c], c, idpks,
                {sender: outbox[sender][c] for sender in order}, epks, context,
            )
            for c in order
        }
        return SimpleNamespace(
            order=order, identity=identity, idpks=idpks, mask_keys=mask_keys,
            epks=epks, self_seeds=self_seeds, held=held, outbox=outbox,
            context=context,
        )

    return build
