"""Property sweep: dropout recovery equals the survivors' quantized sum across random
cohort sizes, thresholds, drop patterns, weights, and round numbers.

The r3 suite pinned the streamed reduce against the materialized one the same way;
here the invariant is the double-masking algebra (``recover_unmasked_sum``): for ANY
drop pattern that leaves >= max(threshold, min_clients) survivors, summing the
survivors' double-masked vectors and removing (a) reconstructed self masks and
(b) reconstructed orphaned pairwise masks yields exactly the survivors' weighted
quantized sum — bit-for-bit modular arithmetic, not approximately.
"""

import pytest

pytest.importorskip(
    "cryptography", reason="secure-aggregation protocol tests need the optional crypto dependency"
)

import numpy as np

from nanofed_tpu.core.exceptions import AggregationError
from nanofed_tpu.security.secure_agg import (
    SecureAggregationConfig,
    build_unmask_reveals,
    dequantize,
    mask_update,
    quantize,
    recover_unmasked_sum,
)
from nanofed_tpu.utils.trees import tree_ravel


def _setup_cohort(tolerant_cohort, n, threshold, rng, dim):
    order = [f"c{i}" for i in range(n)]
    cohort = tolerant_cohort(order, threshold, f"s{rng.integers(1 << 16)}:0")
    params = {c: {"w": rng.normal(size=(dim,)).astype(np.float32)} for c in order}
    weights = {c: float(w) for c, w in
               zip(order, rng.uniform(0.05, 1.0, size=n))}
    return (order, cohort.mask_keys, cohort.epks, params, weights,
            cohort.self_seeds, cohort.held)


@pytest.mark.parametrize("seed", range(8))
def test_recovery_equals_survivor_sum_random_configs(seed, tolerant_cohort):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(3, 8))
    threshold = n // 2 + 1
    min_clients = 2
    cfg = SecureAggregationConfig(
        min_clients=min_clients, frac_bits=16, threshold=threshold,
        dropout_tolerant=True,
    )
    dim = int(rng.integers(3, 40))
    rnd = int(rng.integers(0, 50))
    order, mask_keys, epks, params, weights, self_seeds, held = _setup_cohort(
        tolerant_cohort, n, threshold, rng, dim
    )
    max_drops = n - max(threshold, min_clients)
    n_drop = int(rng.integers(0, max_drops + 1))
    dropped = list(rng.choice(order, size=n_drop, replace=False))
    survivors = [c for c in order if c not in dropped]

    masked = {
        c: mask_update(
            params[c], order.index(c), mask_keys[c], [epks[x] for x in order],
            rnd, cfg, weight=weights[c], self_seed=self_seeds[c],
        )
        for c in survivors
    }
    request = {"round": rnd, "dropped": sorted(dropped),
               "survivors": sorted(survivors)}
    reveals = {c: build_unmask_reveals(request, c, held[c]) for c in survivors}
    total = recover_unmasked_sum(masked, order, epks, rnd, reveals, cfg)

    # Bit-exact modular identity: the corrected sum equals the modular sum of each
    # survivor's bare quantized (weight-scaled) vector.
    expected = np.zeros_like(total)
    for c in survivors:
        flat, _ = tree_ravel(params[c])
        expected = expected + quantize(
            np.asarray(flat, np.float64) * weights[c], cfg.frac_bits
        )
    np.testing.assert_array_equal(total, expected)
    # And the float interpretation matches the weighted survivor sum.
    float_expected = np.zeros(dim)
    for c in survivors:
        float_expected += np.asarray(params[c]["w"], np.float64) * weights[c]
    np.testing.assert_allclose(
        dequantize(total, cfg.frac_bits), float_expected, atol=n * 2**-15
    )


# --- round-5: the attacks the docstrings claim to stop, actually mounted ---------


def test_epk_substitution_is_refused_before_masking(tolerant_cohort):
    """Mount the attack ``open_share_inbox``'s docstring describes: the epk map
    travels in an unsigned GET, so a malicious server swaps in its OWN ephemeral key
    for a peer (it could then compute every pairwise seed with that peer and strip
    the pairwise masks).  The sealed per-sender attestation must catch the mismatch
    and abort BEFORE this client masks anything."""
    from nanofed_tpu.security.secure_agg import ClientKeyPair, open_share_inbox

    order = ["a", "b", "c"]
    cohort = tolerant_cohort(order, 2, "sess:0")
    # The server relays the epk map with b's key replaced by the server's own.
    forged = dict(cohort.epks)
    forged["b"] = ClientKeyPair.generate().public_bytes()
    inbox_for_a = {sender: cohort.outbox[sender]["a"] for sender in order}
    with pytest.raises(AggregationError, match="epk substitution"):
        open_share_inbox(
            cohort.identity["a"], "a", cohort.idpks, inbox_for_a, forged, "sess:0"
        )


def test_replayed_prior_round_inbox_is_refused(tolerant_cohort):
    """Mount the attack ``_share_aad``'s docstring describes: the server already
    learned round 0's self seeds in that round's unmask; replaying round 0's sealed
    inbox during round 1 would let it harvest the matching MASK KEYS — both secrets
    of a victim, across two rounds.  The AAD binds each blob to its round context,
    so the replay must fail authentication (AES-GCM InvalidTag), not decrypt."""
    from cryptography.exceptions import InvalidTag

    from nanofed_tpu.security.secure_agg import open_share_inbox

    order = ["a", "b", "c"]
    round0 = tolerant_cohort(order, 2, "sess:0")
    inbox_for_a = {sender: round0.outbox[sender]["a"] for sender in order}
    # Honest round-0 open works (sanity)...
    open_share_inbox(
        round0.identity["a"], "a", round0.idpks, inbox_for_a, round0.epks, "sess:0"
    )
    # ...but the same wire blobs presented as round 1's inbox do not decrypt.
    with pytest.raises(InvalidTag):
        open_share_inbox(
            round0.identity["a"], "a", round0.idpks, inbox_for_a, round0.epks,
            "sess:1",
        )


def test_cross_cohort_session_replay_is_refused(tolerant_cohort):
    """Same replay, other axis: blobs from an earlier cohort SESSION (same round
    number) must fail too — the AAD context is session:round, not round alone."""
    from cryptography.exceptions import InvalidTag

    from nanofed_tpu.security.secure_agg import open_share_inbox

    order = ["a", "b"]
    old = tolerant_cohort(order, 2, "old-session:0")
    inbox_for_a = {sender: old.outbox[sender]["a"] for sender in order}
    with pytest.raises(InvalidTag):
        open_share_inbox(
            old.identity["a"], "a", old.idpks, inbox_for_a, old.epks,
            "new-session:0",
        )


@pytest.mark.parametrize("seed", range(4))
def test_tampered_reveal_share_always_fails_closed(seed, tolerant_cohort):
    """Flipping any revealed share value must produce a clean AggregationError
    (commitment/public-key verification), never a silently-corrupt aggregate."""
    rng = np.random.default_rng(2000 + seed)
    n = 5
    threshold = 3
    cfg = SecureAggregationConfig(
        min_clients=2, frac_bits=16, threshold=threshold, dropout_tolerant=True
    )
    order, mask_keys, epks, params, weights, self_seeds, held = _setup_cohort(
        tolerant_cohort, n, threshold, rng, 8
    )
    dropped = [order[int(rng.integers(n))]]
    survivors = [c for c in order if c not in dropped]
    masked = {
        c: mask_update(
            params[c], order.index(c), mask_keys[c], [epks[x] for x in order],
            0, cfg, weight=weights[c], self_seed=self_seeds[c],
        )
        for c in survivors
    }
    request = {"round": 0, "dropped": dropped, "survivors": sorted(survivors)}
    reveals = {c: build_unmask_reveals(request, c, held[c]) for c in survivors}
    # Tamper: corrupt one share value in the FIRST survivor's reveal — reconstruction
    # uses the first `threshold` collected shares (collection follows reveals'
    # insertion order), so this share is guaranteed to be consumed; a corrupted share
    # outside that subset is simply unused and harmless.  Both verification paths
    # (dropped client's key vs survivor's self-seed commitment) must catch it.
    victim = survivors[0]
    kind = "sk" if rng.random() < 0.5 else "b"
    target = dropped[0] if kind == "sk" else survivors[int(rng.integers(len(survivors)))]
    entry = reveals[victim][kind][target]
    entry["values"] = list(entry["values"])
    entry["values"][0] = int(entry["values"][0]) ^ 0x5A5A
    commitments = {}
    import hashlib

    for c in survivors:
        commitments[c] = hashlib.sha256(self_seeds[c]).digest()
    with pytest.raises(AggregationError):
        recover_unmasked_sum(
            masked, order, epks, 0, reveals, cfg,
            self_seed_commitments=commitments,
        )
