"""The core round engine must import on a base install (no `cryptography`): the security
package's crypto-backed modules are lazy exports, only the validation path is eager."""

import subprocess
import sys

_SCRIPT = r"""
import sys

class _Block:
    def find_module(self, name, path=None):
        if name == "cryptography" or name.startswith("cryptography."):
            return self
    def load_module(self, name):
        raise ImportError(f"blocked: {name}")

sys.meta_path.insert(0, _Block())
import nanofed_tpu.parallel.round_step  # noqa: F401  (pulls security.validation)
from nanofed_tpu.security import ValidationConfig  # noqa: F401
print("OK")
"""


def test_round_engine_imports_without_cryptography():
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        capture_output=True,
        text=True,
        timeout=240,
        cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
