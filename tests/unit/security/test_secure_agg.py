"""Secure aggregation tests — the capability surface of the reference's
``tests/unit/server/aggregator/test_secure.py:55-272`` (round-trips, tamper detection,
min-client enforcement) against the honest constructions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from cryptography.exceptions import InvalidTag

from nanofed_tpu.core.exceptions import AggregationError
from nanofed_tpu.security import (
    ClientKeyPair,
    SecureAggregationConfig,
    ThresholdSecureAggregator,
    TransportBox,
    dequantize,
    mask_update,
    quantize,
    reconstruct_vector,
    share_vector,
    unmask_sum,
)


def _client_params(seed, scale=1.0):
    k = jax.random.key(seed)
    k1, k2 = jax.random.split(k)
    return {
        "dense": {
            "w": jax.random.normal(k1, (4, 3)) * scale,
            "b": jax.random.normal(k2, (3,)) * scale,
        }
    }


def _tree_allclose(a, b, atol):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


class TestQuantization:
    def test_roundtrip(self):
        v = np.array([-3.25, 0.0, 1.5, 0.0001, -200.0])
        out = dequantize(quantize(v, 16), 16)
        np.testing.assert_allclose(out, v, atol=2**-16)

    def test_modular_sum_is_exact(self):
        # (q(a) + q(b)) mod 2^32 dequantizes to a+b even when one addend is negative.
        a, b = np.array([-1.5]), np.array([2.25])
        total = quantize(a, 16) + quantize(b, 16)
        np.testing.assert_allclose(dequantize(total, 16), a + b, atol=2**-15)


class TestPairwiseMasking:
    def test_masks_cancel_to_weighted_mean(self):
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = [_client_params(i) for i in range(3)]
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        weights = np.array([1.0, 2.0, 1.0])
        rel = weights / weights.sum()
        masked = [
            mask_update(params[i], i, keys[i], pks, round_number=0, config=cfg, weight=rel[i])
            for i in range(3)
        ]
        out = unmask_sum(masked, params[0], cfg)
        expected = jax.tree.map(
            lambda *xs: sum(w * x for w, x in zip(rel, xs)), *params
        )
        _tree_allclose(out, expected, atol=3 * 2**-15)

    def test_masked_vector_hides_plaintext(self):
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        p = _client_params(0)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        masked = mask_update(p, 0, keys[0], pks, round_number=0, config=cfg)
        plain = quantize(np.asarray(jax.flatten_util.ravel_pytree(p)[0], np.float64), 16)
        # A uniformly-masked vector should share (essentially) no entries with plaintext.
        assert np.mean(masked == plain) < 0.01

    def test_round_context_changes_masks(self):
        cfg = SecureAggregationConfig(min_clients=3)
        p = _client_params(0)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        m0 = mask_update(p, 0, keys[0], pks, round_number=0, config=cfg)
        m1 = mask_update(p, 0, keys[0], pks, round_number=1, config=cfg)
        assert np.mean(m0 == m1) < 0.01

    def test_min_clients_enforced(self):
        cfg = SecureAggregationConfig(min_clients=3)
        keys = [ClientKeyPair.generate() for _ in range(2)]
        pks = [k.public_bytes() for k in keys]
        with pytest.raises(AggregationError):
            mask_update(_client_params(0), 0, keys[0], pks, 0, cfg)
        with pytest.raises(AggregationError):
            unmask_sum([np.zeros(5, np.uint32)] * 2, _client_params(0), cfg)


class TestShamir:
    def test_share_reconstruct_exact(self):
        secret = np.array([123456, -98765, 0, 1], np.int64)
        shares = share_vector(secret, num_shares=5, threshold=3, rng=np.random.default_rng(0))
        # Any 3 of 5 reconstruct exactly — including a non-prefix subset.
        np.testing.assert_array_equal(reconstruct_vector(shares[2:], 3), secret)
        np.testing.assert_array_equal(
            reconstruct_vector([shares[0], shares[2], shares[4]], 3), secret
        )

    def test_below_threshold_fails(self):
        shares = share_vector(np.array([42], np.int64), 4, 3)
        with pytest.raises(AggregationError):
            reconstruct_vector(shares[:2], 3)

    def test_single_share_reveals_nothing(self):
        # Same secret, two sharings: an individual share is (overwhelmingly) different.
        s1 = share_vector(np.arange(100, dtype=np.int64), 3, 2, np.random.default_rng(1))
        s2 = share_vector(np.arange(100, dtype=np.int64), 3, 2, np.random.default_rng(2))
        assert np.mean(s1[0].values == s2[0].values) < 0.05

    def test_threshold_aggregator_sums_updates(self):
        cfg = SecureAggregationConfig(min_clients=2, threshold=2, frac_bits=16)
        agg = ThresholdSecureAggregator(num_parties=3, config=cfg)
        params = [_client_params(i) for i in range(3)]
        shares = [agg.share_update(p, weight=1.0 / 3) for p in params]
        out = agg.aggregate(shares, params[0])
        expected = jax.tree.map(lambda *xs: sum(xs) / 3, *params)
        _tree_allclose(out, expected, atol=3 * 2**-15)

    def test_aggregator_min_clients(self):
        cfg = SecureAggregationConfig(min_clients=3, threshold=2)
        agg = ThresholdSecureAggregator(num_parties=3, config=cfg)
        shares = [agg.share_update(_client_params(0))]
        with pytest.raises(AggregationError):
            agg.aggregate(shares, _client_params(0))


class TestTransportBox:
    def test_roundtrip(self):
        box = TransportBox()
        blob = box.encrypt(b"payload", b"round:3")
        assert box.decrypt(blob, b"round:3") == b"payload"

    def test_tamper_detected(self):
        box = TransportBox()
        blob = bytearray(box.encrypt(b"payload"))
        blob[-1] ^= 0xFF
        with pytest.raises(InvalidTag):
            box.decrypt(bytes(blob))

    def test_wrong_aad_detected(self):
        box = TransportBox()
        blob = box.encrypt(b"payload", b"round:3")
        with pytest.raises(InvalidTag):
            box.decrypt(blob, b"round:4")

    def test_shared_key(self):
        a = TransportBox()
        b = TransportBox(key=a.key)
        assert b.decrypt(a.encrypt(b"x")) == b"x"


class TestDeviceBackendMasking:
    """backend="device": ops.quantize Pallas kernels do the quantize + PRG expansion.

    Same HKDF pair seeds, different (on-core) PRNG stream — cancellation must hold
    whenever the WHOLE cohort uses the device backend."""

    def test_device_cohort_cancels_to_weighted_mean(self):
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = [_client_params(i) for i in range(3)]
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        weights = np.array([3.0, 1.0, 2.0])
        rel = weights / weights.sum()
        masked = [
            mask_update(params[i], i, keys[i], pks, round_number=1, config=cfg,
                        weight=rel[i], backend="device")
            for i in range(3)
        ]
        out = unmask_sum(masked, params[0], cfg)
        expected = jax.tree.map(
            lambda *xs: sum(w * x for w, x in zip(rel, xs)), *params
        )
        _tree_allclose(out, expected, atol=3 * 2**-15)

    def test_device_masked_vector_hides_plaintext(self):
        from nanofed_tpu.security.secure_agg import quantize

        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = _client_params(0)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        masked = mask_update(params, 0, keys[0], pks, round_number=0, config=cfg,
                             backend="device")
        from nanofed_tpu.utils.trees import tree_ravel

        flat, _ = tree_ravel(params)
        bare = quantize(np.asarray(flat, np.float64), cfg.frac_bits)
        assert not np.array_equal(masked, bare)

    def test_mixed_backends_do_not_cancel(self):
        # The documented contract: host and device streams differ, so a mixed cohort's
        # masks leave residue — pin it so nobody assumes interop.
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = [_client_params(i) for i in range(3)]
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        backends = ["host", "device", "device"]
        masked = [
            mask_update(params[i], i, keys[i], pks, round_number=0, config=cfg,
                        weight=1 / 3, backend=backends[i])
            for i in range(3)
        ]
        out = unmask_sum(masked, params[0], cfg)
        expected = jax.tree.map(lambda *xs: sum(xs) / 3, *params)
        leaves_close = all(
            np.allclose(np.asarray(a), np.asarray(b), atol=1e-2)
            for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(expected))
        )
        assert not leaves_close

    def test_unknown_backend_raises(self):
        import pytest

        cfg = SecureAggregationConfig(min_clients=3)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        with pytest.raises(ValueError, match="backend"):
            mask_update(_client_params(0), 0, keys[0], pks, 0, cfg, backend="gpu")
