"""Secure aggregation tests — the capability surface of the reference's
``tests/unit/server/aggregator/test_secure.py:55-272`` (round-trips, tamper detection,
min-client enforcement) against the honest constructions."""

import pytest

pytest.importorskip(
    "cryptography", reason="secure-aggregation protocol tests need the optional crypto dependency"
)

import jax
import jax.numpy as jnp
import numpy as np
from cryptography.exceptions import InvalidTag

from nanofed_tpu.core.exceptions import AggregationError
from nanofed_tpu.security import (
    ClientKeyPair,
    SecureAggregationConfig,
    ThresholdSecureAggregator,
    TransportBox,
    dequantize,
    mask_update,
    quantize,
    reconstruct_vector,
    share_vector,
    unmask_sum,
)


def _client_params(seed, scale=1.0):
    k = jax.random.key(seed)
    k1, k2 = jax.random.split(k)
    return {
        "dense": {
            "w": jax.random.normal(k1, (4, 3)) * scale,
            "b": jax.random.normal(k2, (3,)) * scale,
        }
    }


def _tree_allclose(a, b, atol):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


class TestQuantization:
    def test_roundtrip(self):
        v = np.array([-3.25, 0.0, 1.5, 0.0001, -200.0])
        out = dequantize(quantize(v, 16), 16)
        np.testing.assert_allclose(out, v, atol=2**-16)

    def test_modular_sum_is_exact(self):
        # (q(a) + q(b)) mod 2^32 dequantizes to a+b even when one addend is negative.
        a, b = np.array([-1.5]), np.array([2.25])
        total = quantize(a, 16) + quantize(b, 16)
        np.testing.assert_allclose(dequantize(total, 16), a + b, atol=2**-15)


class TestPairwiseMasking:
    def test_masks_cancel_to_weighted_mean(self):
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = [_client_params(i) for i in range(3)]
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        weights = np.array([1.0, 2.0, 1.0])
        rel = weights / weights.sum()
        masked = [
            mask_update(params[i], i, keys[i], pks, round_number=0, config=cfg, weight=rel[i])
            for i in range(3)
        ]
        out = unmask_sum(masked, params[0], cfg)
        expected = jax.tree.map(
            lambda *xs: sum(w * x for w, x in zip(rel, xs)), *params
        )
        _tree_allclose(out, expected, atol=3 * 2**-15)

    def test_masked_vector_hides_plaintext(self):
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        p = _client_params(0)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        masked = mask_update(p, 0, keys[0], pks, round_number=0, config=cfg)
        plain = quantize(np.asarray(jax.flatten_util.ravel_pytree(p)[0], np.float64), 16)
        # A uniformly-masked vector should share (essentially) no entries with plaintext.
        assert np.mean(masked == plain) < 0.01

    def test_round_context_changes_masks(self):
        cfg = SecureAggregationConfig(min_clients=3)
        p = _client_params(0)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        m0 = mask_update(p, 0, keys[0], pks, round_number=0, config=cfg)
        m1 = mask_update(p, 0, keys[0], pks, round_number=1, config=cfg)
        assert np.mean(m0 == m1) < 0.01

    def test_min_clients_enforced(self):
        cfg = SecureAggregationConfig(min_clients=3)
        keys = [ClientKeyPair.generate() for _ in range(2)]
        pks = [k.public_bytes() for k in keys]
        with pytest.raises(AggregationError):
            mask_update(_client_params(0), 0, keys[0], pks, 0, cfg)
        with pytest.raises(AggregationError):
            unmask_sum([np.zeros(5, np.uint32)] * 2, _client_params(0), cfg)


class TestShamir:
    def test_share_reconstruct_exact(self):
        secret = np.array([123456, -98765, 0, 1], np.int64)
        shares = share_vector(secret, num_shares=5, threshold=3, rng=np.random.default_rng(0))
        # Any 3 of 5 reconstruct exactly — including a non-prefix subset.
        np.testing.assert_array_equal(reconstruct_vector(shares[2:], 3), secret)
        np.testing.assert_array_equal(
            reconstruct_vector([shares[0], shares[2], shares[4]], 3), secret
        )

    def test_below_threshold_fails(self):
        shares = share_vector(np.array([42], np.int64), 4, 3)
        with pytest.raises(AggregationError):
            reconstruct_vector(shares[:2], 3)

    def test_single_share_reveals_nothing(self):
        # Same secret, two sharings: an individual share is (overwhelmingly) different.
        s1 = share_vector(np.arange(100, dtype=np.int64), 3, 2, np.random.default_rng(1))
        s2 = share_vector(np.arange(100, dtype=np.int64), 3, 2, np.random.default_rng(2))
        assert np.mean(s1[0].values == s2[0].values) < 0.05

    def test_threshold_aggregator_sums_updates(self):
        cfg = SecureAggregationConfig(min_clients=2, threshold=2, frac_bits=16)
        agg = ThresholdSecureAggregator(num_parties=3, config=cfg)
        params = [_client_params(i) for i in range(3)]
        shares = [agg.share_update(p, weight=1.0 / 3) for p in params]
        out = agg.aggregate(shares, params[0])
        expected = jax.tree.map(lambda *xs: sum(xs) / 3, *params)
        _tree_allclose(out, expected, atol=3 * 2**-15)

    def test_aggregator_min_clients(self):
        cfg = SecureAggregationConfig(min_clients=3, threshold=2)
        agg = ThresholdSecureAggregator(num_parties=3, config=cfg)
        shares = [agg.share_update(_client_params(0))]
        with pytest.raises(AggregationError):
            agg.aggregate(shares, _client_params(0))


class TestTransportBox:
    def test_roundtrip(self):
        box = TransportBox()
        blob = box.encrypt(b"payload", b"round:3")
        assert box.decrypt(blob, b"round:3") == b"payload"

    def test_tamper_detected(self):
        box = TransportBox()
        blob = bytearray(box.encrypt(b"payload"))
        blob[-1] ^= 0xFF
        with pytest.raises(InvalidTag):
            box.decrypt(bytes(blob))

    def test_wrong_aad_detected(self):
        box = TransportBox()
        blob = box.encrypt(b"payload", b"round:3")
        with pytest.raises(InvalidTag):
            box.decrypt(blob, b"round:4")

    def test_shared_key(self):
        a = TransportBox()
        b = TransportBox(key=a.key)
        assert b.decrypt(a.encrypt(b"x")) == b"x"


class TestDeviceBackendMasking:
    """backend="device": ops.quantize Pallas kernels do the quantize + PRG expansion.

    Same HKDF pair seeds, different (on-core) PRNG stream — cancellation must hold
    whenever the WHOLE cohort uses the device backend."""

    def test_device_cohort_cancels_to_weighted_mean(self):
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = [_client_params(i) for i in range(3)]
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        weights = np.array([3.0, 1.0, 2.0])
        rel = weights / weights.sum()
        masked = [
            mask_update(params[i], i, keys[i], pks, round_number=1, config=cfg,
                        weight=rel[i], backend="device")
            for i in range(3)
        ]
        out = unmask_sum(masked, params[0], cfg)
        expected = jax.tree.map(
            lambda *xs: sum(w * x for w, x in zip(rel, xs)), *params
        )
        _tree_allclose(out, expected, atol=3 * 2**-15)

    def test_device_masked_vector_hides_plaintext(self):
        from nanofed_tpu.security.secure_agg import quantize

        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = _client_params(0)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        masked = mask_update(params, 0, keys[0], pks, round_number=0, config=cfg,
                             backend="device")
        from nanofed_tpu.utils.trees import tree_ravel

        flat, _ = tree_ravel(params)
        bare = quantize(np.asarray(flat, np.float64), cfg.frac_bits)
        assert not np.array_equal(masked, bare)

    def test_mixed_backends_do_not_cancel(self):
        # The documented contract: host and device streams differ, so a mixed cohort's
        # masks leave residue — pin it so nobody assumes interop.
        cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
        params = [_client_params(i) for i in range(3)]
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        backends = ["host", "device", "device"]
        masked = [
            mask_update(params[i], i, keys[i], pks, round_number=0, config=cfg,
                        weight=1 / 3, backend=backends[i])
            for i in range(3)
        ]
        out = unmask_sum(masked, params[0], cfg)
        expected = jax.tree.map(lambda *xs: sum(xs) / 3, *params)
        leaves_close = all(
            np.allclose(np.asarray(a), np.asarray(b), atol=1e-2)
            for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(expected))
        )
        assert not leaves_close

    def test_unknown_backend_raises(self):
        import pytest

        cfg = SecureAggregationConfig(min_clients=3)
        keys = [ClientKeyPair.generate() for _ in range(3)]
        pks = [k.public_bytes() for k in keys]
        with pytest.raises(ValueError, match="backend"):
            mask_update(_client_params(0), 0, keys[0], pks, 0, cfg, backend="gpu")


class TestDropoutRecovery:
    """Bonawitz §4 double masking: self masks + Shamir recovery of orphaned masks."""

    CTX = "session0:0"

    def _cohort(self, tolerant_cohort, n, threshold, seed0=10):
        # Long-lived identity keys seal the share transport; FRESH per-round mask
        # keys carry the pairwise seeds (per-execution freshness is the security —
        # revealing a dropped client's mask key burns only this round).  The
        # scaffold itself lives in the shared tolerant_cohort fixture.
        order = [f"c{i}" for i in range(n)]
        cohort = tolerant_cohort(order, threshold, self.CTX)
        params = {c: _client_params(seed0 + i) for i, c in enumerate(order)}
        return (order, cohort.mask_keys, cohort.epks, params, cohort.self_seeds,
                cohort.held)

    def test_secret_bytes_share_roundtrip(self):
        import secrets as pysecrets

        from nanofed_tpu.security import reconstruct_secret_bytes, share_secret_bytes

        secret = pysecrets.token_bytes(32)
        shares = share_secret_bytes(secret, 5, 3)
        assert reconstruct_secret_bytes(shares[1:4], 3) == secret
        with pytest.raises(AggregationError):
            reconstruct_secret_bytes(shares[:2], 3)

    def test_sealed_share_transport(self):
        from nanofed_tpu.security import open_share_payload, seal_share_payload

        a, b = ClientKeyPair.generate(), ClientKeyPair.generate()
        payload = {"x": 2, "sk": [1, 2], "b": [3, 4]}
        blob = seal_share_payload(a, b.public_bytes(), payload)
        assert open_share_payload(b, a.public_bytes(), blob) == payload
        # A third party (the routing server) cannot open it.
        eve = ClientKeyPair.generate()
        with pytest.raises(InvalidTag):
            open_share_payload(eve, a.public_bytes(), blob)

    def test_dropout_round_recovers_survivor_sum(self, tolerant_cohort):
        from nanofed_tpu.security import (
            build_unmask_reveals,
            mask_update,
            recover_unmasked_sum,
        )
        from nanofed_tpu.utils.trees import tree_ravel

        cfg = SecureAggregationConfig(min_clients=3, threshold=3, dropout_tolerant=True)
        order, keys, pks, params, self_seeds, held = self._cohort(tolerant_cohort, 5, cfg.threshold)
        ordered_pks = [pks[c] for c in order]
        # c3 drops AFTER enrollment (its pairwise masks are baked into everyone's
        # vectors) — it never submits.
        dropped, survivors = ["c3"], [c for c in order if c != "c3"]
        masked = {
            c: mask_update(params[c], order.index(c), keys[c], ordered_pks, 7, cfg,
                           self_seed=self_seeds[c])
            for c in survivors
        }
        request = {"round": 7, "dropped": dropped, "survivors": survivors}
        reveals = {c: build_unmask_reveals(request, c, held[c]) for c in survivors}
        total = recover_unmasked_sum(masked, order, pks, 7, reveals, cfg)
        expected = np.zeros(total.size)
        for c in survivors:
            flat, _ = tree_ravel(params[c])
            expected = expected + np.asarray(flat, np.float64)
        np.testing.assert_allclose(
            dequantize(total, cfg.frac_bits), expected, atol=1e-3
        )

    def test_no_dropout_still_needs_self_mask_removal(self, tolerant_cohort):
        from nanofed_tpu.security import (
            build_unmask_reveals,
            mask_update,
            recover_unmasked_sum,
        )
        from nanofed_tpu.utils.trees import tree_ravel

        cfg = SecureAggregationConfig(min_clients=3, threshold=2, dropout_tolerant=True)
        order, keys, pks, params, self_seeds, held = self._cohort(tolerant_cohort, 3, cfg.threshold)
        ordered_pks = [pks[c] for c in order]
        masked = {
            c: mask_update(params[c], order.index(c), keys[c], ordered_pks, 0, cfg,
                           self_seed=self_seeds[c])
            for c in order
        }
        # Pairwise masks cancel in the full sum, but self masks remain: the raw
        # modular sum must NOT dequantize to the true sum.
        raw = np.zeros_like(masked[order[0]])
        for v in masked.values():
            raw = raw + v
        expected = np.zeros(raw.size)
        for c in order:
            flat, _ = tree_ravel(params[c])
            expected = expected + np.asarray(flat, np.float64)
        assert np.abs(dequantize(raw, cfg.frac_bits) - expected).max() > 1.0
        request = {"round": 0, "dropped": [], "survivors": order}
        reveals = {c: build_unmask_reveals(request, c, held[c]) for c in order}
        total = recover_unmasked_sum(masked, order, pks, 0, reveals, cfg)
        np.testing.assert_allclose(
            dequantize(total, cfg.frac_bits), expected, atol=1e-3
        )

    def test_reveal_refusals(self):
        from nanofed_tpu.security import build_unmask_reveals

        held = {"c0": {"x": 1, "sk": [0] * 16, "b": [0] * 16},
                "c1": {"x": 1, "sk": [0] * 16, "b": [0] * 16}}
        # Overlapping dropped/survivor sets: would reveal both secrets of one client.
        with pytest.raises(AggregationError):
            build_unmask_reveals(
                {"dropped": ["c1"], "survivors": ["c0", "c1"]}, "c0", held
            )
        # A live client listed as dropped refuses (it submitted this round).
        with pytest.raises(AggregationError):
            build_unmask_reveals({"dropped": ["c0"], "survivors": ["c1"]}, "c0", held)

    def test_below_threshold_reveals_fail_closed(self, tolerant_cohort):
        from nanofed_tpu.security import (
            build_unmask_reveals,
            mask_update,
            recover_unmasked_sum,
        )

        cfg = SecureAggregationConfig(min_clients=3, threshold=4, dropout_tolerant=True)
        order, keys, pks, params, self_seeds, held = self._cohort(tolerant_cohort, 5, cfg.threshold)
        ordered_pks = [pks[c] for c in order]
        survivors = order[:3]  # 3 < threshold=4
        masked = {
            c: mask_update(params[c], order.index(c), keys[c], ordered_pks, 1, cfg,
                           self_seed=self_seeds[c])
            for c in survivors
        }
        request = {"round": 1, "dropped": order[3:], "survivors": survivors}
        reveals = {c: build_unmask_reveals(request, c, held[c]) for c in survivors}
        with pytest.raises(AggregationError):
            recover_unmasked_sum(masked, order, pks, 1, reveals, cfg)


class TestDeviceBackendDropoutRecovery:
    """Dropout recovery must expand the SAME mask streams the clients used: when the
    cohort masked with backend="device" (on-core PRNG kernels), ``expand_mask`` and
    ``recover_unmasked_sum(backend="device")`` must reproduce those streams exactly."""

    def test_expand_mask_matches_device_masking_kernel(self):
        import jax.numpy as jnp

        from nanofed_tpu.ops import add_mask
        from nanofed_tpu.security.secure_agg import _fold_seed_words, expand_mask

        seed = bytes(range(32))
        size = 1000
        mask = expand_mask(seed, size, backend="device")
        # The kernel path: adding the mask to zeros must give the same stream.
        direct = np.asarray(add_mask(jnp.zeros((size,), jnp.uint32),
                                     jnp.asarray(_fold_seed_words(seed)),
                                     jnp.int32(1)))
        np.testing.assert_array_equal(mask, direct)
        # And host vs device streams genuinely differ (wire-incompatibility is real).
        assert not np.array_equal(mask, expand_mask(seed, size, backend="host"))

    def test_device_cohort_dropout_recovery(self, tolerant_cohort):
        from nanofed_tpu.security import (
            build_unmask_reveals,
            mask_update,
            recover_unmasked_sum,
        )
        from nanofed_tpu.utils.trees import tree_ravel

        cfg = SecureAggregationConfig(min_clients=3, threshold=3,
                                      dropout_tolerant=True)
        order = [f"c{i}" for i in range(4)]
        cohort = tolerant_cohort(order, cfg.threshold, "sess:3")
        mask_keys, epks = cohort.mask_keys, cohort.epks
        self_seeds, held = cohort.self_seeds, cohort.held
        params = {c: _client_params(20 + i) for i, c in enumerate(order)}
        survivors = [c for c in order if c != "c1"]
        masked = {
            c: mask_update(params[c], order.index(c), mask_keys[c],
                           [epks[x] for x in order], 3, cfg,
                           self_seed=self_seeds[c], backend="device")
            for c in survivors
        }
        request = {"round": 3, "dropped": ["c1"], "survivors": survivors}
        reveals = {c: build_unmask_reveals(request, c, held[c]) for c in survivors}
        total = recover_unmasked_sum(masked, order, epks, 3, reveals, cfg,
                                     backend="device")
        expected = np.zeros(total.size)
        for c in survivors:
            flat, _ = tree_ravel(params[c])
            expected = expected + np.asarray(flat, np.float64)
        np.testing.assert_allclose(dequantize(total, cfg.frac_bits), expected,
                                   atol=1e-3)
