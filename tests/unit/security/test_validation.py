"""Validation tests — behavior parity with ``tests/unit/server/test_validation.py:62-166``
(shape/range/statistics verdicts) plus the SPMD stacked-axis path."""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.core.types import ClientMetrics, ClientUpdates, ModelUpdate
from nanofed_tpu.security import (
    ValidationConfig,
    ValidationResult,
    apply_validation_mask,
    reference_shapes,
    validate_client_updates,
    validate_range,
    validate_shape,
    validate_statistics,
)


def _stacked_updates(client_vectors):
    c = len(client_vectors)
    params = {"w": jnp.stack([jnp.asarray(v, jnp.float32) for v in client_vectors])}
    return ClientUpdates(
        params=params,
        weights=jnp.ones((c,), jnp.float32),
        metrics=ClientMetrics(
            loss=jnp.zeros((c,)), accuracy=jnp.zeros((c,)), samples=jnp.ones((c,))
        ),
    )


def _host_update(vec, client_id="c0"):
    return ModelUpdate(
        client_id=client_id,
        round_number=0,
        params={"w": jnp.asarray(vec, jnp.float32)},
        metrics={},
        timestamp="2026-01-01T00:00:00",
    )


class TestStackedValidation:
    def test_all_valid(self):
        ups = _stacked_updates([[0.1, 0.2], [0.2, 0.1], [0.15, 0.15]])
        report = validate_client_updates(ups, ValidationConfig(min_clients_for_stats=5))
        assert report.num_valid() == 3
        assert bool(np.all(np.asarray(report.finite)))
        assert bool(np.all(np.asarray(report.range_ok)))

    def test_nonfinite_client_flagged(self):
        ups = _stacked_updates([[0.1, 0.2], [np.nan, 0.1], [0.15, np.inf]])
        report = validate_client_updates(ups)
        np.testing.assert_array_equal(np.asarray(report.finite), [True, False, False])
        np.testing.assert_array_equal(np.asarray(report.valid), [True, False, False])

    def test_norm_bound(self):
        ups = _stacked_updates([[0.1, 0.0], [100.0, 0.0]])
        report = validate_client_updates(ups, ValidationConfig(max_norm=10.0))
        np.testing.assert_array_equal(np.asarray(report.range_ok), [True, False])

    def test_zscore_anomaly(self):
        # Five near-identical clients + one far outlier: outlier is anomalous.
        vecs = [[1.0, 1.0]] * 5 + [[9.0, 9.0]]
        report = validate_client_updates(
            _stacked_updates(vecs),
            ValidationConfig(max_norm=100.0, min_clients_for_stats=5, z_score_threshold=2.0),
        )
        assert np.asarray(report.anomalous).tolist() == [False] * 5 + [True]
        assert report.num_valid() == 5

    def test_zscore_fires_at_min_cohort(self):
        # Self-inclusive z with ddof=1 caps at (n-1)/sqrt(n) = 1.79 for n=5, so a plain
        # z-score could NEVER flag an attacker at the default min cohort — leave-one-out
        # statistics must.
        vecs = [[1.0, 1.0], [1.01, 1.0], [0.99, 1.0], [1.0, 1.02]] + [[9.0, 9.0]]
        report = validate_client_updates(
            _stacked_updates(vecs),
            ValidationConfig(max_norm=100.0, min_clients_for_stats=5, z_score_threshold=2.0),
        )
        assert np.asarray(report.anomalous).tolist() == [False] * 4 + [True]

    def test_nan_clients_excluded_from_cohort_stats(self):
        # 4 NaN clients get norm 0 after sanitization; they must not drag the cohort mean
        # toward 0 and get the honest clients flagged.
        vecs = [[np.nan, 0.0]] * 4 + [[1.0, 1.0], [1.2, 1.0], [0.9, 1.0], [1.0, 1.3]]
        report = validate_client_updates(
            _stacked_updates(vecs),
            ValidationConfig(max_norm=100.0, min_clients_for_stats=3, z_score_threshold=2.0),
        )
        assert np.asarray(report.valid).tolist() == [False] * 4 + [True] * 4

    def test_stats_skipped_below_min_cohort(self):
        vecs = [[1.0, 1.0], [9.0, 9.0]]
        report = validate_client_updates(
            _stacked_updates(vecs), ValidationConfig(max_norm=100.0, min_clients_for_stats=5)
        )
        assert not np.any(np.asarray(report.anomalous))

    def test_mask_application_zeroes_invalid_weights(self):
        ups = _stacked_updates([[0.1, 0.2], [np.nan, 0.1], [0.2, 0.2]])
        report = validate_client_updates(ups)
        w = apply_validation_mask(jnp.asarray([2.0, 3.0, 4.0]), report)
        np.testing.assert_allclose(np.asarray(w), [2.0, 0.0, 4.0])

    def test_jit_compatible(self):
        # The whole report must be producible inside jit (fixed shapes, no host sync).
        ups = _stacked_updates([[0.1, 0.2], [0.2, 0.1], [0.3, 0.3]])

        @jax.jit
        def f(u):
            return validate_client_updates(u).valid

        assert np.asarray(f(ups)).shape == (3,)


class TestHostPathParity:
    def test_shape_valid_and_mismatch(self):
        ref = reference_shapes({"w": jnp.zeros((2,))})
        assert validate_shape(_host_update([0.1, 0.2]), ref) is ValidationResult.VALID
        assert (
            validate_shape(_host_update([0.1, 0.2, 0.3]), ref)
            is ValidationResult.INVALID_SHAPE
        )
        assert (
            validate_shape(_host_update([0.1, 0.2]), {"other": (2,)})
            is ValidationResult.INVALID_SHAPE
        )

    def test_range(self):
        cfg = ValidationConfig(max_norm=1.0)
        assert validate_range(_host_update([0.1, 0.2]), cfg) is ValidationResult.VALID
        assert validate_range(_host_update([5.0, 0.0]), cfg) is ValidationResult.INVALID_RANGE
        assert (
            validate_range(_host_update([np.nan, 0.0]), cfg) is ValidationResult.INVALID_RANGE
        )

    def test_statistics(self):
        cfg = ValidationConfig(min_clients_for_stats=3, z_score_threshold=2.0)
        cohort = [_host_update([1.0, 1.0], f"c{i}") for i in range(5)]
        # Cohort of identical norms: identical update is fine, outlier is anomalous.
        assert validate_statistics(_host_update([1.0, 1.0]), cohort, cfg) is (
            ValidationResult.VALID
        )
        assert validate_statistics(_host_update([50.0, 50.0]), cohort, cfg) is (
            ValidationResult.ANOMALOUS
        )
        # Below the min cohort size statistics are skipped entirely.
        assert validate_statistics(_host_update([50.0, 50.0]), cohort[:2], cfg) is (
            ValidationResult.VALID
        )
