"""Signing tests — parity with the reference's sign/verify round-trips
(``tests/unit/server/test_validation.py``, SecurityManager section)."""

import pytest

pytest.importorskip(
    "cryptography", reason="secure-aggregation protocol tests need the optional crypto dependency"
)

import jax.numpy as jnp

from nanofed_tpu.security import SecurityManager, canonical_bytes, verify_signature


def _params(v=1.0):
    return {"dense": {"w": jnp.full((3, 2), v), "b": jnp.zeros((2,))}}


def test_sign_verify_roundtrip():
    mgr = SecurityManager(key_size=2048)
    sig = mgr.sign_params(_params())
    assert mgr.verify_signature(_params(), sig, mgr.get_public_key())
    # Verifiers don't need a keypair of their own: module-level verify.
    assert verify_signature(_params(), sig, mgr.get_public_key())


def test_tampered_params_fail():
    mgr = SecurityManager()
    sig = mgr.sign_params(_params(1.0))
    assert not mgr.verify_signature(_params(1.001), sig, mgr.get_public_key())


def test_wrong_key_fails():
    a, b = SecurityManager(), SecurityManager()
    sig = a.sign_params(_params())
    assert not b.verify_signature(_params(), sig, b.get_public_key())
    # Garbage PEM fails closed, not with an exception.
    assert not a.verify_signature(_params(), sig, b"not a pem")


def test_canonical_bytes_distinguishes_shape_and_dtype():
    # The reference's raw-bytes concat can't tell a reshaped leaf apart; ours must.
    a = canonical_bytes({"w": jnp.zeros((2, 3))})
    b = canonical_bytes({"w": jnp.zeros((3, 2))})
    c = canonical_bytes({"w": jnp.zeros((2, 3), jnp.bfloat16)})
    assert a != b and a != c


class TestUpdateSignatureBinding:
    """A signed update is bound to (client, round, metrics, params): changing ANY
    component must invalidate the signature (replay/splice protection)."""

    def test_context_binding(self):
        from nanofed_tpu.security.signing import SecurityManager, verify_update_signature

        sm = SecurityManager(key_size=2048)
        import numpy as np

        params = {"w": np.arange(4, dtype=np.float32)}
        metrics = '{"loss": 0.5, "num_samples": 10}'
        sig = sm.sign_update(params, "c1", 3, metrics)
        pk = sm.get_public_key()

        assert verify_update_signature(params, "c1", 3, metrics, sig, pk)
        # Replay into a later round.
        assert not verify_update_signature(params, "c1", 4, metrics, sig, pk)
        # Splice onto another client id.
        assert not verify_update_signature(params, "c2", 3, metrics, sig, pk)
        # Rewritten metrics (forged aggregation weight).
        forged = '{"loss": 0.5, "num_samples": 1000000.0}'
        assert not verify_update_signature(params, "c1", 3, forged, sig, pk)
        # Tampered params.
        other = {"w": np.zeros(4, dtype=np.float32)}
        assert not verify_update_signature(other, "c1", 3, metrics, sig, pk)
