"""Device-resident ingest buffer + pipeline units (nanofed_tpu.ingest).

The invariants that make batched ingest SAFE to swap for the per-submit path:
slot bookkeeping (free-list, latest-wins replacement, full -> None), drain
math (FedAvg weighted mean, FedBuff staleness discounts, K-oldest selection,
out-of-window skips), freed-slot hygiene (stale contents can never reach a
reduce), and the flatten layout matching ``tree_ravel`` exactly."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import fedbuff_combine
from nanofed_tpu.core.types import ModelUpdate
from nanofed_tpu.ingest import (
    DeviceIngestBuffer,
    IngestConfig,
    IngestPipeline,
    weight_from_metrics,
)
from nanofed_tpu.ingest.pipeline import flatten_params
from nanofed_tpu.observability.registry import MetricsRegistry
from nanofed_tpu.utils.trees import tree_ravel


def _params():
    return {"a": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
            "b": jnp.ones((4,), jnp.float32)}


def _deltas(n, size=10, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=size).astype(np.float32) for i in range(n)]


def test_ingest_config_validation():
    with pytest.raises(ValueError, match="capacity"):
        IngestConfig(capacity=0)
    with pytest.raises(ValueError, match="batch_size"):
        IngestConfig(capacity=8, batch_size=9)
    with pytest.raises(ValueError, match="decode_workers"):
        IngestConfig(decode_workers=0)


def test_flatten_matches_tree_ravel_layout():
    params = _params()
    flat, unravel = tree_ravel(params)
    host = flatten_params(params)
    np.testing.assert_array_equal(host, np.asarray(flat))
    # The unravel of a host-flattened vector restores the exact tree.
    for got, want in zip(jax.tree.leaves(unravel(jnp.asarray(host))),
                         jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_offer_drain_fedavg_weighted_mean():
    params = _params()
    base = flatten_params(params)
    buf = DeviceIngestBuffer(params, capacity=4)
    deltas, weights = _deltas(3), [1.0, 2.0, 3.0]
    for i, (d, w) in enumerate(zip(deltas, weights)):
        assert buf.offer(d, client_id=f"c{i}", round_number=0,
                         weight=w, metrics={"num_samples": w}) is not None
    assert buf.fill == 3
    out, metas = buf.drain_fedavg(base)
    want = base + sum(w * d for w, d in zip(weights, deltas)) / sum(weights)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-6)
    assert [m.client_id for m in metas] == ["c0", "c1", "c2"]
    assert buf.fill == 0
    # Empty drain is a (None, []) no-op, not an error.
    out2, metas2 = buf.drain_fedavg(base)
    assert out2 is None and metas2 == []


def test_offer_replaces_same_client_latest_wins():
    params = _params()
    base = flatten_params(params)
    buf = DeviceIngestBuffer(params, capacity=2)
    d_old, d_new = _deltas(2)
    buf.offer(d_old, client_id="c0", round_number=0, weight=1.0)
    buf.offer(d_new, client_id="c0", round_number=0, weight=1.0)
    assert buf.fill == 1  # one live slot per client, like _updates[client_id]
    out, _ = buf.drain_fedavg(base)
    np.testing.assert_allclose(np.asarray(out), base + d_new,
                               rtol=1e-4, atol=1e-6)


def test_offer_full_returns_none_and_slots_recycle():
    params = _params()
    base = flatten_params(params)
    buf = DeviceIngestBuffer(params, capacity=2)
    (d,) = _deltas(1)
    assert buf.offer(d, client_id="a", round_number=0, weight=1.0) is not None
    assert buf.offer(d, client_id="b", round_number=0, weight=1.0) is not None
    assert buf.offer(d, client_id="c", round_number=0, weight=1.0) is None
    buf.drain_fedavg(base)
    # Freed slots admit new clients, and the freed contents cannot leak: a
    # drain of ONE new client must not include the two drained deltas.
    assert buf.offer(2 * d, client_id="c", round_number=0, weight=1.0) is not None
    out, metas = buf.drain_fedavg(base)
    assert [m.client_id for m in metas] == ["c"]
    np.testing.assert_allclose(np.asarray(out), base + 2 * d,
                               rtol=1e-4, atol=1e-6)


def test_offer_trace_rides_slot_meta_into_drain():
    """The X-NanoFed-Trace trace id offered with a submit must come back on
    the drained SlotMeta (how a round names the submits it consumed) — and a
    latest-wins replacement must replace the trace with it."""
    params = _params()
    base = flatten_params(params)
    buf = DeviceIngestBuffer(params, capacity=4)
    d0, d1, d2 = _deltas(3)
    buf.offer(d0, client_id="c0", round_number=0, weight=1.0, trace="aa" * 16)
    buf.offer(d1, client_id="c1", round_number=0, weight=1.0)  # untraced
    buf.offer(d2, client_id="c0", round_number=0, weight=1.0, trace="bb" * 16)
    _, metas = buf.drain_fedavg(base)
    assert {m.client_id: m.trace for m in metas} == {
        "c0": "bb" * 16, "c1": "",
    }


def test_pipeline_offer_forwards_trace():
    params = _params()
    pipe = IngestPipeline(params, IngestConfig(capacity=4, batch_size=4),
                          registry=MetricsRegistry())
    (d,) = _deltas(1, size=flatten_params(params).size)
    assert pipe.offer(d, client_id="c0", round_number=0,
                      metrics={"num_samples": 2}, trace="cd" * 16) is not None
    _, _, metas = pipe.drain_fedavg_partial()
    assert [m.trace for m in metas] == ["cd" * 16]


def test_clear_frees_everything():
    params = _params()
    buf = DeviceIngestBuffer(params, capacity=4)
    for i, d in enumerate(_deltas(3)):
        buf.offer(d, client_id=f"c{i}", round_number=0, weight=1.0)
    assert buf.clear() == 3
    assert buf.fill == 0 and buf.client_ids() == set()
    out, metas = buf.drain_fedavg(flatten_params(params))
    assert out is None and metas == []


def test_drain_fedbuff_matches_fedbuff_combine():
    """The batched FedBuff drain must be ``fedbuff_combine`` to float
    tolerance — staleness discounts, the unnormalized 1/K form, server_lr,
    and out-of-window skips included."""
    params = _params()
    base_flat, unravel = tree_ravel(params)
    versions = {0: params,
                1: jax.tree.map(lambda x: x + 0.5, params),
                2: jax.tree.map(lambda x: x + 1.0, params)}
    current = 2
    rounds = [0, 1, 2, 2]
    rng = np.random.default_rng(3)
    client_params = []
    buf = DeviceIngestBuffer(params, capacity=8)
    for i, r in enumerate(rounds):
        noise = rng.normal(size=int(base_flat.size)).astype(np.float32)
        base_r = flatten_params(versions[r])
        client_params.append(unravel(jnp.asarray(base_r + noise)))
        buf.offer(noise, client_id=f"c{i}", round_number=r, weight=1.0)
    # Reference: the host-path combine over equivalent ModelUpdate records.
    updates = [
        ModelUpdate(client_id=f"c{i}", round_number=r, params=client_params[i],
                    metrics={}, timestamp="")
        for i, r in enumerate(rounds)
    ]
    want, want_stats = fedbuff_combine(
        versions[current], updates, versions, current,
        staleness_exponent=0.5, server_lr=0.8,
    )
    out, live, stats = buf.drain_fedbuff(
        4, current, versions, flatten_params(versions[current]),
        staleness_exponent=0.5, server_lr=0.8,
    )
    np.testing.assert_allclose(
        np.asarray(out), flatten_params(want), rtol=1e-4, atol=1e-5
    )
    assert stats["num_aggregated"] == want_stats["num_aggregated"]
    assert stats["staleness"] == want_stats["staleness"]
    assert stats["discounts"] == want_stats["discounts"]


def test_drain_fedbuff_takes_k_oldest_and_leaves_surplus():
    params = _params()
    base = flatten_params(params)
    buf = DeviceIngestBuffer(params, capacity=8)
    deltas = _deltas(5)
    for i, d in enumerate(deltas):
        buf.offer(d, client_id=f"c{i}", round_number=0, weight=1.0)
    out, live, stats = buf.drain_fedbuff(3, 0, [0], base)
    assert [m.client_id for m in live] == ["c0", "c1", "c2"]
    assert buf.fill == 2  # surplus stays for the next aggregation
    want = base + sum(deltas[:3]) / 3
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-6)


def test_drain_fedbuff_skips_out_of_window_and_raises_when_all_stale():
    params = _params()
    base = flatten_params(params)
    buf = DeviceIngestBuffer(params, capacity=4)
    d0, d1 = _deltas(2)
    buf.offer(d0, client_id="stale", round_number=0, weight=1.0)
    buf.offer(d1, client_id="fresh", round_number=3, weight=1.0)
    out, live, stats = buf.drain_fedbuff(2, 3, [2, 3], base)
    assert stats["num_skipped_out_of_window"] == 1
    assert [m.client_id for m in live] == ["fresh"]
    np.testing.assert_allclose(np.asarray(out), base + d1, rtol=1e-4, atol=1e-6)
    # All-stale drain raises (fedbuff_combine parity) but still CONSUMES the
    # slots, so the engine makes progress on the next drain.
    buf.offer(d0, client_id="stale", round_number=0, weight=1.0)
    with pytest.raises(ValueError, match="version window"):
        buf.drain_fedbuff(1, 5, [4, 5], base)
    assert buf.fill == 0


def test_weight_from_metrics_defensive_coercion():
    assert weight_from_metrics({"num_samples": 32}) == 32.0
    assert weight_from_metrics({"samples_processed": 8}) == 8.0
    assert weight_from_metrics({"num_samples": "oops"}) == 1.0
    assert weight_from_metrics({"num_samples": -5}) == 1.0
    assert weight_from_metrics({"num_samples": float("inf")}) == 1.0
    assert weight_from_metrics({}) == 1.0
    assert weight_from_metrics(None) == 1.0


def test_pipeline_version_cache_and_metrics():
    params = _params()
    registry = MetricsRegistry()
    pipe = IngestPipeline(params, IngestConfig(capacity=4, batch_size=2),
                          registry=registry)
    try:
        pipe.note_version(0, params, window=2)
        pipe.note_version(1, jax.tree.map(lambda x: x + 1, params), window=2)
        pipe.note_version(4, jax.tree.map(lambda x: x + 4, params), window=2)
        # Pruned to the window: rounds below 4 - 2 are gone.
        assert pipe.base_flat(0) is None and pipe.base_flat(1) is None
        assert pipe.base_flat(4) is not None
        (d,) = _deltas(1)
        pipe.offer(d, client_id="c0", round_number=4,
                   metrics={"num_samples": 3})
        pipe.offer(d, client_id="c0", round_number=4, metrics={})
        pipe.offer(d, client_id="c1", round_number=4, metrics={})
        out, metas = pipe.drain_fedavg(4)
        assert len(metas) == 2
        snap = registry.snapshot()
        offers = snap["nanofed_ingest_offers_total"]["values"]
        assert offers == {"accepted": 2.0, "replaced": 1.0}
        assert snap["nanofed_ingest_buffer_fill"]["values"][""] == 0.0
        assert snap["nanofed_ingest_drains_total"]["values"]["fedavg"] == 1.0
    finally:
        pipe.close()


def test_pipeline_bounded_decode_pool_runs_off_loop():
    params = _params()
    registry = MetricsRegistry()
    pipe = IngestPipeline(params, IngestConfig(capacity=2, decode_workers=2),
                          registry=registry)

    async def main():
        import threading

        loop_thread = threading.get_ident()
        seen = []

        def job(x):
            seen.append(threading.get_ident())
            return x * 2

        results = await asyncio.gather(*(pipe.run_decode(job, i)
                                         for i in range(8)))
        assert results == [i * 2 for i in range(8)]
        assert all(t != loop_thread for t in seen)
        # Bounded: never more threads than decode_workers.
        assert len(set(seen)) <= 2

    try:
        asyncio.run(main())
        assert pipe.decode_busy_seconds() > 0
        snap = registry.snapshot()
        assert snap["nanofed_ingest_decode_seconds"]["values"][""]["count"] == 8
        assert snap["nanofed_ingest_decode_queue_depth"]["values"][""] == 0.0
    finally:
        pipe.close()
