"""RoundScheduler units: HBM bin-pack admission and start-time-fair ordering.

The ordering tests drive the scheduler's acquire/release seam directly with
SYNTHETIC durations — fairness must be a deterministic property of the
virtual-time arithmetic, not of how long a test host happens to sleep."""

import asyncio

import pytest

from nanofed_tpu.service.scheduler import (
    AdmissionError,
    RoundScheduler,
    TenantFootprint,
)
from nanofed_tpu.observability.registry import MetricsRegistry


def _sched(budget=None):
    return RoundScheduler(hbm_budget_bytes=budget, registry=MetricsRegistry())


def _fp(resident, peak):
    return TenantFootprint(resident_bytes=resident, peak_extra_bytes=peak)


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


# -- admission (space) ----------------------------------------------------


def test_binpack_sums_resident_and_takes_max_peak():
    s = _sched(budget=100)
    s.admit("a", _fp(40, 10))
    # 40 + 40 resident + max(10, 20) peak = 100 <= 100: fits exactly.
    s.admit("b", _fp(40, 20))
    assert s.admitted() == ["a", "b"]
    with pytest.raises(AdmissionError) as e:
        s.admit("c", _fp(10, 5))
    # Both sides of the inequality and the provenance are in the message.
    assert "90" in str(e.value) and "100" in str(e.value)
    assert "explicit" in str(e.value)
    assert "c" not in s.admitted()


def test_remove_frees_the_reservation():
    s = _sched(budget=100)
    s.admit("a", _fp(60, 10))
    with pytest.raises(AdmissionError):
        s.admit("b", _fp(50, 10))
    s.remove("a")
    s.admit("b", _fp(50, 10))
    assert s.admitted() == ["b"]


def test_unbounded_budget_admits_anything_with_basis_stated():
    s = RoundScheduler(hbm_budget_bytes=None, registry=MetricsRegistry())
    if s.hbm_budget_bytes is None:
        assert "unbounded" in s.hbm_budget_basis
        s.admit("a", _fp(10**15, 10**15))  # no fabricated limit
    else:
        # A runtime that DOES expose a bytes_limit still packs against it.
        assert s.hbm_budget_basis


def test_duplicate_admission_refused():
    s = RoundScheduler(hbm_budget_bytes=1 << 40,
                       registry=MetricsRegistry())
    s.admit("a", _fp(1, 1))
    with pytest.raises(AdmissionError):
        s.admit("a", _fp(1, 1))


def test_footprint_rejects_negative():
    with pytest.raises(ValueError):
        TenantFootprint(resident_bytes=-1, peak_extra_bytes=0)


# -- ordering (time) ------------------------------------------------------


async def _settle(n=3):
    for _ in range(n):
        await asyncio.sleep(0)


def test_lowest_virtual_pass_granted_first_regardless_of_fifo():
    """A heavy tenant that has accrued pass yields to a light one even when
    the heavy one enqueued first — the no-starvation property."""

    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        for name in ("blocker", "heavy", "light"):
            s.admit(name, _fp(1, 1))
        # Accrue history: heavy has burned 10 virtual seconds, light 1.
        await s._acquire("heavy")
        s._release("heavy", 10.0)
        await s._acquire("light")
        s._release("light", 1.0)
        # Blocker holds the device; heavy enqueues BEFORE light.
        await s._acquire("blocker")
        grants = []

        async def wait_for(name):
            await s._acquire(name)
            grants.append(name)

        t_heavy = asyncio.ensure_future(wait_for("heavy"))
        t_light = asyncio.ensure_future(wait_for("light"))
        await _settle()
        s._release("blocker", 0.5)
        await _settle()
        assert grants == ["light"]  # lower pass wins over FIFO order
        s._release("light", 1.0)
        await _settle()
        assert grants == ["light", "heavy"]
        s._release("heavy", 1.0)
        await asyncio.gather(t_heavy, t_light)

    _run(scenario())


def test_weight_scales_the_charge():
    """weight=4 pays a quarter of the virtual pass for the same measured
    duration — entitled to 4x the device time under contention."""

    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        s.admit("gold", _fp(1, 1), weight=4.0)
        s.admit("std", _fp(1, 1), weight=1.0)
        await s._acquire("gold")
        s._release("gold", 8.0)
        await s._acquire("std")
        s._release("std", 8.0)
        stats = s.stats()["tenants"]
        assert stats["gold"]["virtual_pass"] == pytest.approx(2.0)
        assert stats["std"]["virtual_pass"] == pytest.approx(8.0)

    _run(scenario())


def test_idle_tenant_rejoins_at_global_virtual_time():
    """Sleeping banks no credit: a tenant that idled while others worked
    re-enters at the global virtual time, not at its stale pass."""

    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        s.admit("worker", _fp(1, 1))
        s.admit("sleeper", _fp(1, 1))
        for _ in range(3):
            await s._acquire("worker")
            s._release("worker", 5.0)
        await s._acquire("sleeper")
        # Global virtual time is the last GRANT's start tag (the worker's
        # pass at its third acquire): the sleeper joins there, not at 0.
        assert s._pass["sleeper"] == pytest.approx(10.0)
        s._release("sleeper", 1.0)

    _run(scenario())


def test_lease_context_manager_measures_and_serializes():
    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        s.admit("a", _fp(1, 1))
        s.admit("b", _fp(1, 1))
        inside = []

        async def worker(name):
            async with s.lease(name):
                inside.append(name)
                assert len(inside) == 1  # mutual exclusion
                await asyncio.sleep(0.001)
                inside.remove(name)

        await asyncio.gather(*(worker(n) for n in ("a", "b", "a", "b")))
        stats = s.stats()["tenants"]
        assert stats["a"]["leases"] == 2
        assert stats["b"]["leases"] == 2
        assert stats["a"]["device_seconds"] > 0

    _run(scenario())


def test_remove_while_queued_fails_typed_and_frees_the_device():
    """remove() of a tenant with a QUEUED lease request must not deadlock
    the pool: the waiter gets a typed error and the next waiter is granted."""

    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        for name in ("holder", "doomed", "survivor"):
            s.admit(name, _fp(1, 1))
        await s._acquire("holder")
        t_doomed = asyncio.ensure_future(s._acquire("doomed"))
        t_survivor = asyncio.ensure_future(s._acquire("survivor"))
        await _settle()
        s.remove("doomed")
        s._release("holder", 1.0)
        await _settle()
        with pytest.raises(RuntimeError, match="removed while waiting"):
            t_doomed.result()
        assert t_survivor.done()  # the pool moved on
        s._release("survivor", 1.0)

    _run(scenario())


def test_cancelled_waiter_after_grant_does_not_leak_the_lease():
    """The asyncio.Lock lost-wakeup case: a waiter cancelled AFTER the grant
    landed on its future must hand the lease onward, not strand the pool."""

    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        for name in ("holder", "victim", "next"):
            s.admit(name, _fp(1, 1))
        await s._acquire("holder")
        t_victim = asyncio.ensure_future(s._acquire("victim"))
        t_next = asyncio.ensure_future(s._acquire("next"))
        await _settle()
        s._release("holder", 1.0)  # grant lands on victim's future ...
        t_victim.cancel()  # ... but victim is cancelled before it resumes
        await _settle()
        assert t_victim.cancelled()
        assert t_next.done() and not t_next.cancelled()  # lease moved on
        s._release("next", 1.0)

    _run(scenario())


def test_unadmitted_lease_refused():
    async def scenario():
        s = RoundScheduler(hbm_budget_bytes=1 << 40,
                           registry=MetricsRegistry())
        with pytest.raises(RuntimeError):
            async with s.lease("ghost"):
                pass

    _run(scenario())


def test_for_fleet_footprint_sized_by_max_rank_tier():
    import numpy as np

    from nanofed_tpu.fleet import reference_fleet

    base = {
        "dense1": {"kernel": np.zeros((64, 64), np.float32)},
        "dense2": {"kernel": np.zeros((64, 32), np.float32)},
    }
    prof = reference_fleet()
    fp = TenantFootprint.for_fleet(prof, base, ingest_capacity=32, agg_k=8)
    flat = 64 * 64 + 64 * 32
    # dense ingest dominates: base + published + capacity rows, all P-sized
    assert fp.resident_bytes >= (2 + 32) * flat * 4
    assert fp.peak_extra_bytes == 10 * flat * 4
    # the basis names the tier that set the adapter cost
    assert "silo" in fp.basis and "rank 32" in fp.basis
    # a fatter max-rank tier grows resident, never shrinks it
    fat = reference_fleet(silo_rank=64)
    fp2 = TenantFootprint.for_fleet(fat, base, ingest_capacity=32, agg_k=8)
    assert fp2.resident_bytes > fp.resident_bytes
    assert fp2.peak_extra_bytes == fp.peak_extra_bytes  # drain shape is dense
