"""Data layer tests: loaders (IDX round-trip), partitioner properties, packing masks.

Analogs: ``nanofed/data/mnist.py`` subset behavior; the padded packing is new TPU-side
capability whose mask/weight accounting the aggregation correctness depends on.
"""

import gzip
import struct

import numpy as np
import pytest

from nanofed_tpu.core.types import ClientData
from nanofed_tpu.data import (
    dirichlet_partition,
    federate,
    iid_partition,
    label_skew_partition,
    load_mnist,
    pack_clients,
    pack_eval,
    subset_iid,
    synthetic_classification,
)


# ---------------------------------------------------------------------------
# Datasets
# ---------------------------------------------------------------------------


def test_synthetic_deterministic_and_learnable_shape():
    d1 = synthetic_classification(100, 10, (28, 28, 1), seed=5)
    d2 = synthetic_classification(100, 10, (28, 28, 1), seed=5)
    np.testing.assert_array_equal(d1.x, d2.x)
    assert d1.x.shape == (100, 28, 28, 1)
    assert d1.y.min() >= 0 and d1.y.max() <= 9
    assert set(np.unique(d1.y)).issubset(set(range(10)))


def test_mnist_synthetic_fallback():
    d = load_mnist("train", data_dir=None, synthetic_size=50)
    assert d.x.shape == (50, 28, 28, 1)
    assert d.num_classes == 10


def _write_idx(path, arr):
    ndim = arr.ndim
    magic = (0x08 << 8) | ndim  # ubyte type
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">I", magic))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(np.uint8).tobytes())


def test_mnist_idx_loading(tmp_path):
    imgs = np.arange(2 * 28 * 28, dtype=np.uint8).reshape(2, 28, 28) % 255
    lbls = np.array([3, 7], dtype=np.uint8)
    _write_idx(tmp_path / "train-images-idx3-ubyte.gz", imgs)
    _write_idx(tmp_path / "train-labels-idx1-ubyte.gz", lbls)
    d = load_mnist("train", data_dir=tmp_path)
    assert d.x.shape == (2, 28, 28, 1)
    np.testing.assert_array_equal(d.y, [3, 7])
    # Normalization applied: pixel 0 -> (0 - .1307)/.3081
    assert d.x.min() == pytest.approx((0 - 0.1307) / 0.3081, abs=1e-4)


def test_mnist_no_fallback_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_mnist("train", data_dir=tmp_path, synthetic_fallback=False)


# ---------------------------------------------------------------------------
# Partitioners
# ---------------------------------------------------------------------------


def test_iid_partition_covers_everything():
    parts = iid_partition(100, 7, seed=1)
    allidx = np.concatenate(parts)
    assert sorted(allidx) == list(range(100))


def test_iid_partition_proportions():
    # The reference example's 12k/8k/4k split as fractions (run_experiment.py:126-131).
    parts = iid_partition(600, 3, proportions=[0.2, 0.4, 0.1])
    assert [len(p) for p in parts] == [120, 240, 60]
    assert len(np.unique(np.concatenate(parts))) == 420  # disjoint


def test_subset_iid_parity():
    idx = subset_iid(1000, 0.25, seed=3)
    assert len(idx) == 250
    assert len(np.unique(idx)) == 250
    with pytest.raises(ValueError):
        subset_iid(10, 0.0)


def test_label_skew_limits_classes_per_client():
    y = np.repeat(np.arange(10), 50)  # 500 samples, 10 classes
    parts = label_skew_partition(y, num_clients=10, shards_per_client=2, seed=0)
    classes_per_client = [len(np.unique(y[p])) for p in parts]
    assert max(classes_per_client) <= 3  # 2 shards ≈ ≤3 classes with boundary overlap
    assert sum(len(p) for p in parts) == 500


def test_dirichlet_partition_coverage_and_skew():
    y = np.repeat(np.arange(10), 100)
    parts = dirichlet_partition(y, num_clients=5, alpha=0.1, seed=0)
    assert sum(len(p) for p in parts) == 1000
    # Strong skew: some client concentrates a class heavily.
    props = []
    for p in parts:
        counts = np.bincount(y[p], minlength=10)
        props.append(counts.max() / max(1, counts.sum()))
    assert max(props) > 0.4


# ---------------------------------------------------------------------------
# Packing
# ---------------------------------------------------------------------------


def test_pack_clients_masks_and_counts():
    d = synthetic_classification(30, 3, (4,), seed=0)
    parts = [np.arange(10), np.arange(10, 25), np.arange(25, 30)]
    cd = pack_clients(d, parts, batch_size=4)
    assert isinstance(cd, ClientData)
    # capacity = 15 rounded up to multiple of 4 = 16
    assert cd.x.shape == (3, 16, 4)
    np.testing.assert_array_equal(np.asarray(cd.num_samples), [10, 15, 5])
    # padded region is zeros with mask 0
    assert cd.mask[0, 10:].sum() == 0
    assert np.all(cd.x[0, 10:] == 0)


def test_pack_real_samples_roundtrip():
    d = synthetic_classification(12, 3, (2,), seed=1)
    parts = [np.array([0, 5, 7])]
    cd = pack_clients(d, parts, batch_size=1)
    np.testing.assert_array_equal(cd.x[0, :3], d.x[[0, 5, 7]])
    np.testing.assert_array_equal(cd.y[0, :3], d.y[[0, 5, 7]])


def test_pack_eval_pads_to_batch():
    d = synthetic_classification(10, 2, (3,), seed=2)
    ed = pack_eval(d, batch_size=4)
    assert ed.x.shape == (12, 3)
    assert float(np.asarray(ed.mask).sum()) == 10.0


def test_federate_one_call():
    d = synthetic_classification(64, 4, (3,), seed=3)
    cd = federate(d, num_clients=4, scheme="iid", batch_size=8)
    assert cd.x.shape[0] == 4
    assert float(np.asarray(cd.num_samples).sum()) == 64.0


def test_digits_dataset_real_data():
    """The bundled sklearn digits dataset: real pixels, deterministic disjoint split."""
    from nanofed_tpu.data import load_digits_dataset

    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    assert train.name == "digits" and train.num_classes == 10
    assert train.x.shape[1:] == (8, 8, 1) and test.x.shape[1:] == (8, 8, 1)
    assert len(train) + len(test) == 1797
    assert 0.0 <= float(train.x.min()) and float(train.x.max()) <= 1.0
    # Deterministic across calls.
    again = load_digits_dataset("train")
    np.testing.assert_array_equal(train.y, again.y)


def test_digits_mlp_experiment_path(tmp_path):
    """run_experiment routes (8,8,1)-input models onto the real digits dataset."""
    from nanofed_tpu.experiments import run_experiment

    out = run_experiment(model="digits_mlp", num_clients=8, num_rounds=2,
                         local_epochs=1, batch_size=16, learning_rate=0.5,
                         out_dir=tmp_path)
    assert out["rounds_completed"] == 2
    assert out["final_eval_metrics"]["accuracy"] > 0.5


class TestResizeImages:
    def test_upsample_shapes_and_labels(self):
        from nanofed_tpu.data import load_digits_dataset
        from nanofed_tpu.data.datasets import resize_images

        ds = load_digits_dataset("train")
        up = resize_images(ds, 28, 28)
        assert up.x.shape == (len(ds), 28, 28, 1)
        assert up.x.dtype == np.float32
        np.testing.assert_array_equal(up.y, ds.y)
        assert up.name == "digits@28x28"
        # Bilinear interpolation cannot exceed the source intensity range.
        assert up.x.min() >= ds.x.min() - 1e-6 and up.x.max() <= ds.x.max() + 1e-6

    def test_identity_resize_is_lossless(self):
        from nanofed_tpu.data import load_digits_dataset
        from nanofed_tpu.data.datasets import resize_images

        ds = load_digits_dataset("test")
        same = resize_images(ds, 8, 8)
        np.testing.assert_allclose(same.x, ds.x, atol=1e-6)
