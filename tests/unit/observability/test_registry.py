"""Metrics registry: instrument semantics, label handling, thread safety, and the
Prometheus text exposition format (the exact shape a scraper parses)."""

import threading

import pytest

from nanofed_tpu.observability import MetricsRegistry, get_registry


def test_counter_inc_and_labels():
    reg = MetricsRegistry()
    c = reg.counter("nanofed_rounds_total", "rounds", labels=("status",))
    c.inc(status="completed")
    c.inc(2, status="completed")
    c.inc(status="failed")
    assert c.value(status="completed") == 3
    assert c.value(status="failed") == 1
    assert c.value(status="never-seen") == 0


def test_counter_refuses_decrease_and_label_mismatch():
    reg = MetricsRegistry()
    c = reg.counter("c_total", labels=("a",))
    with pytest.raises(ValueError, match="decrease"):
        c.inc(-1, a="x")
    with pytest.raises(ValueError, match="labels"):
        c.inc(b="x")
    with pytest.raises(ValueError, match="labels"):
        c.inc()  # missing the declared label entirely


def test_gauge_set_and_inc():
    reg = MetricsRegistry()
    g = reg.gauge("g")
    g.set(5)
    g.inc(-2)
    assert g.value() == 3


def test_histogram_buckets_sum_count():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    assert h.sample_count() == 3
    assert h.sample_sum() == pytest.approx(2.55)
    lines = h.collect()
    assert 'h_seconds_bucket{le="0.1"} 1' in lines
    assert 'h_seconds_bucket{le="1"} 2' in lines  # cumulative
    assert 'h_seconds_bucket{le="+Inf"} 3' in lines
    assert "h_seconds_count 3" in lines


def test_idempotent_registration_and_kind_mismatch():
    reg = MetricsRegistry()
    a = reg.counter("x_total", labels=("k",))
    assert reg.counter("x_total", labels=("k",)) is a
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total", labels=("k",))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("x_total", labels=("other",))


def test_histogram_bucket_mismatch_refused_but_omission_adopts():
    reg = MetricsRegistry()
    a = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    # Omitting buckets adopts the registered boundaries.
    assert reg.histogram("h_seconds") is a
    # An EXPLICIT disagreement raises, like kind/label mismatches.
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("h_seconds", buckets=(0.5,))


def test_invalid_names_refused():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad-name")
    with pytest.raises(ValueError, match="invalid label name"):
        reg.counter("ok_total", labels=("bad-label",))


def test_prometheus_text_format():
    reg = MetricsRegistry()
    c = reg.counter("nanofed_rounds_total", "Rounds by outcome", labels=("status",))
    c.inc(2, status="completed")
    g = reg.gauge("nanofed_cohort_size", "Cohort")
    g.set(7)
    text = reg.render_prometheus()
    assert "# HELP nanofed_rounds_total Rounds by outcome\n" in text
    assert "# TYPE nanofed_rounds_total counter\n" in text
    assert 'nanofed_rounds_total{status="completed"} 2\n' in text
    assert "# TYPE nanofed_cohort_size gauge\n" in text
    assert "nanofed_cohort_size 7\n" in text
    assert text.endswith("\n")


def test_label_value_escaping():
    reg = MetricsRegistry()
    c = reg.counter("esc_total", labels=("v",))
    c.inc(v='a"b\\c\nd')
    line = c.collect()[0]
    assert line == 'esc_total{v="a\\"b\\\\c\\nd"} 1'


def test_integer_rendering_has_no_decimal_point():
    reg = MetricsRegistry()
    c = reg.counter("n_total")
    c.inc(3)
    assert c.collect() == ["n_total 3"]
    g = reg.gauge("ratio")
    g.set(0.25)
    assert g.collect() == ["ratio 0.25"]


def test_thread_safety_under_contention():
    reg = MetricsRegistry()
    c = reg.counter("hammer_total", labels=("t",))
    h = reg.histogram("hammer_seconds", buckets=(0.5,))

    def work(tid):
        for _ in range(1000):
            c.inc(t=tid % 2)
            h.observe(0.1)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(t=0) + c.value(t=1) == 8000
    assert h.sample_count() == 8000


def test_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("a_total", labels=("x",)).inc(x="1")
    reg.histogram("b_seconds", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["a_total"] == {"kind": "counter", "values": {"1": 1.0}}
    assert snap["b_seconds"]["kind"] == "histogram"
    assert snap["b_seconds"]["values"][""]["count"] == 1


def test_default_registry_is_process_wide():
    assert get_registry() is get_registry()
