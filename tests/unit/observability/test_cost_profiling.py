"""Compiled-program cost profiling: cost/memory extraction, roofline verdicts,
the program catalog's gauges + compile histogram, and the derived device-occupancy
gauge.  Everything runs on the CPU backend — ``compiled.cost_analysis()`` works
there, which is exactly why the profiler can be tier-1-tested at all."""

import jax
import jax.numpy as jnp
import pytest

from nanofed_tpu.observability import (
    MetricsRegistry,
    PlatformPeaks,
    ProgramCatalog,
    ProgramCostReport,
    format_cost_table,
    peaks_for_device_kind,
    profile_program,
    update_device_occupancy,
)
from nanofed_tpu.observability.profiling import (
    DEVICE_OCCUPANCY_GAUGE,
    PROGRAM_COMPILE_HISTOGRAM,
    PROGRAM_FLOPS_GAUGE,
    PROGRAM_INTENSITY_GAUGE,
    PROGRAM_PEAK_BYTES_GAUGE,
    extract_cost_analysis,
    extract_memory_analysis,
)
from nanofed_tpu.observability.spans import SPAN_HISTOGRAM, SpanTracer


def _matmul_jit():
    return jax.jit(lambda x, y: (x @ y).sum() + jnp.sin(x).sum())


def test_profile_program_extracts_compiler_costs_on_cpu():
    fn = _matmul_jit()
    x = jnp.ones((64, 64))
    report = profile_program("matmul", fn, x, x)
    # XLA's numbers, not an analytic guess: a 64x64x64 matmul alone is
    # 2*64^3 = 524288 FLOPs; sin contributes transcendentals.
    assert report.flops >= 2 * 64**3
    assert report.transcendentals >= 64 * 64
    assert report.bytes_accessed > 0
    assert report.peak_bytes > 0
    assert report.arithmetic_intensity == pytest.approx(
        report.flops / report.bytes_accessed
    )
    assert report.compile_seconds > 0
    assert report.platform == "cpu"
    # CPU has no published peak: the verdict must SAY so, never fabricate one.
    assert report.peaks is None
    assert report.verdict == "no peak basis"
    assert report.lower_bound_s is None
    assert report.mfu(1.0) is None


def test_report_roofline_verdicts_against_explicit_peaks():
    fn = _matmul_jit()
    x = jnp.ones((64, 64))
    base = profile_program("m", fn, x, x)
    # Ridge = flops_per_s / bytes_per_s.  Pick peaks on either side of the
    # program's measured intensity to force both verdicts.
    ai = base.arithmetic_intensity
    compute_bound = ProgramCostReport(
        **{**base.__dict__, "peaks": PlatformPeaks(1e12, 1e12 / (ai / 2), "test")}
    )
    assert compute_bound.verdict == "compute-bound"
    memory_bound = ProgramCostReport(
        **{**base.__dict__, "peaks": PlatformPeaks(1e12, 1e12 / (ai * 2), "test")}
    )
    assert memory_bound.verdict == "memory-bound"
    # Lower bound: the slower of the two feeds, per device.
    peaks = memory_bound.peaks
    expect = max(base.flops / peaks.flops_per_s,
                 base.bytes_accessed / peaks.hbm_bytes_per_s)
    assert memory_bound.lower_bound_s == pytest.approx(expect)
    # MFU from a measured walltime, on the compiler-FLOPs basis.
    assert memory_bound.mfu(2.0) == pytest.approx(
        base.flops / 2.0 / peaks.flops_per_s
    )


def test_report_to_dict_is_json_shaped():
    fn = _matmul_jit()
    x = jnp.ones((8, 8))
    d = profile_program("p", fn, x, x, rounds=4, attrs={"k": 1}).to_dict()
    assert d["program"] == "p"
    assert d["rounds"] == 4
    assert d["flops_per_round"] == pytest.approx(d["flops"] / 4)
    assert d["verdict"] == "no peak basis"
    assert d["attrs"] == {"k": 1}
    import json

    json.dumps(d)  # must be JSON-serializable as-is (telemetry record shape)


def test_peaks_table_matches_device_kinds():
    v5e = peaks_for_device_kind("TPU v5 lite", "tpu")
    assert v5e is not None and v5e.flops_per_s == 197e12
    v5p = peaks_for_device_kind("TPU v5p", "tpu")
    assert v5p is not None and v5p.flops_per_s == 459e12
    assert peaks_for_device_kind("TPU v4", "tpu").hbm_bytes_per_s == 1228e9
    # No fabricated peaks: CPU and unknown kinds get None.
    assert peaks_for_device_kind("cpu", "cpu") is None
    assert peaks_for_device_kind("TPU v99", "tpu") is None


def test_extractors_tolerate_version_shapes_and_absence():
    class ListStyle:  # older jaxlib: one-element list of dicts
        def cost_analysis(self):
            return [{"flops": 10.0, "bytes accessed": 4.0, "transcendentals": 1.0}]

    class DictStyle:  # newer jax: plain dict
        def cost_analysis(self):
            return {"flops": 7.0, "bytes accessed": 2.0}

    class Broken:
        def cost_analysis(self):
            raise RuntimeError("backend says no")

        def memory_analysis(self):
            return None

    assert extract_cost_analysis(ListStyle()) == {
        "flops": 10.0, "transcendentals": 1.0, "bytes_accessed": 4.0
    }
    assert extract_cost_analysis(DictStyle())["flops"] == 7.0
    assert extract_cost_analysis(DictStyle())["transcendentals"] == 0.0
    # A missing analysis degrades to zeros — it must never raise.
    assert extract_cost_analysis(Broken())["flops"] == 0.0
    assert extract_memory_analysis(Broken())["peak_bytes"] == 0


def test_memory_analysis_peak_subtracts_aliased_bytes():
    class Stats:
        argument_size_in_bytes = 100
        output_size_in_bytes = 60
        temp_size_in_bytes = 40
        alias_size_in_bytes = 50  # donated buffers counted once, not twice
        generated_code_size_in_bytes = 7

    class Compiled:
        def memory_analysis(self):
            return Stats()

    mem = extract_memory_analysis(Compiled())
    assert mem["peak_bytes"] == 100 + 60 + 40 - 50
    assert mem["generated_code_bytes"] == 7


def test_catalog_registers_lazily_and_publishes_gauges():
    reg = MetricsRegistry()
    catalog = ProgramCatalog(registry=reg)
    calls = {"n": 0}

    def factory():
        calls["n"] += 1
        x = jnp.ones((16, 16))
        return (x, x), {}

    catalog.register("prog", _matmul_jit(), args_factory=factory, rounds=2)
    assert calls["n"] == 0  # registration materializes NOTHING
    assert catalog.report("prog") is None
    report = catalog.profile("prog")
    assert calls["n"] == 1
    assert report.rounds == 2
    # Cached: a second profile is free (and the factory untouched).
    assert catalog.profile("prog") is report
    assert calls["n"] == 1
    # Gauges + compile histogram landed in the registry, labeled by program.
    assert reg.gauge(PROGRAM_FLOPS_GAUGE, labels=("program",)).value(
        program="prog"
    ) == report.flops
    assert reg.gauge(PROGRAM_PEAK_BYTES_GAUGE, labels=("program",)).value(
        program="prog"
    ) == report.peak_bytes
    assert reg.gauge(PROGRAM_INTENSITY_GAUGE, labels=("program",)).value(
        program="prog"
    ) == pytest.approx(report.arithmetic_intensity)
    hist = reg.histogram(PROGRAM_COMPILE_HISTOGRAM, labels=("program",))
    assert hist.sample_count(program="prog") == 1
    # /metrics exposition: the new gauges render in Prometheus text format.
    text = reg.render_prometheus()
    assert f'{PROGRAM_FLOPS_GAUGE}{{program="prog"}}' in text
    assert f'{PROGRAM_PEAK_BYTES_GAUGE}{{program="prog"}}' in text


def test_catalog_unknown_program_and_unlowerable_fn():
    catalog = ProgramCatalog(registry=MetricsRegistry())
    with pytest.raises(KeyError, match="no program"):
        catalog.profile("nope")
    with pytest.raises(TypeError, match="not lowerable"):
        profile_program("plain", lambda x: x, 1)


def test_jit_program_attribute_is_honored():
    """A plain wrapper exposing its inner jit via .jit_program (the fused-block
    builder's shape) profiles through to the real program."""
    inner = _matmul_jit()

    def wrapper(x, y):  # pragma: no cover - never executed by the profiler
        return inner(x, y)

    wrapper.jit_program = inner
    x = jnp.ones((16, 16))
    report = profile_program("wrapped", wrapper, x, x)
    assert report.flops >= 2 * 16**3


def test_device_occupancy_from_fused_spans():
    reg = MetricsRegistry()
    hist = reg.histogram(SPAN_HISTOGRAM, labels=("span",))
    hist.observe(1.0, span="dispatch")
    hist.observe(3.0, span="host_sync")
    ratio = update_device_occupancy(reg)
    assert ratio == pytest.approx(0.75)
    assert reg.gauge(DEVICE_OCCUPANCY_GAUGE).value() == pytest.approx(0.75)
    # publish is host time the device spends idle — it must DILUTE the ratio
    # (it lives outside dispatch/host_sync in the coordinator loop), or a
    # publish-heavy run would overstate occupancy above the lower bound.
    hist.observe(4.0, span="publish")
    assert update_device_occupancy(reg) == pytest.approx(3.0 / 8.0)


def test_device_occupancy_single_round_fallback_and_empty():
    reg = MetricsRegistry()
    assert update_device_occupancy(reg) is None  # nothing recorded yet
    hist = reg.histogram(SPAN_HISTOGRAM, labels=("span",))
    hist.observe(8.0, span="round")
    hist.observe(6.0, span="local-train")
    assert update_device_occupancy(reg) == pytest.approx(0.75)
    # publish sits outside the round span in the single-round loop too.
    hist.observe(4.0, span="publish")
    assert update_device_occupancy(reg) == pytest.approx(0.5)
    # Once fused spans exist they win over the single-round basis (publish
    # still in the denominator).
    hist.observe(1.0, span="dispatch")
    hist.observe(3.0, span="host_sync")
    assert update_device_occupancy(reg) == pytest.approx(3.0 / 8.0)


def test_device_occupancy_ratio_is_clamped():
    reg = MetricsRegistry()
    hist = reg.histogram(SPAN_HISTOGRAM, labels=("span",))
    # local-train can nominally exceed its parent round under clock skew of
    # nested perf_counter reads; the published ratio must stay a ratio.
    hist.observe(2.0, span="local-train")
    hist.observe(1.0, span="round")
    assert update_device_occupancy(reg) == 1.0


def test_occupancy_integrates_with_real_tracer_spans():
    reg = MetricsRegistry()
    tracer = SpanTracer(registry=reg)
    with tracer.span("dispatch"):
        pass
    with tracer.span("host_sync"):
        pass
    ratio = update_device_occupancy(reg)
    assert ratio is not None and 0.0 <= ratio <= 1.0


def test_format_cost_table_shapes():
    fn = _matmul_jit()
    x = jnp.ones((8, 8))
    r = profile_program("tiny_program", fn, x, x, rounds=2)
    table = format_cost_table([r])
    assert "tiny_program" in table
    assert "flops/round" in table
    assert "no peak basis" in table  # CPU: stated, not fabricated
    with_peaks = ProgramCostReport(
        **{**r.__dict__, "peaks": PlatformPeaks(197e12, 819e9, "TPU v5e test")}
    )
    table2 = format_cost_table([with_peaks])
    assert "TPU v5e test" in table2
    assert with_peaks.verdict in table2
