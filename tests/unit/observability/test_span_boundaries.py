"""S3 boundary semantics: SpanTracer nesting across ``asyncio.to_thread``
(the HTTP server's decode-offload shape) and the Prometheus label-escape
round trip in the registry's text exposition."""

import asyncio
import re
import threading

from nanofed_tpu.observability import MetricsRegistry, SpanTracer


def test_span_nesting_across_to_thread_boundary():
    """The server wraps its decode offload in a span on a POOL thread while
    the handler's own span stays open on the event-loop thread.  The stacks
    are thread-local: the pool-side span must come out a ROOT (depth 0, no
    parent), not a child of the handler span — cross-thread parentage would
    fabricate a nesting the scheduler never guaranteed."""
    tracer = SpanTracer(registry=False, annotate_device=False)

    def decode():
        with tracer.span("submit-decode", trace="ab" * 16):
            with tracer.span("unpack"):
                pass
        return threading.get_ident()

    async def handler():
        with tracer.span("handle-submit"):
            return await asyncio.to_thread(decode)

    pool_tid = asyncio.run(handler())
    records = {r.name: r for r in tracer.records}
    assert records["handle-submit"].depth == 0
    assert records["submit-decode"].depth == 0
    assert records["submit-decode"].parent_id is None
    assert records["submit-decode"].thread_id == pool_tid
    assert records["submit-decode"].attrs == {"trace": "ab" * 16}
    # WITHIN the pool thread, nesting still works normally.
    assert records["unpack"].depth == 1
    assert records["unpack"].parent_id == records["submit-decode"].span_id
    # The handler span stayed open across the await and closed last.
    assert records["handle-submit"].duration_s >= records["submit-decode"].duration_s


def test_span_stack_isolated_per_thread_after_boundary():
    """A span left open on one thread must not leak parentage into spans
    opened on another thread afterwards (the pool thread is reused)."""
    tracer = SpanTracer(registry=False, annotate_device=False)

    async def run():
        with tracer.span("outer"):
            await asyncio.to_thread(lambda: tracer.span("first").__enter__())
        # Same process, new to_thread hop: the leaked-open "first" span lives
        # on the POOL thread's stack, so a main-thread span is unaffected.
        with tracer.span("after"):
            pass

    asyncio.run(run())
    after = next(r for r in tracer.records if r.name == "after")
    assert after.depth == 0 and after.parent_id is None


def _unescape_label(value: str) -> str:
    """Inverse of the Prometheus text-format escaping (backslash, quote,
    newline) — what a scraper applies when parsing the exposition."""
    out, i = [], 0
    while i < len(value):
        if value[i] == "\\" and i + 1 < len(value):
            out.append({"\\": "\\", '"': '"', "n": "\n"}[value[i + 1]])
            i += 2
        else:
            out.append(value[i])
            i += 1
    return "".join(out)


def test_histogram_label_escape_round_trip():
    reg = MetricsRegistry()
    h = reg.histogram("nanofed_test_seconds", "escape test", labels=("name",))
    hostile = 'quote " backslash \\ newline \n tab \t done'
    h.observe(0.5, name=hostile)
    lines = h.collect()
    # Every rendered line stays single-line (the newline was escaped) ...
    assert all("\n" not in line for line in lines)
    count_line = next(line for line in lines
                      if line.startswith("nanofed_test_seconds_count"))
    rendered = re.search(r'name="((?:[^"\\]|\\.)*)"', count_line).group(1)
    # ... and a conforming scraper recovers the exact original value.
    assert rendered != hostile
    assert _unescape_label(rendered) == hostile
    assert h.sample_count(name=hostile) == 1
