"""Trace-context wire format + the crash flight recorder
(observability.tracing): derived ids, lenient parsing, the bounded ring,
dump-never-raises, and the MTTR phase decomposition."""

import json

from nanofed_tpu.observability import (
    FlightRecorder,
    TraceContext,
    mttr_decomposition,
    new_trace,
    parse_trace,
)
from nanofed_tpu.observability.tracing import TRACE_VERSION


def test_header_round_trip():
    ctx = new_trace("client-7", 3, 0)
    header = ctx.header()
    version, trace_id, span_id, flags = header.split("-")
    assert version == TRACE_VERSION
    assert len(trace_id) == 32 and len(span_id) == 16 and flags == "01"
    parsed = parse_trace(header)
    assert parsed == ctx


def test_trace_ids_are_derived_not_drawn():
    # Retries of one logical submit share ONE trace (the idempotency contract
    # in trace form); a different submit sequence is a different trace.
    assert new_trace("c0", 5, 2) == new_trace("c0", 5, 2)
    assert new_trace("c0", 5, 2).trace_id != new_trace("c0", 5, 3).trace_id
    # The unit separator keeps part boundaries significant.
    assert new_trace("ab", "c").trace_id != new_trace("a", "bc").trace_id


def test_child_keeps_trace_forks_span_deterministically():
    root = new_trace("c0", 0, 0)
    child = root.child("decode")
    assert child.trace_id == root.trace_id
    assert child.span_id != root.span_id
    assert root.child("decode") == child  # re-processing re-derives, not forks


def test_parse_is_lenient_never_raises():
    assert parse_trace(None) is None
    assert parse_trace("") is None
    assert parse_trace("not a trace") is None
    assert parse_trace("00-short-deadbeefdeadbeef-01") is None
    assert parse_trace("00-" + "g" * 32 + "-" + "a" * 16 + "-01") is None
    assert parse_trace("00-" + "a" * 32 + "-" + "b" * 16) is None  # 3 fields
    # A bare 32-hex trace id is accepted (degraded clients).
    bare = parse_trace("A" * 32)
    assert bare is not None and bare.trace_id == "a" * 32


def test_flight_recorder_ring_is_bounded():
    rec = FlightRecorder(capacity=4, name="t")
    for i in range(10):
        rec.note("tick", i=i)
    events = rec.snapshot()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]  # newest survive
    assert all("t_mono" in e and "t_wall" in e for e in events)


def test_flight_recorder_explicit_t_mono_overrides_stamp():
    # The harness notes first_progress RETROACTIVELY by mapping a wall stamp
    # onto the monotonic axis — the explicit kwarg must win over the auto one.
    rec = FlightRecorder(capacity=8)
    mark = rec.note("first_progress", t_mono=123.456)
    assert mark["t_mono"] == 123.456
    assert rec.snapshot()[-1]["t_mono"] == 123.456


def test_dump_creates_parents_and_reports_drops(tmp_path):
    rec = FlightRecorder(capacity=2, name="supervisor")
    for i in range(5):
        rec.note("tick", i=i)
    out = rec.dump(tmp_path / "deep" / "nested" / "flight_recorder.json",
                   extra={"victim": 1})
    assert out is not None and out.exists()
    doc = json.loads(out.read_text())
    assert doc["recorder"] == "supervisor"
    assert doc["events_dropped"] == 3
    assert doc["victim"] == 1
    assert [e["i"] for e in doc["events"]] == [3, 4]


def test_dump_never_raises(tmp_path):
    # Dump runs inside the supervisor's reap path: any failure must come back
    # as None, never as an exception that would abort the recovery.
    blocker = tmp_path / "file"
    blocker.write_text("not a directory")
    rec = FlightRecorder(capacity=2)
    rec.note("tick")
    assert rec.dump(blocker / "sub" / "flight_recorder.json") is None


def test_mttr_decomposition_phases_and_partial_recovery():
    events = [
        {"kind": "kill_detected", "t_mono": 10.0},
        {"kind": "reaped", "t_mono": 10.5},
        {"kind": "reaped", "t_mono": 99.0},  # re-noted marks must not stretch
        {"kind": "respawned", "t_mono": 11.0},
        {"kind": "first_progress", "t_mono": 14.0},
    ]
    sequence = [
        ("kill_detected", None),
        ("reaped", "reap"),
        ("respawned", "respawn"),
        ("ready", "bring_up"),  # absent mark: phase skipped, chain continues
        ("first_progress", "recompile"),
    ]
    phases = mttr_decomposition(events, sequence)
    assert phases == {"reap": 0.5, "respawn": 0.5, "recompile": 3.0}
    assert mttr_decomposition([], sequence) == {}
