"""Merged federation timelines (observability.critical_path): stream loading,
clock alignment at the bring-up barrier, the Chrome timeline, per-round
critical-path coverage, and trace resolution — all on synthetic streams."""

import json

import pytest

from nanofed_tpu.observability import (
    clock_offsets,
    critical_path_rounds,
    federation_timeline,
    load_host_streams,
    merge_timeline,
    resolve_traces,
    segment_digest,
    summarize_telemetry,
)


def _write(path, records):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _round(host, rnd, start, dur, traces, scale=1.0):
    # Segments tile `scale` of the duration, split 50/10/15/10/10/5 percent.
    split = (0.50, 0.10, 0.15, 0.10, 0.10, 0.05)
    names = ("wire_wait", "decode", "drain", "collective", "apply", "publish")
    return {
        "type": "round", "host": host, "round": rnd, "status": "COMPLETED",
        "duration_s": dur, "start_wall": start, "drained": len(traces),
        "segments": {n: round(dur * scale * f, 6) for n, f in zip(names, split)},
        "traces": traces,
    }


@pytest.fixture
def telemetry_dir(tmp_path):
    """Two workers with a 0.5s clock skew plus the supervisor's stream."""
    _write(tmp_path / "telemetry.jsonl", [
        {"type": "host_failure", "kind": "host_crash", "host": 1, "round": 1},
        {"type": "recovery", "recovery_s": 2.5,
         "mttr_phases": {"reap": 0.5, "respawn": 1.0, "recompile": 1.0}},
    ])
    _write(tmp_path / "host_0" / "telemetry.jsonl", [
        {"type": "clock_sync", "host": 0, "anchor_wall": 1000.0,
         "process_id": 0},
        _round(0, 0, 1000.2, 1.0, ["aa" * 16, "bb" * 16]),
        _round(0, 1, 1001.2, 1.0, ["cc" * 16], scale=0.96),
        {"type": "span", "name": "submit-decode", "start_unix": 1000.4,
         "duration_s": 0.05, "attrs": {"trace": "aa" * 16}},
    ])
    _write(tmp_path / "host_1" / "telemetry.jsonl", [
        {"type": "clock_sync", "host": 1, "anchor_wall": 1000.5,
         "process_id": 1},
        _round(1, 0, 1000.7, 1.0, ["dd" * 16]),
    ])
    return tmp_path


def test_load_host_streams_labels_and_torn_lines(telemetry_dir):
    (telemetry_dir / "host_1" / "telemetry.jsonl").open("a").write(
        '{"type": "round", "torn'  # crashed writer's tail
    )
    streams = load_host_streams(telemetry_dir)
    assert set(streams) == {".", "host_0", "host_1"}
    assert len(streams["host_1"]) == 2  # the torn line is skipped, not fatal
    # A single file loads as the "." stream.
    only = load_host_streams(telemetry_dir / "host_0" / "telemetry.jsonl")
    assert set(only) == {"."} and len(only["."]) == 4


def test_clock_offsets_pin_the_barrier(telemetry_dir):
    streams = load_host_streams(telemetry_dir)
    offsets = clock_offsets(streams)
    # host_0 is the reference (lowest labelled stream with a clock_sync);
    # host_1's clock runs 0.5s ahead, so 0.5s is SUBTRACTED from its stamps.
    assert offsets == {".": 0.0, "host_0": 0.0, "host_1": -0.5}


def test_merge_timeline_lanes_and_alignment(telemetry_dir):
    streams = load_host_streams(telemetry_dir)
    doc = merge_timeline(streams, clock_offsets(streams))
    events = doc["traceEvents"]
    pids = {e["pid"] for e in events}
    assert pids == {0, 1, 1000}  # two worker lanes + the supervisor lane
    rounds = [e for e in events if e["ph"] == "X" and e.get("tid") == 0]
    segments = [e for e in events if e.get("tid") == 1]
    decodes = [e for e in events if e.get("tid") == 2]
    spans = [e for e in events if e.get("tid") == 3]
    assert len(rounds) == 3 and len(decodes) == 3 and len(spans) == 1
    # Both hosts' round 0 started 0.2s after their shared barrier: after
    # alignment the two beats coincide on the timeline.
    r0 = {e["pid"]: e["ts"] for e in rounds if e["args"]["round"] == 0}
    assert r0[1] == pytest.approx(r0[0])
    # Sequential segments tile each beat contiguously (decode is an overlay).
    host0_r0 = sorted((e for e in segments
                       if e["pid"] == 0 and e["args"]["round"] == 0),
                      key=lambda e: e["ts"])
    for prev, nxt in zip(host0_r0, host0_r0[1:]):
        assert nxt["ts"] == pytest.approx(prev["ts"] + prev["dur"])


def test_critical_path_rounds_coverage(telemetry_dir):
    rows = critical_path_rounds(load_host_streams(telemetry_dir))
    assert [(r["host"], r["round"]) for r in rows] == [(0, 0), (1, 0), (0, 1)]
    assert rows[0]["coverage"] == pytest.approx(1.0)
    assert rows[2]["coverage"] == pytest.approx(0.96)  # the scaled round
    digest = segment_digest(rows)
    assert set(digest["segments"]) == {
        "wire_wait", "decode", "drain", "collective", "apply", "publish",
    }
    assert digest["coverage"]["rounds"] == 3
    assert digest["coverage"]["min"] == pytest.approx(0.96)


def test_resolve_traces_healthy_and_degraded(telemetry_dir):
    streams = load_host_streams(telemetry_dir)
    res = resolve_traces(streams)
    assert res["consumed_submits"] == 4
    assert res["unique_traces"] == 4
    assert res["untraced"] == 0 and res["multi_consumed"] == {}
    assert res["resolved"] is True
    assert res["by_trace"]["cc" * 16] == {"host": 0, "round": 1}
    # An untraced submit or a double consumption breaks resolution.
    streams["host_1"].append(_round(1, 1, 1001.7, 1.0, ["", "aa" * 16]))
    res = resolve_traces(streams)
    assert res["untraced"] == 1
    assert res["multi_consumed_count"] == 1
    assert res["resolved"] is False


def test_federation_timeline_digest(telemetry_dir):
    digest = federation_timeline(telemetry_dir)
    assert digest["streams"]["host_1"]["clock_offset_s"] == -0.5
    assert len(digest["rounds"]) == 3
    assert digest["coverage"]["min"] >= 0.95  # the acceptance bar
    assert digest["trace_resolution"]["resolved"] is True
    assert "by_trace" not in digest["trace_resolution"]  # withheld by default
    assert digest["recoveries"][0]["mttr_phases"]["reap"] == 0.5
    assert digest["host_failures"][0]["kind"] == "host_crash"
    with_map = federation_timeline(telemetry_dir, include_trace_map=True)
    assert len(with_map["trace_resolution"]["by_trace"]) == 4


def test_summarize_telemetry_digests_segments_and_clock_sync(telemetry_dir):
    summary = summarize_telemetry(telemetry_dir / "host_0" / "telemetry.jsonl")
    assert summary["critical_path"]["wire_wait"]["count"] == 2
    assert summary["critical_path"]["publish"]["total_s"] == pytest.approx(
        0.05 + 0.048
    )
    assert summary["clock_sync"] == {"hosts": 1, "anchor_spread_s": 0.0}
