"""Span tracer: nesting, the registry histogram bridge, exports, and the
RunTelemetry / summarize_telemetry round trip."""

import json

from nanofed_tpu.observability import (
    SPAN_HISTOGRAM,
    MetricsRegistry,
    RunTelemetry,
    SpanTracer,
    find_latest_telemetry,
    summarize_telemetry,
)


def test_span_nesting_depth_and_parent():
    tracer = SpanTracer(registry=False, annotate_device=False)
    with tracer.span("round", round=0):
        with tracer.span("local-train"):
            pass
        with tracer.span("aggregate"):
            pass
    records = {r.name: r for r in tracer.records}
    assert records["round"].depth == 0 and records["round"].parent_id is None
    for child in ("local-train", "aggregate"):
        assert records[child].depth == 1
        assert records[child].parent_id == records["round"].span_id
    # Children close before the parent, and the parent's duration covers them.
    assert records["round"].duration_s >= records["local-train"].duration_s


def test_span_records_survive_exceptions():
    tracer = SpanTracer(registry=False, annotate_device=False)
    try:
        with tracer.span("round"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert [r.name for r in tracer.records] == ["round"]
    # The stack unwound: a following span is a fresh root, not a child.
    with tracer.span("next"):
        pass
    assert tracer.records[-1].depth == 0


def test_span_histogram_bridge():
    reg = MetricsRegistry()
    tracer = SpanTracer(registry=reg, annotate_device=False)
    with tracer.span("round"):
        pass
    with tracer.span("round"):
        pass
    h = reg.histogram(SPAN_HISTOGRAM, labels=("span",))
    assert h.sample_count(span="round") == 2


def test_phase_summary():
    tracer = SpanTracer(registry=False, annotate_device=False)
    for _ in range(3):
        with tracer.span("round"):
            pass
    summary = tracer.phase_summary()
    assert summary["round"]["count"] == 3
    assert summary["round"]["total_s"] >= summary["round"]["max_s"]
    assert set(summary["round"]) == {"count", "total_s", "max_s", "mean_s"}


def test_jsonl_and_chrome_trace_export(tmp_path):
    tracer = SpanTracer(registry=False, annotate_device=False)
    with tracer.span("round", round=3):
        with tracer.span("local-train"):
            pass
    jsonl = tracer.export_jsonl(tmp_path / "spans.jsonl")
    lines = [json.loads(line) for line in jsonl.read_text().splitlines()]
    assert {r["name"] for r in lines} == {"round", "local-train"}
    assert next(r for r in lines if r["name"] == "round")["attrs"] == {"round": 3}

    chrome = tracer.export_chrome_trace(tmp_path / "trace.json")
    doc = json.loads(chrome.read_text())
    events = doc["traceEvents"]
    assert all(e["ph"] == "X" for e in events)
    assert {e["name"] for e in events} == {"round", "local-train"}
    assert all("ts" in e and "dur" in e and "pid" in e for e in events)


def test_run_telemetry_round_trip(tmp_path):
    reg = MetricsRegistry()
    tel = RunTelemetry(tmp_path, registry=reg, annotate_device=False)
    rounds = reg.counter("nanofed_rounds_total", labels=("status",))
    with tel.span("round", round=0):
        with tel.span("local-train"):
            pass
    rounds.inc(status="completed")
    tel.record("round", round=0, status="COMPLETED", duration_s=0.25)
    tel.close()
    # close() is idempotent; records after close are dropped, not raised.
    tel.close()
    tel.record("span", name="late")

    path = find_latest_telemetry(tmp_path)
    assert path == tmp_path / "telemetry.jsonl"
    summary = summarize_telemetry(path)
    assert summary["rounds"] == {"COMPLETED": 1}
    assert summary["phases"]["round"]["count"] == 1
    assert summary["phases"]["local-train"]["count"] == 1
    assert summary["round_duration"]["p50_s"] == 0.25
    assert summary["counters"]["nanofed_rounds_total"] == {"completed": 1.0}
    # The late post-close records never landed.
    names = [json.loads(line)["type"] for line in path.read_text().splitlines()]
    assert names.count("metrics_snapshot") == 1
    assert names[-1] == "metrics_snapshot"


def test_summarize_tolerates_torn_tail_line(tmp_path):
    p = tmp_path / "telemetry.jsonl"
    p.write_text(
        json.dumps({"type": "round", "status": "COMPLETED", "duration_s": 1.0})
        + "\n"
        + '{"type": "round", "status": "COMPL'  # crash mid-write
    )
    summary = summarize_telemetry(p)
    assert summary["rounds"] == {"COMPLETED": 1}
    assert summary["malformed_lines"] == 1


def test_streaming_tracer_does_not_retain_records():
    """A tracer with an on_close sink (the long-lived coordinator shape) must not
    accumulate records in memory — the sink and the histogram see every span."""
    seen = []
    tracer = SpanTracer(registry=False, on_close=seen.append, annotate_device=False)
    for _ in range(5):
        with tracer.span("round"):
            pass
    assert len(seen) == 5
    assert tracer.records == []
    # Explicit opt-in restores retention even with a sink (bench's shape).
    keeper = SpanTracer(registry=False, on_close=seen.append,
                        annotate_device=False, keep_records=True)
    with keeper.span("round"):
        pass
    assert len(keeper.records) == 1


def test_tracer_threads_nest_independently():
    import threading

    tracer = SpanTracer(registry=False, annotate_device=False)
    barrier = threading.Barrier(2)

    def work(name):
        with tracer.span(name):
            barrier.wait(timeout=5)

    threads = [threading.Thread(target=work, args=(f"t{i}",)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Both spans overlap in time but neither is the other's child.
    assert all(r.depth == 0 and r.parent_id is None for r in tracer.records)


def test_summarize_digests_host_failure_and_recovery_records(tmp_path):
    """The hostchaos supervisor's telemetry (parallel.resilience): detected
    host failures by kind and elastic recoveries with an MTTR digest."""
    p = tmp_path / "telemetry.jsonl"
    p.write_text(
        json.dumps({"type": "host_failure", "kind": "host_crash", "host": 1,
                    "round": 3, "detection_s": 0.08, "detail": "rc=31"})
        + "\n"
        + json.dumps({"type": "host_failure", "kind": "host_stall", "host": 0,
                      "round": 5, "detection_s": 6.2})
        + "\n"
        + json.dumps({"type": "recovery", "recovery_s": 9.7,
                      "resumed_generation": 1, "resumed_round": 2,
                      "rounds_lost": 1, "hosts_before": 3, "hosts_after": 2,
                      "reshape": True, "rejoin": False})
        + "\n"
        + json.dumps({"type": "recovery", "resumed_generation": 3,
                      "resumed_round": 6, "rounds_lost": 0,
                      "hosts_before": 2, "hosts_after": 3, "reshape": True,
                      "rejoin": True})
        + "\n"
    )
    summary = summarize_telemetry(p)
    assert summary["host_failures"]["by_kind"] == {
        "host_crash": 1, "host_stall": 1,
    }
    assert summary["host_failures"]["events"][0]["host"] == 1
    rec = summary["recoveries"]
    assert rec["count"] == 2
    assert rec["mttr"]["count"] == 1  # the rejoin record carries no MTTR
    assert rec["mttr"]["p50_s"] == 9.7
    assert rec["events"][1]["rejoin"] is True


def test_summarize_digests_fleet_records(tmp_path):
    """metrics-summary folds `fleet` telemetry into a `fleets` block keyed by
    profile, last record per profile winning — the tenants/loadtests policy."""
    p = tmp_path / "telemetry.jsonl"
    stale = {
        "type": "fleet", "profile": "phone_edge_silo", "tiers": 3,
        "accepted_total": 1, "ignored_field": "dropped",
    }
    fresh = {
        "type": "fleet", "profile": "phone_edge_silo", "tiers": 3,
        "population": 60, "max_rank": 32, "accepted_total": 41,
        "failed_total": 0, "rejected_429_total": 2,
        "wire_bytes_by_tier": {"phone": 1000, "edge": 2000, "silo": 9000},
        "p99_s_by_tier": {"phone": 0.1, "edge": 0.2, "silo": 0.3},
        "parity_max_abs_diff": 4.5e-08, "rounds": 5,
    }
    other = {"type": "fleet", "profile": "all_silo", "tiers": 1,
             "accepted_total": 7}
    p.write_text("\n".join(json.dumps(r) for r in (stale, fresh, other)) + "\n")

    summary = summarize_telemetry(p)
    assert set(summary["fleets"]) == {"all_silo", "phone_edge_silo"}
    rec = summary["fleets"]["phone_edge_silo"]
    assert rec["accepted_total"] == 41  # last record won
    assert rec["parity_max_abs_diff"] == 4.5e-08
    assert "ignored_field" not in rec
    assert summary["fleets"]["all_silo"] == {"tiers": 1, "accepted_total": 7}
