"""S1 regression: concurrent tenant engines each hold their OWN RunTelemetry
on ONE shared telemetry.jsonl.  Before the O_APPEND fd discipline, stdio
buffering split large records across multiple writes and interleaved them
mid-line; every line must parse, from every writer, with nothing lost."""

import json
import threading

from nanofed_tpu.observability import MetricsRegistry, RunTelemetry


def test_concurrent_instances_never_tear_lines(tmp_path):
    writers, records_each = 4, 50
    # Records far above any stdio buffer: a torn write WOULD interleave.
    payload = "x" * 16384
    tels = [
        RunTelemetry(tmp_path, registry=MetricsRegistry(),
                     annotate_device=False)
        for _ in range(writers)
    ]
    barrier = threading.Barrier(writers)

    def work(w):
        barrier.wait(timeout=10)
        for i in range(records_each):
            tels[w].record("round", writer=w, seq=i, blob=payload)

    threads = [threading.Thread(target=work, args=(w,))
               for w in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for tel in tels:
        tel.close()

    lines = (tmp_path / "telemetry.jsonl").read_text().splitlines()
    parsed = [json.loads(line) for line in lines]  # raises on any torn line
    rounds = [r for r in parsed if r["type"] == "round"]
    assert len(rounds) == writers * records_each
    # Every (writer, seq) pair landed exactly once — nothing lost, nothing
    # duplicated by the append discipline.
    seen = {(r["writer"], r["seq"]) for r in rounds}
    assert len(seen) == writers * records_each
    assert all(r["blob"] == payload for r in rounds)
    # Each writer's close() appended its own snapshot.
    assert sum(1 for r in parsed if r["type"] == "metrics_snapshot") == writers
