"""Asynchronous buffered federation (FedBuff, Nguyen et al. 2022): the pure combine
math, the staleness window on the wire, async x compression base-correctness, and an
end-to-end heterogeneous-speed federation.

The reference framework (and this one's default mode) is strictly synchronous: a
round is a barrier every sampled client must reach.  FedBuff removes the barrier —
the server aggregates whenever K updates are buffered, whatever version each was
trained from, discounting stale directions by (1 + s)^-alpha.  The fast clients stop
waiting for the slow ones; the slow ones still contribute.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
    fedbuff_combine,
)
from nanofed_tpu.core.types import ModelUpdate
from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.local import make_local_fit

PORT = 18732


def _upd(cid, rnd, params):
    return ModelUpdate(client_id=cid, round_number=rnd, params=params,
                       metrics={"loss": 0.1}, timestamp="t")


def test_fedbuff_combine_discounts_staleness():
    """Fresh and stale updates with KNOWN deltas: the aggregate is the discount-
    weighted mean of per-base deltas, applied with server_lr."""
    g0 = {"w": np.zeros(3, np.float32)}
    g1 = {"w": np.ones(3, np.float32)}
    versions = {0: g0, 1: g1}
    fresh = _upd("a", 1, {"w": np.asarray([3.0, 1.0, 1.0], np.float32)})  # delta 2,0,0
    stale = _upd("b", 0, {"w": np.asarray([0.0, 2.0, 0.0], np.float32)})  # delta 0,2,0
    new, stats = fedbuff_combine(
        g1, [fresh, stale], versions, current_version=1,
        staleness_exponent=1.0, server_lr=1.0,
    )
    # UNNORMALIZED FedBuff mean (1/K) * sum(discount * delta): fresh discount 1.0,
    # stale (1+1)^-1 = 0.5 -> (1*[2,0,0] + 0.5*[0,2,0]) / 2 = [1.0, 0.5, 0.0].
    want = np.asarray([1.0, 1.0, 1.0]) + np.asarray([1.0, 0.5, 0.0])
    np.testing.assert_allclose(np.asarray(new["w"]), want, rtol=1e-6)
    assert stats["staleness"] == [0, 1]
    assert stats["num_skipped_out_of_window"] == 0


def test_fedbuff_homogeneous_staleness_still_damps():
    """The discount must NOT normalize away: an all-stale buffer takes a smaller
    step than an all-fresh one with the same deltas — the regression a
    discount-sum normalization would silently reintroduce."""
    g = {"w": np.zeros(2, np.float32)}
    versions = {0: g, 2: g}
    delta_updates_fresh = [_upd(c, 2, {"w": np.ones(2, np.float32)}) for c in "ab"]
    delta_updates_stale = [_upd(c, 0, {"w": np.ones(2, np.float32)}) for c in "ab"]
    fresh, _ = fedbuff_combine(g, delta_updates_fresh, versions, current_version=2,
                               staleness_exponent=1.0)
    stale, _ = fedbuff_combine(g, delta_updates_stale, versions, current_version=2,
                               staleness_exponent=1.0)
    np.testing.assert_allclose(np.asarray(fresh["w"]), 1.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(stale["w"]), 1.0 / 3.0, rtol=1e-6)


def test_fedbuff_combine_skips_out_of_window_bases():
    g = {"w": np.zeros(2, np.float32)}
    versions = {5: g}
    ok = _upd("a", 5, {"w": np.ones(2, np.float32)})
    lost = _upd("b", 1, {"w": np.ones(2, np.float32)})  # base 1 evicted
    new, stats = fedbuff_combine(g, [ok, lost], versions, current_version=5)
    assert stats["num_aggregated"] == 1 and stats["num_skipped_out_of_window"] == 1
    np.testing.assert_allclose(np.asarray(new["w"]), 1.0, rtol=1e-6)
    with pytest.raises(ValueError, match="no aggregatable"):
        fedbuff_combine(g, [lost], versions, current_version=5)


def test_async_refuses_round_locked_mechanisms():
    from nanofed_tpu.aggregation import RobustAggregationConfig

    server = HTTPServer(port=1)
    params = {"w": jnp.zeros(2)}
    with pytest.raises(ValueError, match="async_buffer_k"):
        NetworkCoordinator(
            server, params,
            NetworkRoundConfig(num_rounds=1, async_buffer_k=2),
            robust=RobustAggregationConfig(trim_k=1),
        )
    with pytest.raises(ValueError, match="staleness_window"):
        NetworkRoundConfig(num_rounds=1, async_buffer_k=2, staleness_window=0)


def test_sync_coordinator_refuses_a_windowed_server():
    """A windowed server under the SYNC protocol would re-admit cross-round
    contamination (publish no longer clears the buffer) — refused at construction."""
    server = HTTPServer(port=1, staleness_window=3)
    with pytest.raises(ValueError, match="synchronous"):
        NetworkCoordinator(server, {"w": jnp.zeros(2)},
                           NetworkRoundConfig(num_rounds=1))


def test_take_updates_leaves_surplus_buffered():
    """FedBuff aggregates exactly K: surplus arrivals wait for the next step."""
    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    port = PORT + 5

    async def main():
        server = HTTPServer(port=port, staleness_window=2)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            url = f"http://127.0.0.1:{port}"
            for cid in ("a", "b", "c"):
                async with HTTPClient(url, cid, timeout_s=10) as c:
                    await c.fetch_global_model(like=params)
                    assert await c.submit_update(params, {"loss": 0.1})
            taken = await server.take_updates(2)
            assert [u.client_id for u in taken] == ["a", "b"]  # arrival order
            assert server.num_updates() == 1  # "c" still buffered
        finally:
            await server.stop()

    asyncio.run(main())


def test_staleness_window_accepts_in_window_rejects_beyond():
    """The wire contract: an update for version v is accepted while
    current - W <= v, rejected once the window moves past it."""
    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    port = PORT + 1

    async def main():
        server = HTTPServer(port=port, staleness_window=2)
        await server.start()
        try:
            for v in range(4):  # versions 0..3 published; window is [1, 3]
                await server.publish_model(params, round_number=v)
            url = f"http://127.0.0.1:{port}"
            async with HTTPClient(url, "slow", timeout_s=10) as c:
                c.current_round = 1  # in-window stale base
                assert await c.submit_update(params, {"loss": 0.5})
                c.current_round = 0  # beyond the window
                assert not await c.submit_update(params, {"loss": 0.5})
            assert server.num_updates() == 1
        finally:
            await server.stop()

    asyncio.run(main())


def test_async_buffer_survives_publish():
    """Sync mode clears the buffer on publish (cross-round contamination); async
    mode must NOT — a straggler's in-window update stays aggregatable."""
    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    port = PORT + 2

    async def main():
        server = HTTPServer(port=port, staleness_window=3)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(f"http://127.0.0.1:{port}", "c1", timeout_s=10) as c:
                await c.fetch_global_model(like=params)
                assert await c.submit_update(params, {"loss": 0.5})
            await server.publish_model(params, round_number=1)
            assert server.num_updates() == 1  # survived the publish
        finally:
            await server.stop()

    asyncio.run(main())


def test_async_q8_reconstructs_against_the_fetched_base():
    """Compression x staleness: a client that fetched version 0 submits a q8 DELTA
    while the server is already on version 1 — reconstruction must use version 0's
    params (the client's actual base), not the current ones."""
    model = get_model("linear", in_features=4, num_classes=2)
    p0 = model.init(jax.random.key(0))
    p1 = jax.tree.map(lambda p: p + 1.0, p0)  # very different current version
    trained = jax.tree.map(lambda p: p + 0.01 * jnp.ones_like(p), p0)
    port = PORT + 3

    async def main():
        server = HTTPServer(port=port, staleness_window=2)
        await server.start()
        try:
            await server.publish_model(p0, round_number=0)
            async with HTTPClient(f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                                  update_encoding="q8-delta") as c:
                await c.fetch_global_model(like=p0)  # base = version 0
                await server.publish_model(p1, round_number=1)  # server moves on
                assert await c.submit_update(trained, {"loss": 0.1})
            (u,) = await server.drain_updates()
            for got, want, base in zip(jax.tree.leaves(u.params),
                                       jax.tree.leaves(trained),
                                       jax.tree.leaves(p0)):
                scale = float(np.abs(np.asarray(want) - np.asarray(base)).max()) / 127
                np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                           atol=scale * (1 + 1e-6))
        finally:
            await server.stop()

    asyncio.run(main())


def test_heterogeneous_speed_federation_end_to_end(devices):
    """The capability itself: 4 clients at very different speeds, K=2 buffer. The
    federation completes all aggregations without ever waiting for the slowest
    cohort, stale updates appear (and are discounted), and the model learns.

    Deflaked PROPERLY (ISSUE 6 satellite; history: PR 4 widened timeouts, PR 5
    gated the staleness assertion on a load-average check): every wait —
    client "compute speed" delays, coordinator deadlines, poll intervals —
    now rides an injectable ``VirtualClock``, so the slow clients are slow BY
    CONSTRUCTION (virtual deadline order) and not by hoping the CI core is
    contended the right amount.  c3's 0.15 s delay overlapping the first
    version publishes is an ordering guarantee, so the staleness assertion is
    UNCONDITIONAL — no load gate — and host contention can neither starve it
    nor expire a round timeout."""
    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.utils.clock import VirtualClock

    model = get_model("mlp", in_features=8, hidden=16, num_classes=3)
    ds = synthetic_classification(512, 3, (8,), seed=0)
    cd = federate(ds, num_clients=4, scheme="iid", batch_size=16)
    # Jitted: the eager per-op path costs ~1 s per fit on the 1-core host and
    # would make this a compute test instead of a coordination test.
    fit = jax.jit(make_local_fit(
        model.apply, TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.2)
    ))
    params = model.init(jax.random.key(0))
    port = PORT + 4
    clock = VirtualClock()
    delays = {"c0": 0.0, "c1": 0.01, "c2": 0.05, "c3": 0.15}

    async def client(cid, idx):
        data = jax.tree.map(lambda a: jnp.asarray(a[idx]), cd)
        async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=60,
                              clock=clock) as c:
            while True:
                fetched, rnd, active = await c.fetch_global_model(like=params)
                if not active:
                    return
                result = fit(jax.tree.map(jnp.asarray, fetched), data,
                             jax.random.key(idx))
                await clock.sleep(delays[cid])  # heterogeneous compute speed
                await c.submit_update(
                    result.params,
                    {"loss": float(result.metrics.loss), "num_samples": 128.0},
                )
                await clock.sleep(0.005)

    async def main():
        server = HTTPServer(port=port, clock=clock)
        coord = NetworkCoordinator(
            server, params,
            # Virtual seconds: expire by schedule, never by host contention.
            NetworkRoundConfig(num_rounds=6, async_buffer_k=2, staleness_window=4,
                               round_timeout_s=30.0, poll_interval_s=0.005),
            clock=clock,
        )
        assert server.staleness_window == 4  # coordinator wired the window
        await server.start()
        try:
            tasks = [asyncio.create_task(client(f"c{i}", i)) for i in range(4)]
            history = await coord.run()
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=90)
        finally:
            await server.stop()
        return history, coord

    history, coord = asyncio.run(main())
    completed = [h for h in history if h["status"] == "COMPLETED"]
    assert len(completed) == 6
    # No cohort barrier: every aggregation used exactly-ish the buffer fill.
    assert all(h["num_clients"] >= 2 for h in completed)
    # UNCONDITIONAL now: c3 trains from version 0 for 0.15 virtual seconds
    # while c0/c1 fill the K=2 buffer at ~0.01 — at least one later
    # aggregation must therefore see a stale base.  On the virtual clock this
    # is deadline ordering, not a race.
    assert any(s > 0 for h in completed for s in h["staleness"])
    # The model moved and the loss trajectory is sane (finite, generally falling).
    losses = [h["metrics"]["loss"] for h in completed if h["metrics"]["loss"]]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(coord.params)):
        assert float(np.abs(np.asarray(b) - np.asarray(a)).max()) > 0
