"""Chaos harness (nanofed_tpu.faults): liveness invariants under seeded failure.

The ISSUE-6 acceptance criteria, as executable claims — all on a
``VirtualClock`` so every timeout/straggler behavior is a pure function of the
seeded ``FaultPlan``, not of host load:

(a) a sync round survives f = 25% client crashes via completion-rate graceful
    degradation, and the dead clients are EVICTED from the barrier after
    ``straggler_evict_after`` consecutive misses;
(b) a server kill-restart mid-round resumes from the persisted round state
    (``persistence.state_store``) and converges to the same loss trajectory as
    an unfailed run within tolerance — with the SAME client tasks surviving
    the restart through their retry policy;
(c) duplicate submits under the retry policy (a lost-ACK storm) change the
    global params exactly once (FedBuff would otherwise double-count across
    drains);

plus the chaos-smoke seed the CI job runs, and the in-process simulator's
deterministic crash injection.  Retry/eviction/429/fault counters are asserted
visible in the Prometheus rendering and ``telemetry.jsonl``.
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
    RetryPolicy,
)
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.faults import (
    ChaosClient,
    ChaosSchedule,
    FaultEvent,
    FaultPlan,
    InjectedServerCrash,
)
from nanofed_tpu.models import get_model
from nanofed_tpu.observability.registry import MetricsRegistry
from nanofed_tpu.persistence.state_store import FileStateStore, is_recoverable
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.local import make_local_fit
from nanofed_tpu.utils.clock import VirtualClock

PORT = 19050

_MODEL = get_model("linear", in_features=6, num_classes=2)
_TEMPLATE = _MODEL.init(jax.random.key(0))
_FIT = jax.jit(make_local_fit(
    _MODEL.apply, TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
))


def _client_data(idx: int) -> ClientData:
    r = np.random.default_rng(100 + idx)
    x = r.normal(size=(16, 6)).astype(np.float32)
    w = r.normal(size=(6,))
    y = (x @ w > 0).astype(np.int32)
    return ClientData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.ones((16,)))


async def _run_client(
    cid: str,
    idx: int,
    port: int,
    clock: VirtualClock,
    schedule: ChaosSchedule | None,
    registry: MetricsRegistry,
    resubmit_after: float = 2.0,
    start_delay_s: float = 0.0,
) -> None:
    """A production-shaped scripted client: fetch → train (deterministic in
    (round, client)) → submit, with retries, under the chaos plan.  If the
    SAME round stays open ``resubmit_after`` virtual seconds after our submit
    (a restarted server lost its buffer), re-submit — the server's dedupe and
    latest-wins buffering make this safe.  ``start_delay_s`` (virtual) orders
    the FIRST submits across clients: the VirtualClock wakes sleepers in
    deadline order, so a test whose claim depends on which clients the round-0
    barrier sees (the eviction drill) can make that set deterministic instead
    of racing real loopback scheduling."""
    data = _client_data(idx)
    if start_delay_s:
        await clock.sleep(start_delay_s)
    retry = RetryPolicy(max_attempts=10, base_backoff_s=0.02, max_backoff_s=0.5,
                        seed=1234)
    async with HTTPClient(
        f"http://127.0.0.1:{port}", cid, timeout_s=60,
        registry=registry, retry=retry, clock=clock,
    ) as client:
        chaos = ChaosClient(client, schedule, clock=clock) if schedule else None
        submitted: dict[int, float] = {}
        while True:
            try:
                params, rnd, active = await client.fetch_global_model(like=_TEMPLATE)
            except NanoFedError:
                return  # server gone past the retry budget
            if not active:
                return
            if chaos is not None and not chaos.alive(rnd):
                return  # planned crash: silence, like a dead process
            if rnd in submitted and clock.time() - submitted[rnd] < resubmit_after:
                await clock.sleep(0.05)
                continue
            result = _FIT(jax.tree.map(jnp.asarray, params), data,
                          jax.random.key(1000 * rnd + idx))
            metrics = {"loss": float(result.metrics.loss), "num_samples": 16.0}
            if chaos is not None:
                await chaos.submit(result.params, metrics, rnd)
            else:
                await client.submit_update(result.params, metrics)
            submitted[rnd] = clock.time()
            await clock.sleep(0.05)


def test_round_survives_25pct_crashes_with_eviction(tmp_path):
    """(a) 8 clients, 2 crash at round 1 (f = 25%): every round completes via
    the 0.75 completion-rate gate, the dead pair is evicted after 3
    consecutive misses, the barrier degrades, and the counters land in
    /metrics and telemetry.jsonl — all deterministic under the plan.

    Why 3, not 2: rounds BEFORE the eviction require all 6 live clients
    (required=6), so only the dead pair can accrue misses there; after the
    eviction the gate drops to 5 and a live client CAN legitimately lose the
    decode race for a round.  With evict_after=2 the post-eviction window was
    2 rounds long — enough for a straggling live client to be evicted too,
    which flaked the only-the-dead-pair assertion (seen on the seed tree).
    With 3, eviction lands at the end of round 3 and only round 4 runs on
    the shrunk gate: no live client can reach 3 consecutive misses."""
    registry = MetricsRegistry()
    plan = FaultPlan(seed=11, events=(
        FaultEvent(kind="crash", round=1, client="c6"),
        FaultEvent(kind="crash", round=1, client="c7"),
    ))
    schedule = ChaosSchedule(plan, registry=registry)
    clock = VirtualClock()
    port = PORT + 0

    async def main():
        server = HTTPServer(port=port, registry=registry, clock=clock)
        coordinator = NetworkCoordinator(
            server, _TEMPLATE,
            NetworkRoundConfig(
                num_rounds=5, min_clients=8, min_completion_rate=0.75,
                round_timeout_s=20.0, poll_interval_s=0.01,
                straggler_evict_after=3,
            ),
            telemetry_dir=tmp_path, registry=registry, clock=clock,
        )
        await server.start()
        try:
            # The doomed pair submits round 0 FIRST (zero delay; the live six
            # wake 1 virtual ms later): the round-0 barrier closes at 6 of 8,
            # and only clients it SAW become evictable — without the ordering,
            # whether c6/c7 land in the first six is a real socket/decode race
            # and the eviction assertion below flakes (seen on the seed tree).
            tasks = [
                asyncio.create_task(
                    _run_client(f"c{i}", i, port, clock, schedule, registry,
                                start_delay_s=0.0 if i >= 6 else 0.001)
                )
                for i in range(8)
            ]
            history = await coordinator.run()
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
            return history, coordinator
        finally:
            await server.stop()

    history, coordinator = asyncio.run(main())
    assert [h["status"] for h in history] == ["COMPLETED"] * 5
    # Round 0 had all 8; post-crash rounds ran on the 6 survivors, above the
    # ceil(8 * 0.75) = 6 gate (graceful degradation, not a stall).
    assert history[0]["num_clients"] >= 6
    # Rounds 2-3 still gate on required=6 (the evictions land at the END of
    # round 3), so all six survivors are in them.  Round 4 gates on
    # required=5: the barrier may legally close before the sixth straggling
    # submit finishes decoding — that IS the completion-rate gate — so
    # assert the gate there, not a lockstep six (the lockstep form flaked
    # on the decode-thread race).
    assert history[2]["num_clients"] == 6
    assert history[3]["num_clients"] == 6
    assert all(
        6 >= h["num_clients"] >= h["required"] for h in history[2:]
    )
    # The dead pair — and only it — was evicted, and the barrier shrank.
    evicted = sorted(
        c for h in history for c in h.get("evicted_stragglers", ())
    )
    assert evicted == ["c6", "c7"]
    assert history[-1]["required"] == 5  # ceil((8 - 2) * 0.75)
    assert coordinator._evicted_stragglers == {"c6", "c7"}
    # Counters visible where the ISSUE wants them: Prometheus + telemetry.
    text = registry.render_prometheus()
    assert "nanofed_straggler_evictions_total 2" in text
    assert 'nanofed_faults_injected_total{kind="crash"} 2' in text
    telemetry = [
        json.loads(line)
        for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
    ]
    rounds = [t for t in telemetry if t.get("type") == "round"]
    assert len(rounds) == 5
    assert any(t.get("evicted_stragglers") for t in rounds)


def test_server_kill_restart_resumes_and_converges(tmp_path):
    """(b) The kill-restart drill: a planned ``server_kill`` fires mid-round 3,
    the run crashes exactly as ``persistence.is_recoverable`` expects, a new
    server + coordinator rebuilt over the SAME state store resume at round 3,
    the surviving client tasks re-sync through their retry policy, and the
    combined run converges to the unfailed run's loss trajectory."""
    registry_ref = MetricsRegistry()
    clock_ref = VirtualClock()
    port_ref = PORT + 1

    config = dict(num_rounds=6, min_clients=4, min_completion_rate=1.0,
                  round_timeout_s=30.0, poll_interval_s=0.01)

    async def reference():
        server = HTTPServer(port=port_ref, registry=registry_ref, clock=clock_ref)
        coordinator = NetworkCoordinator(
            server, _TEMPLATE, NetworkRoundConfig(**config),
            registry=registry_ref, clock=clock_ref,
        )
        await server.start()
        try:
            tasks = [
                asyncio.create_task(_run_client(
                    f"c{i}", i, port_ref, clock_ref, None, registry_ref))
                for i in range(4)
            ]
            history = await coordinator.run()
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
            return history, coordinator.params
        finally:
            await server.stop()

    ref_history, ref_params = asyncio.run(reference())
    assert [h["status"] for h in ref_history] == ["COMPLETED"] * 6

    registry = MetricsRegistry()
    clock = VirtualClock()
    port = PORT + 2
    store = FileStateStore(tmp_path / "state")
    schedule = ChaosSchedule(
        FaultPlan(seed=7, events=(FaultEvent(kind="server_kill", round=3),)),
        registry=registry,
    )

    async def chaotic():
        tasks = [
            asyncio.create_task(
                _run_client(f"c{i}", i, port, clock, None, registry))
            for i in range(4)
        ]

        async def incarnation():
            server = HTTPServer(port=port, registry=registry, clock=clock)
            coordinator = NetworkCoordinator(
                server, _TEMPLATE, NetworkRoundConfig(**config),
                registry=registry, clock=clock,
                state_store=FileStateStore(tmp_path / "state"),
                chaos=schedule,
            )
            await server.start()
            try:
                return coordinator, await coordinator.run(), None
            except InjectedServerCrash as crash:
                return coordinator, list(coordinator.history), crash
            finally:
                await server.stop()

        try:
            coord1, h1, crash = await incarnation()
            assert crash is not None and is_recoverable(crash)
            assert coord1.start_round == 0
            # Rounds 0-2 completed and were checkpointed before the kill.
            assert [h["status"] for h in h1] == ["COMPLETED"] * 3
            coord2, h2, crash2 = await incarnation()
            assert crash2 is None
            assert coord2.start_round == 3  # resumed, not re-run
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=120)
            return h1 + h2, coord2.params
        finally:
            for t in tasks:
                t.cancel()

    history, params = asyncio.run(chaotic())
    assert store.restore_latest().round_number == 5
    assert [h["round"] for h in history] == list(range(6))
    assert [h["status"] for h in history] == ["COMPLETED"] * 6
    # Convergence: the resumed trajectory matches the unfailed run round for
    # round (identical cohorts + deterministic fits; tolerance covers
    # arrival-order float reassociation in the weighted mean).
    for got, want in zip(history, ref_history):
        assert got["metrics"]["loss"] == pytest.approx(
            want["metrics"]["loss"], abs=1e-4
        )
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert 'nanofed_faults_injected_total{kind="server_kill"} 1' \
        in registry.render_prometheus()


def test_duplicate_submits_change_global_params_exactly_once():
    """(c) FedBuff + lost ACK + retry storm: the aggregation applies the
    client's KNOWN delta exactly once, and the straggling duplicates that
    arrive after the drain never re-enter the buffer."""
    registry = MetricsRegistry()
    clock = VirtualClock()
    port = PORT + 3
    schedule = ChaosSchedule(
        FaultPlan(seed=5, events=(
            FaultEvent(kind="ack_drop", round=0, client="c1", count=1),
        )),
        registry=registry,
    )
    base = {"w": jnp.zeros(4, jnp.float32)}
    trained = {"w": jnp.ones(4, jnp.float32)}  # known delta: +1

    async def main():
        server = HTTPServer(port=port, registry=registry, clock=clock,
                            chaos=schedule)
        coordinator = NetworkCoordinator(
            server, base,
            NetworkRoundConfig(num_rounds=1, async_buffer_k=1,
                               staleness_window=2, round_timeout_s=10.0,
                               poll_interval_s=0.001),
            registry=registry, clock=clock,
        )
        await server.start()
        try:

            async def client():
                async with HTTPClient(
                    f"http://127.0.0.1:{port}", "c1", timeout_s=30,
                    registry=registry, clock=clock,
                    # Backoff LONGER than the coordinator's poll: the retry
                    # lands after the drain, the worst case for double-count.
                    retry=RetryPolicy(max_attempts=6, base_backoff_s=0.05,
                                      seed=0),
                ) as c:
                    await c.fetch_global_model(like=base)
                    assert await c.submit_update(trained, {"loss": 0.5})
                    for _ in range(3):  # keep the storm going post-drain
                        assert await c.resend_last_update()

            task = asyncio.create_task(client())
            history = await coordinator.run()
            await asyncio.wait_for(task, timeout=60)
            return history, coordinator, server

        finally:
            await server.stop()

    history, coordinator, server = asyncio.run(main())
    assert history[0]["status"] == "COMPLETED"
    assert history[0]["num_clients"] == 1
    # Exactly once: base + 1.0, not base + 2.0 (or more).
    np.testing.assert_allclose(np.asarray(coordinator.params["w"]),
                               np.ones(4), atol=1e-6)
    assert server.num_updates() == 0  # duplicates never re-buffered
    text = registry.render_prometheus()
    assert 'nanofed_faults_injected_total{kind="ack_drop"} 1' in text
    assert 'result="duplicate"' in text


def test_chaos_smoke(tmp_path):
    """The CI chaos-smoke seed (make chaos-smoke): a GENERATED 8-client plan
    with one crash and one straggler; the federation completes every round and
    the injected faults are visible in the counters."""
    registry = MetricsRegistry()
    plan = FaultPlan.generate(
        seed=6, clients=[f"c{i}" for i in range(8)], num_rounds=3,
        crash_fraction=1 / 8, straggler_fraction=1 / 8, straggler_delay_s=3.0,
    )
    assert sum(1 for e in plan.events if e.kind == "crash") == 1
    assert sum(1 for e in plan.events if e.kind == "delay") == 1
    schedule = ChaosSchedule(plan, registry=registry)
    clock = VirtualClock()
    port = PORT + 4

    async def main():
        server = HTTPServer(port=port, registry=registry, clock=clock,
                            chaos=schedule)
        coordinator = NetworkCoordinator(
            server, _TEMPLATE,
            NetworkRoundConfig(num_rounds=3, min_clients=8,
                               min_completion_rate=0.75, round_timeout_s=20.0,
                               poll_interval_s=0.01, straggler_evict_after=2),
            telemetry_dir=tmp_path, registry=registry, clock=clock, chaos=schedule,
        )
        await server.start()
        try:
            tasks = [
                asyncio.create_task(
                    _run_client(f"c{i}", i, port, clock, schedule, registry))
                for i in range(8)
            ]
            history = await coordinator.run()
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
            return history
        finally:
            await server.stop()

    history = asyncio.run(main())
    assert [h["status"] for h in history] == ["COMPLETED"] * 3
    counts = schedule.counts()
    assert counts.get("crash", 0) == 1
    assert (tmp_path / "telemetry.jsonl").exists()


def test_simulator_chaos_crashes_gate_rounds(devices):
    """In-process injection point: the SPMD simulator's cohorts drop planned
    crashes deterministically, standing or falling on min_completion_rate
    exactly like a real dropout wave — and an identical run without the plan
    completes."""
    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.orchestration.types import RoundStatus

    ds = synthetic_classification(256, 3, (8,), seed=0)
    cd = federate(ds, num_clients=8, scheme="iid", batch_size=16)
    config = CoordinatorConfig(num_rounds=2, min_completion_rate=0.9, seed=0,
                               save_metrics=False)
    training = TrainingConfig(batch_size=16, local_epochs=1)
    model = get_model("mlp", in_features=8, hidden=8, num_classes=3)

    plan = FaultPlan(seed=3, events=tuple(
        FaultEvent(kind="crash", round=0, client=i) for i in range(3)
    ))
    chaotic = Coordinator(
        model=model, train_data=cd, config=config, training=training,
        chaos=ChaosSchedule(plan, registry=MetricsRegistry()),
    )
    rounds = chaotic.run()
    # 5/8 survivors < 0.9 completion: every round FAILS, deterministically.
    assert all(r.status == RoundStatus.FAILED for r in rounds)

    clean = Coordinator(model=model, train_data=cd, config=config,
                        training=training)
    assert all(r.status == RoundStatus.COMPLETED for r in clean.run())
