"""Observability subsystem, end to end over the real network path.

The acceptance surface of the subsystem: a 2-round ``NetworkCoordinator`` federation
must expose non-zero ``nanofed_rounds_total`` / ``nanofed_bytes_received_total`` and
per-phase span durations via BOTH ``GET /metrics`` (Prometheus text) and the per-run
``telemetry.jsonl`` — plus the satellite regressions this PR folds in: true
error-feedback across a rejected topk8 submit, and the accurate 400 (not 403) for a
straggler racing ``publish_model`` mid-decode.
"""

import asyncio
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
)
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.models import get_model
from nanofed_tpu.observability import MetricsRegistry, summarize_telemetry
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.local import make_local_fit

PORT = 18732


def _client_data(seed):
    r = np.random.default_rng(seed)
    x = r.normal(size=(16, 8)).astype(np.float32)
    w = r.normal(size=(8,))
    y = (x @ w > 0).astype(np.int32)
    return ClientData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.ones((16,)))


async def _run_client(client_id, model, local_fit, data, port, registry):
    async with HTTPClient(f"http://127.0.0.1:{port}", client_id, timeout_s=30,
                          registry=registry) as client:
        while True:
            params, rnd, active = await client.fetch_global_model(
                like=model.init(jax.random.key(0))
            )
            if not active:
                return
            result = local_fit(jax.tree.map(jnp.asarray, params), data,
                               jax.random.key(hash(client_id) % 2**31))
            await client.submit_update(
                result.params,
                {"loss": float(result.metrics.loss),
                 "accuracy": float(result.metrics.accuracy),
                 "num_samples": float(result.metrics.samples)},
            )
            status = await client.check_server_status()
            while status["training_active"] and status["round"] == rnd:
                await asyncio.sleep(0.05)
                status = await client.check_server_status()
            if not status["training_active"]:
                return


def test_two_round_federation_populates_metrics_and_telemetry(tmp_path):
    """The PR's acceptance criterion, verbatim: after a 2-round network federation,
    /metrics and telemetry.jsonl both carry rounds, bytes, and phase durations."""
    model = get_model("linear", in_features=8, num_classes=2)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    local_fit = jax.jit(make_local_fit(model.apply, training))
    registry = MetricsRegistry()  # isolated: assertions must not see other tests

    async def main():
        server = HTTPServer(port=PORT, registry=registry)
        await server.start()
        try:
            init = model.init(jax.random.key(0))
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=2, min_clients=2, round_timeout_s=30),
                telemetry_dir=tmp_path,
            )

            async def scrape():
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"http://127.0.0.1:{PORT}/metrics") as resp:
                        assert resp.status == 200
                        assert resp.headers["Content-Type"].startswith("text/plain")
                        return await resp.text()

            results = await asyncio.gather(
                coordinator.run(),
                _run_client("c1", model, local_fit, _client_data(1), PORT, registry),
                _run_client("c2", model, local_fit, _client_data(2), PORT, registry),
            )
            return results[0], await scrape()
        finally:
            await server.stop()

    history, metrics_text = asyncio.run(main())
    assert [h["status"] for h in history] == ["COMPLETED", "COMPLETED"]

    # --- GET /metrics: Prometheus text with non-zero headline series ---
    lines = metrics_text.splitlines()

    def sample(prefix):
        return [line for line in lines if line.startswith(prefix)
                and not line.startswith("#")]

    rounds = sample('nanofed_rounds_total{status="completed"}')
    assert rounds and float(rounds[0].split()[-1]) == 2.0
    rx = sample('nanofed_bytes_received_total{endpoint="update"}')
    assert rx and float(rx[0].split()[-1]) > 0
    tx = sample('nanofed_bytes_sent_total{endpoint="model"}')
    assert tx and float(tx[0].split()[-1]) > 0
    accepted = sample('nanofed_updates_total{kind="plain",result="accepted"}')
    assert accepted and float(accepted[0].split()[-1]) == 4.0  # 2 clients x 2 rounds
    # Per-phase span durations: every federation phase has a populated histogram.
    for phase in ("round", "publish", "cohort-sample", "aggregate"):
        count = sample(f'nanofed_span_duration_seconds_count{{span="{phase}"}}')
        assert count and float(count[0].split()[-1]) >= 2.0, phase

    # --- telemetry.jsonl: spans + round records + final snapshot ---
    summary = summarize_telemetry(tmp_path / "telemetry.jsonl")
    assert summary["rounds"] == {"COMPLETED": 2}
    for phase in ("round", "publish", "cohort-sample", "aggregate"):
        assert summary["phases"][phase]["count"] == 2, phase
        assert summary["phases"][phase]["total_s"] > 0
    assert summary["round_duration"]["count"] == 2
    assert summary["counters"]["nanofed_rounds_total"] == {"completed": 2.0}
    assert summary["counters"]["nanofed_bytes_received_total"]["update"] > 0
    # Phase spans nest under the round: their wall time is bounded by it.
    assert (summary["phases"]["aggregate"]["total_s"]
            <= summary["phases"]["round"]["total_s"])


def test_topk8_rejected_submit_keeps_error_feedback(tmp_path):
    """Satellite regression (http_client): a rejected topk8 submit folds the WHOLE
    un-sent delta into the residual (error feedback across a dropped round), and an
    immediate retry does NOT double-count the round's delta."""
    model = get_model("linear", in_features=4, num_classes=2)
    params0 = model.init(jax.random.key(0))
    trained = jax.tree.map(lambda p: p + 0.1, params0)

    async def main():
        server = HTTPServer(port=PORT + 1)
        await server.start()
        try:
            await server.publish_model(params0, round_number=5)
            async with HTTPClient(
                f"http://127.0.0.1:{PORT + 1}", "c1", timeout_s=10,
                update_encoding="topk8-delta", topk_fraction=0.4,
                registry=MetricsRegistry(),
            ) as c:
                fetched, rnd, _ = await c.fetch_global_model(like=params0)
                assert rnd == 5
                # Submit against a stale round: rejected, nothing applied.
                c.current_round = 3
                assert not await c.submit_update(trained, {"loss": 0.5})
                assert server.num_updates() == 0
                # True error feedback: the accumulator now holds the FULL delta
                # (params - global), not just the quantization tail.
                full_delta = jax.tree.map(
                    lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
                    trained, fetched,
                )
                for acc, want in zip(jax.tree.leaves(c._residual),
                                     jax.tree.leaves(full_delta)):
                    np.testing.assert_allclose(acc, want, atol=1e-6)
                # Immediate retry at the right round with the SAME params: accepted,
                # and the buffered reconstruction is ~ global + 1x delta (a
                # double-count would land near 2x).
                c.current_round = 5
                assert await c.submit_update(trained, {"loss": 0.5})
                (update,) = await server.drain_updates()
                for got, base, want in zip(jax.tree.leaves(update.params),
                                           jax.tree.leaves(fetched),
                                           jax.tree.leaves(full_delta)):
                    applied = np.asarray(got, np.float32) - np.asarray(
                        base, np.float32
                    )
                    # topk_fraction=0.4 sends only part of the mass; what was sent
                    # must be a subset of ONE delta, never more.
                    assert np.abs(applied).max() <= np.abs(want).max() * 1.01
                    overshoot = np.abs(applied) > np.abs(want) * 1.5
                    assert not overshoot.any()
                # Residual + sent still conserves the total mass (nothing lost,
                # nothing duplicated).
                for res, base, got, want in zip(
                    jax.tree.leaves(c._residual), jax.tree.leaves(fetched),
                    jax.tree.leaves(update.params), jax.tree.leaves(full_delta),
                ):
                    sent = np.asarray(got, np.float32) - np.asarray(base, np.float32)
                    np.testing.assert_allclose(res + sent, want, atol=1e-2)
        finally:
            await server.stop()

    asyncio.run(main())


def test_decode_base_is_snapshotted_before_the_decode_thread():
    """Signature-free core of the race fix: the compressed-update decode must
    receive the base params snapshotted under the lock (the round-0 params the
    client fetched), even when publish_model advances the round before the decode
    thread runs — and the straggler still gets the 400 stale-round rejection."""
    model = get_model("linear", in_features=4, num_classes=2)
    params0 = model.init(jax.random.key(0))
    port = PORT + 3

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            await server.publish_model(params0, round_number=0)
            seen_bases = []
            orig = server._reconstruct_compressed_update
            loop = asyncio.get_event_loop()

            def racy(body, encoding, base):
                seen_bases.append(base)
                fut = asyncio.run_coroutine_threadsafe(
                    server.publish_model(
                        jax.tree.map(lambda p: p + 1.0, params0), 1
                    ),
                    loop,
                )
                fut.result(timeout=10)
                return orig(body, encoding, base)

            server._reconstruct_compressed_update = racy
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                update_encoding="q8-delta", registry=MetricsRegistry(),
            ) as c:
                fetched, rnd, _ = await c.fetch_global_model(like=params0)
                trained = jax.tree.map(lambda p: p + 0.05, fetched)
                ok = await c.submit_update(trained, {"loss": 0.5})
            assert not ok  # locked re-check: the round moved on -> stale
            assert server.num_updates() == 0
            # The decode saw the ROUND-0 base, not the round-1 params that were
            # published mid-flight.
            (base,) = seen_bases
            for got, want in zip(jax.tree.leaves(base), jax.tree.leaves(params0)):
                np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        finally:
            await server.stop()

    asyncio.run(main())


def test_raced_straggler_gets_stale_round_not_signature_failure():
    """Satellite regression (http_server): when publish_model advances the round
    while a compressed update is being decoded, the straggler must get the accurate
    400 stale-round rejection — the decode base was snapshotted under the lock, so
    the signature check can never see a reconstruction against the wrong params
    (which previously surfaced as a misleading 403)."""
    pytest.importorskip("cryptography")
    from nanofed_tpu.security import SecurityManager

    model = get_model("linear", in_features=4, num_classes=2)
    params0 = model.init(jax.random.key(0))
    signer = SecurityManager(key_size=2048)
    port = PORT + 2

    async def main():
        server = HTTPServer(
            port=port,
            client_keys={"c1": signer.get_public_key()},
            require_signatures=True,
        )
        await server.start()
        try:
            await server.publish_model(params0, round_number=0)
            # Make the decode-thread dispatch the race window: the round advances
            # after the under-lock snapshot but before the decode runs.
            orig = server._reconstruct_compressed_update
            loop = asyncio.get_event_loop()

            def racy(body, encoding, base):
                fut = asyncio.run_coroutine_threadsafe(
                    server.publish_model(
                        jax.tree.map(lambda p: p + 1.0, params0), 1
                    ),
                    loop,
                )
                fut.result(timeout=10)
                return orig(body, encoding, base)

            server._reconstruct_compressed_update = racy

            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                security_manager=signer, update_encoding="q8-delta",
                registry=MetricsRegistry(),
            ) as c:
                fetched, rnd, _ = await c.fetch_global_model(like=params0)
                assert rnd == 0
                trained = jax.tree.map(lambda p: p + 0.05, fetched)
                # Bypass HTTPClient's convenience wrapper to read the raw status.
                import base64

                from nanofed_tpu.communication.codec import (
                    encode_delta_q8,
                    reconstruct_q8,
                )
                from nanofed_tpu.communication.http_server import (
                    HEADER_CLIENT,
                    HEADER_ENCODING,
                    HEADER_METRICS,
                    HEADER_ROUND,
                    HEADER_SIGNATURE,
                )

                delta = jax.tree.map(
                    lambda p, g: np.asarray(p, np.float32)
                    - np.asarray(g, np.float32),
                    trained, fetched,
                )
                body = encode_delta_q8(delta)
                signed_params = reconstruct_q8(fetched, body)
                signature = signer.sign_update(signed_params, "c1", 0, "{}")
                async with aiohttp.ClientSession() as s:
                    async with s.post(
                        f"http://127.0.0.1:{port}/update", data=body,
                        headers={
                            HEADER_CLIENT: "c1", HEADER_ROUND: "0",
                            HEADER_METRICS: "{}",
                            HEADER_ENCODING: "q8-delta",
                            HEADER_SIGNATURE: base64.b64encode(signature).decode(),
                        },
                    ) as resp:
                        payload = await resp.json()
                        # The accurate rejection: 400 stale-round, NOT 403
                        # invalid-signature.
                        assert resp.status == 400, payload
                        assert "round" in payload["message"]
            assert server.num_updates() == 0
        finally:
            await server.stop()

    asyncio.run(main())
