"""Fused-vs-single-round timing smoke (the `make bench-smoke` target).

A miniature of bench.py's flagship measurement: time R single-round steps (one
dispatch + one block_until_ready each) against one fused R-round block (one
dispatch + one sync total), on a tiny CPU workload.  This is a PLUMBING test, not
a benchmark: it pins that the fused engine runs end to end, that its phase spans
(dispatch / host_sync) record, and that fused throughput has not regressed to
absurdity relative to the single-round path — so perf-path regressions surface in
tier-1 instead of 20 minutes into a driver bench run.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.observability import SpanTracer
from nanofed_tpu.parallel import (
    build_round_block,
    build_round_step,
    init_server_state,
    make_mesh,
    shard_client_data,
    stack_round_keys,
)
from nanofed_tpu.trainer import TrainingConfig, stack_rngs

R = 4


def test_bench_smoke_fused_vs_single_round(devices):
    m = get_model("mlp", in_features=8, hidden=16, num_classes=4)
    ds = synthetic_classification(256, 4, (8,), seed=0)
    cd = federate(ds, num_clients=8, scheme="iid", batch_size=32, seed=0)
    cfg = TrainingConfig(batch_size=32, local_epochs=1)
    strat = fedavg_strategy()
    mesh = make_mesh()
    data = shard_client_data(cd, mesh)
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    weights = compute_weights(ns)
    tracer = SpanTracer(registry=False)

    # --- single-round path: R dispatches, R host syncs --------------------
    step = build_round_step(m.apply, cfg, mesh, strat)
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    res = step(params, sos, data, weights, stack_rngs(jax.random.key(99), 8))
    jax.block_until_ready(res.params)  # compile warm-up
    params, sos = res.params, res.server_opt_state
    t0 = time.perf_counter()
    for r in range(R):
        res = step(params, sos, data, weights,
                   stack_rngs(jax.random.fold_in(jax.random.key(0), r), 8))
        params, sos = res.params, res.server_opt_state
        jax.block_until_ready(params)
    single_s = time.perf_counter() - t0
    single_loss = float(res.metrics["loss"])

    # --- fused path: one dispatch, one host sync for the same R rounds ----
    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=8, padded_clients=8,
        collect_client_detail=False,
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    mask = jnp.ones((R, 8))
    bres = block(params, sos, data, ns, stack_round_keys(1, range(R)),
                 jnp.ones(R), cohort_mask=mask)
    jax.block_until_ready(bres.params)  # compile warm-up
    t0 = time.perf_counter()
    with tracer.span("dispatch", rounds=R):
        bres = block(bres.params, bres.server_opt_state, data, ns,
                     stack_round_keys(0, range(R)), jnp.ones(R), cohort_mask=mask)
    with tracer.span("host_sync", rounds=R):
        jax.block_until_ready(bres.params)
    fused_s = time.perf_counter() - t0

    # Plumbing invariants, not perf numbers: both paths trained R real rounds...
    assert np.isfinite(single_loss)
    assert bres.metrics["loss"].shape == (R,)
    assert np.isfinite(np.asarray(bres.metrics["loss"])).all()
    assert np.asarray(bres.survivors).tolist() == [8] * R
    # ...the phase split recorded (what bench.py embeds in the flagship record)...
    phases = tracer.phase_summary()
    assert phases["dispatch"]["count"] == 1
    assert phases["host_sync"]["count"] == 1
    assert phases["dispatch"]["total_s"] + phases["host_sync"]["total_s"] >= fused_s * 0.5
    # ...and fusing R rounds did not make the hot path slower than R dispatched
    # rounds by more than noise allows (generous 2x bound: a real regression —
    # e.g. the scan re-gathering the dataset every round — blows far past it).
    assert fused_s < single_s * 2.0, (
        f"fused {R}-round block took {fused_s:.3f}s vs {single_s:.3f}s for "
        f"{R} single rounds"
    )
    print(f"\nbench-smoke: {R} single rounds {single_s:.4f}s | "
          f"fused block {fused_s:.4f}s "
          f"(dispatch {phases['dispatch']['total_s']:.4f}s, "
          f"host_sync {phases['host_sync']['total_s']:.4f}s)")
