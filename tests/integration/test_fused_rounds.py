"""Fused multi-round Coordinator integration: rounds_per_block blocks must be
invisible (same trajectory as the single-round loop), fall back transparently for
unsupported configs, and surface the dispatch/host_sync phase split.

Single-batch clients in the equivalence tests — the fused and single-round paths
are different compiled programs, and the multi-batch epoch shuffle is not
bit-stable across program structures on every jaxlib CPU backend (see
test_round_step.py for the diagnosis).
"""

import json

import jax
import numpy as np
import pytest

from nanofed_tpu.data import federate, pack_eval, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig, RoundStatus
from nanofed_tpu.trainer import TrainingConfig


@pytest.fixture(scope="module")
def mlp():
    return get_model("mlp", in_features=16, hidden=32, num_classes=4)


def _data(n=256, classes=4, feat=16, seed=0):
    return synthetic_classification(n, classes, (feat,), seed=seed)


def _make(mlp, cd, tmp_path, sub, **cfg_kwargs):
    base = tmp_path / sub
    return Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(base_dir=base, **cfg_kwargs),
        training=TrainingConfig(batch_size=16),
    )


def test_fused_blocks_match_single_round_trajectory(mlp, tmp_path, devices):
    """rounds_per_block=2 over 4 rounds (cohort mode, q=0.25) reproduces the
    single-round run: same params, same per-round metrics, same cohorts."""
    cd = federate(_data(), num_clients=16, scheme="iid", batch_size=16)
    kw = dict(num_rounds=4, participation_rate=0.25, seed=7)
    fused = _make(mlp, cd, tmp_path, "fused", rounds_per_block=2, **kw)
    assert fused._round_block is not None and fused._cohort_mode
    single = _make(mlp, cd, tmp_path, "single", **kw)
    fused_rounds = fused.run()
    single_rounds = single.run()

    for a, b in zip(jax.tree.leaves(fused.params), jax.tree.leaves(single.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    assert len(fused_rounds) == 4
    for f, s in zip(fused_rounds, single_rounds):
        assert f.round_id == s.round_id and f.status == s.status
        assert f.num_clients == s.num_clients
        np.testing.assert_allclose(
            f.agg_metrics["loss"], s.agg_metrics["loss"], rtol=1e-4
        )
        assert (
            f.agg_metrics["participating_clients"]
            == s.agg_metrics["participating_clients"]
        )
    # Per-round metrics JSON written for EVERY round, fused or not, and the fused
    # cohort detail names the same clients the single-round run sampled.
    for r in range(4):
        pf = json.loads((tmp_path / "fused" / "metrics" / f"metrics_round_{r}.json").read_text())
        ps = json.loads((tmp_path / "single" / "metrics" / f"metrics_round_{r}.json").read_text())
        assert pf["status"] == ps["status"] == "completed"
        assert pf["clients"]["client_ids"] == ps["clients"]["client_ids"]


def test_fused_cohort_padded_to_population_width_matches_single(mlp, tmp_path, devices):
    """Regression: a cohort whose padding EQUALS the population width (10 of 16
    clients pads to 16 on 8 devices) still runs the slot-ordered gather path —
    the block must take the coordinator's layout, not re-derive it from widths."""
    cd = federate(_data(), num_clients=16, scheme="iid", batch_size=16)
    kw = dict(num_rounds=2, participation_rate=0.6, seed=3)  # cohort 10 -> pad 16
    fused = _make(mlp, cd, tmp_path, "fused", rounds_per_block=2, **kw)
    assert fused._cohort_mode
    assert fused._step_clients == fused._padded_clients  # the trap this pins
    single = _make(mlp, cd, tmp_path, "single", **kw)
    fr = fused.run()
    sr = single.run()
    for a, b in zip(jax.tree.leaves(fused.params), jax.tree.leaves(single.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    for f, s in zip(fr, sr):
        assert f.num_clients == s.num_clients == 10
        np.testing.assert_allclose(
            f.agg_metrics["loss"], s.agg_metrics["loss"], rtol=1e-4
        )


def test_eval_cadence_shorter_than_block_falls_back_with_reason(mlp, tmp_path, devices):
    """eval_every < rounds_per_block can never emit a full block — that must be a
    logged fallback, not a silently dead perf knob."""
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(
            num_rounds=4, rounds_per_block=4, eval_every=2, base_dir=tmp_path,
        ),
        training=TrainingConfig(batch_size=16),
        eval_data=pack_eval(_data(n=128, seed=5), batch_size=64),
    )
    assert coord._round_block is None
    assert "eval_every" in coord._fused_fallback_reason
    rounds = coord.run()
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)
    assert "accuracy" in rounds[1].eval_metrics and "accuracy" in rounds[3].eval_metrics


def test_fused_dropout_failed_rounds_match_single(mlp, tmp_path, devices):
    """Host-sampled dropout means fused and single-round runs fail the SAME rounds;
    failed fused rounds ride the block as in-device identity rounds."""
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=64)
    kw = dict(
        num_rounds=6, participation_rate=0.5, dropout_rate=0.9,
        min_completion_rate=0.75, seed=0,
    )
    fused = _make(mlp, cd, tmp_path, "fused", rounds_per_block=3, **kw)
    single = _make(mlp, cd, tmp_path, "single", **kw)
    fr = fused.run()
    sr = single.run()
    assert [m.status for m in fr] == [m.status for m in sr]
    assert any(m.status == RoundStatus.FAILED for m in fr)
    for a, b in zip(jax.tree.leaves(fused.params), jax.tree.leaves(single.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fused_fallback_for_unsupported_configs(mlp, tmp_path, devices):
    """SCAFFOLD / robust aggregation transparently use the single-round path."""
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    scaffold = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(
            num_rounds=2, rounds_per_block=4, base_dir=tmp_path / "sc",
        ),
        training=TrainingConfig(batch_size=16),
        scaffold=True,
    )
    assert scaffold._round_block is None
    assert "SCAFFOLD" in scaffold._fused_fallback_reason
    rounds = scaffold.run()
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)

    from nanofed_tpu.aggregation import RobustAggregationConfig

    robust = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(
            num_rounds=2, rounds_per_block=4, base_dir=tmp_path / "rb",
        ),
        training=TrainingConfig(batch_size=16),
        robust=RobustAggregationConfig(trim_k=1),
    )
    assert robust._round_block is None
    assert "robust" in robust._fused_fallback_reason
    rounds = robust.run()
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)


def test_fused_tail_and_eval_boundaries(mlp, tmp_path, devices):
    """Blocks cut at eval boundaries; ragged tails run single-round; eval fires on
    schedule either way."""
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(
            num_rounds=5, rounds_per_block=2, eval_every=4, base_dir=tmp_path,
        ),
        training=TrainingConfig(batch_size=16),
        eval_data=pack_eval(_data(n=128, seed=5), batch_size=64),
    )
    rounds = coord.run()
    assert [r.round_id for r in rounds] == [0, 1, 2, 3, 4]
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)
    assert "accuracy" in rounds[3].eval_metrics  # (3+1) % 4 == 0
    assert all(rounds[i].eval_metrics == {} for i in (0, 1, 2, 4))


def test_client_metrics_every_samples_the_detail_dump(mlp, tmp_path, devices):
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(
            num_rounds=4, rounds_per_block=2, client_metrics_every=2,
            base_dir=tmp_path,
        ),
        training=TrainingConfig(batch_size=16),
    )
    coord.run()
    for r in range(4):
        payload = json.loads(
            (tmp_path / "metrics" / f"metrics_round_{r}.json").read_text()
        )
        if r % 2 == 0:
            assert len(payload["clients"]["weights"]) == 8, f"round {r}"
        else:
            assert "clients" not in payload, f"round {r}"


def test_client_metrics_never_in_single_round_path(mlp, tmp_path, devices):
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(
            num_rounds=2, client_metrics_every=0, base_dir=tmp_path,
        ),
        training=TrainingConfig(batch_size=16),
    )
    coord.run()
    for r in range(2):
        payload = json.loads(
            (tmp_path / "metrics" / f"metrics_round_{r}.json").read_text()
        )
        assert "clients" not in payload


def test_dispatch_and_host_sync_spans_in_telemetry(mlp, tmp_path, devices):
    """The fused path's phase split lands in telemetry.jsonl and the
    metrics-summary digest separates dispatch from host_sync time."""
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(num_rounds=4, rounds_per_block=2, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16),
    )
    coord.run()
    from nanofed_tpu.observability import summarize_telemetry

    summary = summarize_telemetry(tmp_path / "telemetry.jsonl")
    assert summary["phases"]["dispatch"]["count"] == 2  # one per block
    assert summary["phases"]["host_sync"]["count"] == 2
    assert summary["rounds"].get("COMPLETED") == 4
    # Round records carry the fused marker.
    fused_rounds = [
        json.loads(line)
        for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
        if json.loads(line).get("type") == "round"
    ]
    assert all(rec.get("fused") and rec["rounds_per_block"] == 2
               for rec in fused_rounds)
