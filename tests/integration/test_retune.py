"""Online retuning integration: the coordinator's swap-under-retune machinery.

The cheap legs (program rebuild + catalog re-registration, refused swaps,
cadence, CLI/config validation) run in tier-1 — rebuilding round programs is
lazy (no trace, no compile).  The full closed-loop runs (measured ranking
disagrees with AOT -> swap at a block boundary -> identical trajectory) pay
real compiles and ride the `slow` marker (the retune-smoke CI job runs this
file unfiltered).
"""

import json

import pytest

from nanofed_tpu.cli import main
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.tuning import (
    AutotuneResult,
    CandidateConfig,
    CandidateOutcome,
    TuningSpace,
)

RPB2 = CandidateConfig(None, 2, 1, 16)
RPB1 = CandidateConfig(None, 1, 1, 16)

SPACE = TuningSpace(
    client_chunks=(None,), rounds_per_blocks=(1, 2), model_shards=(1,),
    batch_sizes=(16,),
)


def make_coord(tmp_path, *, rounds_per_block=2, num_rounds=8, retune_every=0,
               eval_every=0, strict=False, **kw):
    mdl = get_model("digits_mlp")
    train = synthetic_classification(256, 10, (8, 8, 1), seed=0)
    cd = federate(train, num_clients=8, scheme="iid", batch_size=16, seed=0)
    cfg = CoordinatorConfig(
        num_rounds=num_rounds, seed=0, base_dir=tmp_path / "runs",
        rounds_per_block=rounds_per_block, retune_every=retune_every,
        eval_every=eval_every,
    )
    return Coordinator(
        model=mdl, train_data=cd, config=cfg,
        training=TrainingConfig(batch_size=16, local_epochs=1,
                                learning_rate=0.1),
        strict=strict, **kw,
    )


def table_result():
    """A two-row candidate table matching make_coord's configuration: the AOT
    model ranks the fused RPB2 program best."""
    return AutotuneResult(
        winner=RPB2,
        outcomes=[
            CandidateOutcome(RPB2, True, score=1.0, cost={}),
            CandidateOutcome(RPB1, True, score=2.0, cost={}),
        ],
        scoring_basis="test", platform="cpu", device_kind="cpu",
        num_devices=1, hbm_budget_bytes=None, budget_basis="none",
        cache_key="k" * 64,
    )


def autotuned_coord(tmp_path, *, retune_every=2, num_rounds=8, **kw):
    mdl = get_model("digits_mlp")
    train = synthetic_classification(256, 10, (8, 8, 1), seed=0)
    cd = federate(train, num_clients=8, scheme="iid", batch_size=16, seed=0)
    cfg = CoordinatorConfig(
        num_rounds=num_rounds, seed=0, base_dir=tmp_path / "runs",
        retune_every=retune_every,
    )
    return Coordinator.from_autotune(
        mdl, cd, cfg,
        TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1),
        tuning_space=SPACE, autotune_cache_dir=tmp_path / "cache", **kw,
    )


class TestRebuild:
    def test_swap_retires_the_block_program_from_the_catalog(self, tmp_path):
        """Rebuild rpb2 -> rpb1: the catalog must DROP round_block (register
        replaces, but retirement needs remove) so gauges/profiles never
        re-point at a dead program; rebuilding back re-registers it."""
        coord = make_coord(tmp_path, rounds_per_block=2)
        assert "round_block" in coord.program_catalog.names()
        old_step = coord._round_step

        coord._rebuild_round_programs(None, 1)
        assert coord.config.rounds_per_block == 1
        assert coord._round_block is None
        assert "round_block" not in coord.program_catalog.names()
        assert "round_step" in coord.program_catalog.names()
        assert coord._round_step is not old_step  # a NEW program, re-registered

        coord._rebuild_round_programs(None, 2)
        assert coord.config.rounds_per_block == 2
        assert coord._round_block is not None
        assert "round_block" in coord.program_catalog.names()

    def test_refused_swap_is_transactional(self, tmp_path):
        """A rebuild the coordinator cannot honor (eval cadence shorter than
        the proposed block) leaves EVERY program and knob untouched."""
        coord = make_coord(tmp_path, rounds_per_block=1, eval_every=1)
        step, names = coord._round_step, coord.program_catalog.names()
        with pytest.raises(NanoFedError, match="not fused-capable"):
            coord._rebuild_round_programs(None, 2)
        assert coord._round_step is step
        assert coord.config.rounds_per_block == 1
        assert coord.program_catalog.names() == names

    def test_strict_contracts_recheck_on_rebuild(self, tmp_path):
        """Strict mode re-runs the eval_shape contract check on the swapped-in
        programs — a swap must not open a strictness hole."""
        coord = make_coord(tmp_path, rounds_per_block=2, strict=True)
        coord._rebuild_round_programs(None, 1)  # must not raise
        assert coord.config.rounds_per_block == 1


class TestWiring:
    def test_enable_retuning_refuses_scaffold(self, tmp_path):
        coord = make_coord(tmp_path, rounds_per_block=1, scaffold=True)
        with pytest.raises(NanoFedError, match="SCAFFOLD"):
            coord.enable_retuning(table_result())

    def test_refused_swap_keeps_incumbent_live(self, tmp_path):
        """The retuner proposes rpb2; eval_every=1 makes the coordinator refuse
        — applied=False, the incumbent program and candidate stay live."""
        coord = make_coord(tmp_path, rounds_per_block=1, eval_every=1,
                           retune_every=2, num_rounds=100)
        rt = coord.enable_retuning(table_result(), current=RPB1)
        rt.observe(RPB1, rounds=4, walltime_s=4.0)
        rt.observe(RPB2, rounds=4, walltime_s=0.4)   # 10x faster, measured
        coord.current_round = 2
        step = coord._round_step
        coord._maybe_retune()
        assert rt.decisions[-1].swap          # the retuner DID propose it
        assert coord._retune_candidate == RPB1  # the coordinator refused it
        assert coord._round_step is step
        assert coord.config.rounds_per_block == 1

    def test_cadence_counts_from_last_retune_round(self, tmp_path):
        coord = make_coord(tmp_path, rounds_per_block=1, retune_every=3,
                           num_rounds=100)
        rt = coord.enable_retuning(table_result(), current=RPB1)
        for r in (1, 2):
            coord.current_round = r
            coord._maybe_retune()
        assert rt.decisions == []            # under the cadence: no verdicts
        coord.current_round = 3
        coord._maybe_retune()
        assert len(rt.decisions) == 1        # fires at +3
        coord.current_round = 5
        coord._maybe_retune()
        assert len(rt.decisions) == 1        # only +2 since the last verdict
        coord.current_round = 6
        coord._maybe_retune()
        assert len(rt.decisions) == 2

    def test_config_validation(self):
        with pytest.raises(ValueError, match="retune_every"):
            CoordinatorConfig(retune_every=-1)


@pytest.mark.slow
class TestClosedLoop:
    def test_swap_lands_at_a_block_boundary_and_preserves_trajectory(
        self, tmp_path,
    ):
        """The headline loop: AOT picked the fused rpb2 program; a (seeded)
        measurement says the single-round program is faster; the swap fires at
        the round-2 block boundary — never mid-block — retires round_block
        from the catalog, and the post-swap rounds reproduce the UNSWAPPED
        trajectory exactly (cohorts/keys/lr are pure functions of the round
        index; donated buffers of the old program are never re-consumed)."""
        coord = autotuned_coord(tmp_path, retune_every=2)
        assert coord.retuner is not None
        assert coord.config.rounds_per_block == 2
        winner = coord._retune_candidate
        other = RPB1 if winner.rounds_per_block != 1 else RPB2
        # Seed the alternative as decisively faster so the first verdict swaps.
        coord.retuner.observe(other, rounds=100, walltime_s=1e-4)
        rounds = coord.run()
        assert len(rounds) == 8
        swaps = [d for d in coord.retuner.decisions if d.swap]
        assert len(swaps) == 1
        assert coord._retune_candidate == other
        assert coord.config.rounds_per_block == other.rounds_per_block
        assert "round_block" not in coord.program_catalog.names()

        # The swap's telemetry record sits at a block boundary (round % 2 == 0)
        # with applied=True.
        tel = [
            json.loads(line) for line in
            (tmp_path / "runs" / "telemetry.jsonl").read_text().splitlines()
        ]
        swap_recs = [r for r in tel if r["type"] == "retune" and r["swap"]]
        assert len(swap_recs) == 1
        assert swap_recs[0]["applied"] is True
        assert swap_recs[0]["round"] % 2 == 0
        assert swap_recs[0]["new_program"].startswith("cand_")
        assert [r for r in tel if r["type"] == "retune_summary"]

        # Trajectory parity against a no-retune run of the same tuned config
        # (autotune cache hit: the reference costs zero sweep compiles).
        ref = autotuned_coord(tmp_path, retune_every=0, num_rounds=8)
        assert ref.retuner is None
        ref_rounds = ref.run()
        for got, want in zip(rounds, ref_rounds):
            assert got.agg_metrics["loss"] == pytest.approx(
                want.agg_metrics["loss"], rel=1e-6,
            )

        # The measured numbers landed back in the autotune cache entry.
        entry = json.loads(
            next((tmp_path / "cache").glob("autotune_*.json")).read_text()
        )
        assert entry["measured"]["swaps"][0]["new"] == other.to_dict()
        measured_rows = [
            c for c in entry["candidates"]
            if "measured_s_per_round" in c.get("cost", {})
        ]
        assert measured_rows

    def test_strict_mode_stays_green_across_a_swap(self, tmp_path):
        """Strict coordinators keep the transfer guard + contract checks across
        a swap: the swapped-in program dispatches without an implicit-transfer
        error and the run completes."""
        coord = autotuned_coord(tmp_path, retune_every=2, strict=True)
        winner = coord._retune_candidate
        other = RPB1 if winner.rounds_per_block != 1 else RPB2
        coord.retuner.observe(other, rounds=100, walltime_s=1e-4)
        rounds = coord.run()
        assert len(rounds) == 8
        assert any(d.swap for d in coord.retuner.decisions)
        assert all(r.status.name == "COMPLETED" for r in rounds)

    def test_cli_run_retune_every_summary_block(self, tmp_path, capsys,
                                                monkeypatch):
        monkeypatch.chdir(tmp_path)
        # The default sweep may fuse ALL the rounds into one block (no interior
        # boundary -> no verdict); the decision loop itself is pinned by the
        # other closed-loop tests — this one pins the CLI plumbing: the flag
        # reaches the coordinator, walltimes flow, the summary block lands.
        rc = main([
            "run", "--autotune", "--retune-every", "2", "--model",
            "digits_mlp", "--clients", "8", "--rounds", "8", "--epochs", "1",
            "--batch-size", "16", "--train-size", "256",
            "--out-dir", str(tmp_path / "out"),
        ])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["rounds_completed"] == 8
        retunes = summary["retunes"]
        assert set(retunes) >= {"decisions", "swaps", "hysteresis", "measured"}
        assert retunes["measured"]  # block walltimes flowed into the table


def test_cli_retune_requires_autotune(capsys):
    rc = main(["run", "--retune-every", "2", "--model", "digits_mlp"])
    assert rc == 2
    assert "--retune-every requires --autotune" in capsys.readouterr().err


def test_metrics_summary_digests_compile_and_retune_records(tmp_path):
    """`metrics-summary` turns the compile/retune telemetry streams into
    `compiles` / `retunes` blocks — pure digest, no federation."""
    from nanofed_tpu.observability import summarize_telemetry

    lines = [
        {"type": "compile", "program": "cand_chunk0_rpb2_m1_b16_h1",
         "seconds": 2.5, "cache_key": "a" * 16},
        {"type": "compile", "program": "cand_chunk0_rpb1_m1_b16_h1",
         "seconds": 1.5, "cache_key": "a" * 16},
        {"type": "retune", "round": 2, "swap": True, "applied": True,
         "old_program": "cand_chunk0_rpb2_m1_b16_h1",
         "new_program": "cand_chunk0_rpb1_m1_b16_h1",
         "measured_s_per_round": 1.0, "candidate_s_per_round": 0.25,
         "delta": 0.75, "basis": "measured", "considered": []},
        {"type": "retune", "round": 4, "swap": False, "applied": False,
         "measured_s_per_round": 0.25, "basis": "measured",
         "reason": "hysteresis", "considered": []},
        {"type": "retune_summary", "decisions": 2, "swaps": 1,
         "hysteresis": 0.05, "measured": {"cand_chunk0_rpb1_m1_b16_h1": {}},
         "cache_entry": "/tmp/cache/autotune_x.json"},
    ]
    tel = tmp_path / "telemetry.jsonl"
    tel.write_text("".join(json.dumps(r) + "\n" for r in lines))
    digest = summarize_telemetry(tel)

    compiles = digest["compiles"]
    assert compiles["count"] == 2
    assert compiles["total_s"] == pytest.approx(4.0)
    assert compiles["max_s"] == pytest.approx(2.5)
    assert compiles["by_program"]["cand_chunk0_rpb2_m1_b16_h1"] == 2.5

    retunes = digest["retunes"]
    assert retunes["decisions"] == 2
    assert retunes["swaps_proposed"] == 1
    assert retunes["swaps_applied"] == 1
    assert retunes["events"][0]["new_program"] == "cand_chunk0_rpb1_m1_b16_h1"
    assert "considered" not in retunes["events"][0]  # stays in the raw stream
    assert retunes["final"]["cache_entry"].endswith("autotune_x.json")


def test_run_experiment_refuses_retune_without_autotune(tmp_path):
    from nanofed_tpu.experiments import run_experiment

    with pytest.raises(NanoFedError, match="retune_every requires autotune"):
        run_experiment(
            model="digits_mlp", num_clients=4, num_rounds=1,
            retune_every=2, out_dir=tmp_path,
        )
