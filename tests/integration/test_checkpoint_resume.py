"""Coordinator + persistence integration: versioning every round, resume after a crash,
and fault-tolerant retry.  The reference exports its recovery module without wiring it
into the loop (SURVEY.md §5); these tests pin down the integration this framework adds."""

import jax
import numpy as np
import pytest

from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
from nanofed_tpu.persistence import (
    FileStateStore,
    ModelManager,
    SimpleRecoveryStrategy,
    run_fault_tolerant,
)
from nanofed_tpu.trainer import TrainingConfig


@pytest.fixture(scope="module")
def mlp():
    return get_model("mlp", in_features=8, hidden=16, num_classes=3)


@pytest.fixture(scope="module")
def cd():
    ds = synthetic_classification(256, 3, (8,), seed=0)
    return federate(ds, num_clients=8, scheme="iid", batch_size=16)


def _coordinator(mlp, cd, tmp_path, rounds, **kw):
    return Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(num_rounds=rounds, seed=0, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16, local_epochs=1),
        **kw,
    )


def test_model_versioned_every_round(mlp, cd, tmp_path, devices):
    mm = ModelManager(tmp_path)
    coord = _coordinator(mlp, cd, tmp_path, rounds=3, model_manager=mm)
    coord.run()
    versions = mm.list_versions()
    assert [v.round_number for v in versions] == [0, 1, 2]
    # The latest saved version is bit-identical to the live global model.
    restored, _ = mm.load_model(like=coord.params)
    for a, b in zip(jax.tree.leaves(coord.params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_matches_uninterrupted_run(mlp, cd, tmp_path, devices):
    # Uninterrupted 4-round run.
    full = _coordinator(mlp, cd, tmp_path / "full", rounds=4)
    full.run()

    # Interrupted run: 2 rounds with a store, then a fresh coordinator resumes.
    store = FileStateStore(tmp_path / "ckpt")
    first = _coordinator(mlp, cd, tmp_path / "a", rounds=2, state_store=store)
    first.run()
    resumed = _coordinator(mlp, cd, tmp_path / "b", rounds=4, state_store=store)
    assert resumed.current_round == 2
    metrics = resumed.run()
    assert [m.round_id for m in metrics] == [2, 3]

    # Deterministic seeds => resumed params equal the uninterrupted run's params.
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_resume_continues_lr_schedule_exactly(mlp, cd, tmp_path, devices):
    """The schedule is a pure function of the round index, so a resumed run must
    train its remaining rounds at the SAME decayed scales as the uninterrupted run
    — restarting the schedule at 1.0 would silently re-heat the lr mid-training."""

    def make(path, rounds, store=None):
        return Coordinator(
            model=mlp,
            train_data=cd,
            config=CoordinatorConfig(num_rounds=rounds, seed=0, base_dir=path,
                                     lr_schedule="cosine", lr_min_factor=0.2),
            training=TrainingConfig(batch_size=16, local_epochs=1),
            state_store=store,
        )

    full = make(tmp_path / "full", 4)
    full_metrics = full.run()

    # Crash mid-run: the interrupted coordinator is configured for the SAME 4-round
    # horizon (the schedule is a function of num_rounds — a 2-round config would
    # legitimately decay faster) and dies after 2 rounds.
    store = FileStateStore(tmp_path / "ckpt")
    first = make(tmp_path / "a", 4, store=store)
    gen = first.start_training()
    next(gen)
    next(gen)
    gen.close()
    resumed = make(tmp_path / "b", 4, store=store)
    assert resumed.current_round == 2
    resumed_metrics = resumed.run()

    # Rounds 2-3 of the resumed run report the rounds-2-3 scales, not a restarted
    # schedule's rounds-0-1 scales.
    full_scales = [m.agg_metrics["lr_scale"] for m in full_metrics]
    resumed_scales = [m.agg_metrics["lr_scale"] for m in resumed_metrics]
    assert resumed_scales == full_scales[2:]
    assert resumed_scales[0] < 1.0  # actually decayed, not re-heated
    # And the trained params match the uninterrupted scheduled run bit-for-bit
    # (deterministic seeds).
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_resume_preserves_privacy_accounting(mlp, cd, tmp_path, devices):
    """A resumed central-DP run must carry the pre-crash accounting events: restarting
    at ε=0 would report a budget covering only post-crash rounds while the restored
    params already embody every pre-crash noised release."""
    from nanofed_tpu.aggregation import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy import PrivacyConfig

    dp = dict(
        central_privacy=PrivacyAwareAggregationConfig(
            privacy=PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
        )
    )
    full = _coordinator(mlp, cd, tmp_path / "full", rounds=4, **dp)
    full.run()

    store = FileStateStore(tmp_path / "ckpt")
    first = _coordinator(mlp, cd, tmp_path / "a", rounds=2, state_store=store, **dp)
    first.run()
    resumed = _coordinator(mlp, cd, tmp_path / "b", rounds=4, state_store=store, **dp)
    assert resumed.current_round == 2
    # Pre-crash events restored before any new round runs.
    assert resumed.privacy_accountant.state_dict() == first.privacy_accountant.state_dict()
    resumed.run()
    # Accounting events are deterministic (σ, q, count) — the resumed total must equal
    # the uninterrupted run's cumulative spend, not just the post-crash tail.
    assert resumed.privacy_spent.epsilon_spent == pytest.approx(
        full.privacy_spent.epsilon_spent
    )
    assert len(resumed.privacy_accountant.state_dict()["events"]) == len(
        full.privacy_accountant.state_dict()["events"]
    )


def test_run_fault_tolerant_retries_through_crash(mlp, cd, tmp_path, devices):
    store = FileStateStore(tmp_path / "ckpt")
    crashed = {"done": False}

    def make():
        coord = _coordinator(mlp, cd, tmp_path, rounds=3, state_store=store)
        if not crashed["done"]:
            # Inject a recoverable failure after round 1's checkpoint.
            def boom(metrics):
                if metrics.round_id == 1:
                    crashed["done"] = True
                    raise ConnectionError("simulated network partition")

            coord.on_round_end = boom
        return coord

    history = run_fault_tolerant(make, SimpleRecoveryStrategy(max_retries=2))
    assert crashed["done"]
    assert [m.round_id for m in history] == [2]  # resumed past checkpointed rounds 0-1
    assert store.restore_latest().round_number == 2


def test_run_fault_tolerant_propagates_unrecoverable(mlp, cd, tmp_path, devices):
    def make():
        coord = _coordinator(mlp, cd, tmp_path, rounds=2)

        def boom(metrics):
            raise ValueError("deterministic bug")

        coord.on_round_end = boom
        return coord

    with pytest.raises(ValueError):
        run_fault_tolerant(make)
