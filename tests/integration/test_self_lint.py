"""Self-lint gate: the shipped package must be fedlint-clean.

This is the integration contract of the analysis subsystem — every FED001-FED006
invariant holds across ``nanofed_tpu/`` with zero unsuppressed findings, and
every suppression that makes that true carries a reason (reasonless ones are
FED000 findings, which also fail here)."""

from __future__ import annotations

import re
from pathlib import Path

from nanofed_tpu.analysis import lint_paths, render_text

PACKAGE = Path(__file__).resolve().parents[2] / "nanofed_tpu"


def test_package_is_fedlint_clean():
    diagnostics = lint_paths([PACKAGE])
    assert diagnostics == [], "\n" + render_text(diagnostics)


def test_suppressions_exist_and_carry_reasons():
    """The clean result above must come from DOCUMENTED intentional sites, not
    from the rules never firing: the tree carries suppressions (the coordinator's
    block-boundary syncs, the un-donated eval jits, the lock-held helper) and
    each one states its reason."""
    pattern = re.compile(r"#\s*fedlint:\s*disable(?:-file)?=([A-Z0-9,\s]+?)\s*\(([^)]+)\)")
    found: list[tuple[str, str, str]] = []
    for path in sorted(PACKAGE.rglob("*.py")):
        for line in path.read_text().splitlines():
            m = pattern.search(line)
            if m:
                found.append((path.name, m.group(1).strip(), m.group(2).strip()))
    codes = {code for _, code, _ in found}
    assert {"FED001", "FED004", "FED005"} <= codes, found
    for fname, code, reason in found:
        # A real reason, not a placeholder: the linter only checks non-empty,
        # the test holds the bar a little higher.
        assert len(reason) >= 15, f"{fname}: suppression of {code} has a token reason"


def test_rule_catalogue_matches_docs():
    """Every rule in the engine is documented in docs/static-analysis.md and
    vice versa — the catalogue cannot silently drift from the docs page."""
    from nanofed_tpu.analysis import RULES

    doc = (PACKAGE.parent / "docs" / "static-analysis.md").read_text()
    for code in RULES:
        assert f"### {code}" in doc, f"{code} missing from docs/static-analysis.md"
    documented = set(re.findall(r"^### (FED\d{3})", doc, re.MULTILINE))
    assert documented == set(RULES)
