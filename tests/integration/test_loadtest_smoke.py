"""Loadtest smoke (ISSUE 7 satellite): the swarm harness end to end, tier-1.

A ~200-client swarm on a ``VirtualClock`` (arrival offsets and retry backoffs
in virtual time — milliseconds of real time, deterministic seeds) drives BOTH
serving paths; the artifact must parse, every latency percentile must be
finite, no submit may be lost outright, and ``metrics-summary`` must digest
the ``loadtest`` telemetry records.  This is what ``make loadtest-smoke`` and
the CI job run."""

import json
import math
from pathlib import Path

from nanofed_tpu.loadgen import run_loadtest_comparison
from nanofed_tpu.observability.telemetry import summarize_telemetry

SWARM_CLIENTS = 200


def test_loadtest_smoke(tmp_path):
    artifact = run_loadtest_comparison(
        modes=("per-submit", "ingest"),
        out_dir=tmp_path,
        telemetry_dir=tmp_path,
        tag="smoke",
        clients=SWARM_CLIENTS,
        async_buffer_k=25,
        arrival="poisson",
        arrival_rate=5000.0,
        max_inflight=128,
        ingest_capacity=128,
        round_timeout_s=60.0,
        virtual_clock=True,
        seed=0,
    )
    # The artifact on disk parses and is the same document we got back.
    path = Path(artifact["artifact_path"])
    assert path.name.startswith("loadtest_")
    parsed = json.loads(path.read_text())
    assert parsed["record_type"] == "loadtest"
    assert set(parsed["modes"]) == {"per-submit", "ingest"}

    for mode, rec in parsed["modes"].items():
        lat = rec["submit_latency_s"]
        assert lat["count"] > 0, mode
        assert lat["p99_s"] is not None and math.isfinite(lat["p99_s"]), mode
        assert lat["p50_s"] <= lat["p99_s"] <= lat["max_s"], mode
        # Every logical submit resolved: accepted (or deduped) — 429s were
        # retried through, nothing was lost outright.
        assert rec["failed_submits"] == 0, mode
        assert rec["accepted"] + rec["duplicates"] >= SWARM_CLIENTS, mode
        assert rec["aggregations_completed"] > 0, mode
        assert rec["rounds_per_sec"] is not None and rec["rounds_per_sec"] > 0
        assert rec["clock"] == "virtual"
    # The batched path's extra surfaces are recorded.
    ingest_rec = parsed["modes"]["ingest"]
    assert ingest_rec["decode_pool"] is not None
    assert ingest_rec["ingest"]["capacity"] == 128

    # metrics-summary digests the loadtest records like program_profile ones.
    summary = summarize_telemetry(tmp_path / "telemetry.jsonl")
    assert set(summary["loadtests"]) == {"per-submit", "ingest"}
    for mode, digest in summary["loadtests"].items():
        assert math.isfinite(digest["p99_s"]), mode
        assert digest["clients"] == SWARM_CLIENTS
