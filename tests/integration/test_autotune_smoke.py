"""Autotune smoke (the `make autotune-smoke` / CI job): a tiny MLP space swept on
CPU must pick a winner via AOT analysis alone, emit a parseable ranked-table
artifact whose scoring basis is stated, show the fused q8 epilogue's measured
bytes-accessed reduction in the catalog's cost table, and hit the sweep cache on
the second invocation with ZERO compiles."""

import json

import pytest

from nanofed_tpu.cli import main
from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.tuning import (
    PopulationSpec,
    TuningSpace,
    autotune,
    profile_aggregation_epilogues,
)

SPACE = TuningSpace(
    client_chunks=(None, 1),
    rounds_per_blocks=(1, 4),
    model_shards=(1, 2),
    batch_sizes=(16, 32),
)


def _sweep(tmp_path, **kwargs):
    return autotune(
        get_model("digits_mlp"),
        PopulationSpec(num_clients=8, capacity=32, sample_shape=(8, 8, 1)),
        TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1),
        num_rounds=8, space=SPACE,
        cache_dir=tmp_path / "cache", out_dir=tmp_path / "runs",
        include_epilogues=False, **kwargs,
    )


# Tier-1 budget relief (PR 13): the four compile-heavy sweeps below are
# `slow` — they cost ~75s of the 870s tier-1 budget and are exercised
# end-to-end by the dedicated autotune-smoke CI job (`make autotune-smoke`
# runs this whole file with no marker filter).  The cheap assertions
# (epilogue bytes drop, pinned-knob refusal) stay in tier-1.
@pytest.mark.slow
def test_autotune_smoke_winner_artifact_and_cache(tmp_path):
    first = _sweep(tmp_path)

    # A winner was chosen by AOT analysis alone (nothing ran: the sweep's only
    # jax work is lower+compile on ShapeDtypeStruct arguments).
    assert first.winner is not None
    assert first.compiles == len(SPACE.candidates())

    # The artifact parses and carries the FULL ranked table with its basis.
    artifact = json.loads((tmp_path / "runs").glob("autotune_*.json")
                          .__next__().read_text())
    assert artifact["winner"] == first.winner.to_dict()
    assert len(artifact["candidates"]) == len(SPACE.candidates())
    assert "bytes-accessed ordering" in artifact["scoring_basis"]  # CPU basis
    assert artifact["tie_break"]
    feasible_scores = [
        c["score"] for c in artifact["candidates"] if c["feasible"]
    ]
    assert feasible_scores == sorted(feasible_scores)

    # Second invocation: cache hit skips ALL compiles, same winner.
    second = _sweep(tmp_path)
    assert second.cache_hit
    assert second.compiles == 0
    assert second.winner == first.winner


def test_fused_epilogue_bytes_drop_in_catalog_cost_table(tmp_path):
    """The acceptance bar: the fused Pallas q8/topk aggregation epilogue must
    show a MEASURED bytes-accessed reduction vs the separate dequant-then-reduce
    programs, in the program catalog's own cost table — on this CPU the fused
    kernel runs under the Pallas interpreter (whose accounting inflates it), so
    a positive reduction here is a conservative floor on the TPU number."""
    from nanofed_tpu.observability.profiling import ProgramCatalog

    catalog = ProgramCatalog()
    record = profile_aggregation_epilogues(
        flat_size=65_536, clients=64, catalog=catalog
    )
    q8 = record["q8"]
    assert q8["bytes_accessed_reduction_pct"] > 0, q8
    assert q8["fused_bytes_accessed"] < q8["unfused_bytes_accessed"]
    # The comparison is drawn from CATALOG reports, and the basis is stated.
    assert catalog.report("q8_epilogue_fused") is not None
    assert catalog.report("q8_epilogue_dequant") is not None
    assert "cost_analysis" in record["basis"]
    # The validated epilogue is also catalogued; its reduction only shows on
    # real TPU kernels, and the basis says so rather than fabricating one.
    assert catalog.report("validated_epilogue_fused") is not None
    assert "interpreter" in record["basis"]


@pytest.mark.slow
def test_profile_sweep_cli_prints_table_and_epilogues(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)  # .jax_cache + runs/ land in the tmp dir
    rc = main([
        "profile", "--sweep", "--model", "digits_mlp", "--clients", "8",
        "--batch-size", "16", "--train-size", "256",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "winner:" in out
    assert "scoring basis:" in out
    assert "q8 epilogue:" in out
    assert "reduction" in out
    assert (tmp_path / "runs").glob("autotune_*.json").__next__().exists()


@pytest.mark.slow
def test_run_autotune_records_tuned_config(tmp_path, capsys, monkeypatch):
    """`run --autotune` end to end: the tuner picks the config (zero round
    executions before the first real round — the sweep lowers candidates with
    abstract arguments), the run completes, and the summary carries
    tuned_config with provenance."""
    monkeypatch.chdir(tmp_path)
    rc = main([
        "run", "--autotune", "--model", "digits_mlp", "--clients", "8",
        "--rounds", "4", "--epochs", "1", "--batch-size", "16",
        "--train-size", "256", "--out-dir", str(tmp_path / "out"),
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["rounds_completed"] == 4
    tuned = summary["tuned_config"]
    assert tuned["used"] == "tuned"
    assert "scoring_basis" in tuned
    # The winner's knobs are the coordinator's realized configuration.
    assert set(tuned) >= {"client_chunk", "rounds_per_block", "model_shards",
                          "batch_size"}
    # The ranked table landed under the run's out dir.
    assert list((tmp_path / "out").glob("autotune_*.json"))


def test_run_autotune_refuses_pinned_knobs(capsys):
    rc = main([
        "run", "--autotune", "--rounds-per-block", "4",
        "--model", "digits_mlp",
    ])
    assert rc == 2
    assert "--autotune cannot be combined" in capsys.readouterr().err


@pytest.mark.slow
def test_metrics_summary_digests_autotune_records(tmp_path, capsys):
    telemetry_dir = tmp_path / "tel"
    from nanofed_tpu.observability import RunTelemetry

    tel = RunTelemetry(telemetry_dir)
    res = _sweep(tmp_path, telemetry=tel)
    tel.close()
    rc = main(["metrics-summary", str(telemetry_dir)])
    assert rc == 0
    digest = json.loads(capsys.readouterr().out)
    block = digest["autotunes"]
    (entry,) = block.values()
    assert entry["winner"] == res.winner.to_dict()
    assert "bytes-accessed ordering" in entry["scoring_basis"]
    assert entry["candidates_total"] == len(SPACE.candidates())
