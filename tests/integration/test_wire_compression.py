"""q8-delta wire compression: the quantized-update codec and its HTTP round trip.

The reference ships weights as JSON float lists (~9x inflation,
``nanofed/communication/http/server.py:140-149``); this framework's baseline wire format
is already binary npz, and ``q8-delta`` cuts the client->server payload a further ~4x by
shipping the stochastically-rounded int8 round delta (QSGD-style, Alistarh et al. 2017).
These tests pin the codec's three load-bearing claims — bounded error, unbiasedness,
strict template validation — and the wire contract: the server reconstructs EXACTLY what
the client signed, so signature enforcement composes with compression.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    decode_delta_q8,
    decode_delta_topk8,
    encode_delta_q8,
    encode_delta_topk8,
    encode_params,
)
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.models import get_model

PORT = 18632


def _delta_tree(seed=0, scale=0.01):
    rng = np.random.default_rng(seed)
    return {
        "fc1": {"kernel": rng.normal(0, scale, (64, 32)).astype(np.float32),
                "bias": rng.normal(0, scale, (32,)).astype(np.float32)},
        "head": rng.normal(0, scale * 3, (32, 10)).astype(np.float32),
    }


# ---------------------------------------------------------------------------
# Codec
# ---------------------------------------------------------------------------


def test_q8_roundtrip_error_is_bounded_by_one_step():
    """Stochastic rounding moves each value at most one quantization step, so the
    dequantized leaf differs from the original by <= its absmax/127 scale."""
    delta = _delta_tree()
    out = decode_delta_q8(encode_delta_q8(delta, seed=7), like=delta)
    for (x, y) in zip(jax.tree.leaves(delta), jax.tree.leaves(out)):
        scale = np.abs(x).max() / 127.0
        assert np.abs(y - x).max() <= scale * (1 + 1e-6)


def test_q8_is_unbiased():
    """E[dequantized] = original: the rounding noise must average OUT across clients
    (FedAvg's mean), not accumulate as a bias."""
    delta = {"w": np.asarray([0.00731, -0.0042, 0.0099, 0.00011], np.float32)}
    draws = np.stack([
        decode_delta_q8(encode_delta_q8(delta, seed=s), like=delta)["w"]
        for s in range(400)
    ])
    scale = np.abs(delta["w"]).max() / 127.0
    # Mean-of-400 standard error is scale/sqrt(400); 4 sigma keeps this deterministic
    # enough while still catching a deterministic-rounding (biased) regression.
    np.testing.assert_allclose(
        draws.mean(axis=0), delta["w"], atol=4 * scale / np.sqrt(400)
    )


def test_q8_zero_leaves_and_size():
    delta = _delta_tree()
    delta["zeros"] = np.zeros((128,), np.float32)
    out = decode_delta_q8(encode_delta_q8(delta, seed=0), like=delta)
    np.testing.assert_array_equal(out["zeros"], 0.0)
    # The point of the codec: ~4x fewer bytes than the float32 npz of the same tree.
    # Measured on a model-sized leaf — tiny trees are dominated by per-member zip
    # overhead (q8 stores two entries per leaf), which washes out at real sizes.
    big = {"w": np.random.default_rng(0).normal(0, 0.01, (256, 256)).astype(np.float32)}
    assert len(encode_delta_q8(big, seed=0)) < 0.30 * len(encode_params(big))


def test_q8_bfloat16_template_roundtrips():
    """Leaf dtypes are NOT on the wire — the decoder casts to the TEMPLATE's dtype,
    so a bfloat16 model federates over the identical payload format."""
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    delta = {"w": np.asarray([0.01, -0.005, 0.002], np.float32).astype(bf16)}
    out = decode_delta_q8(encode_delta_q8(delta, seed=0), like=delta)
    assert out["w"].dtype == bf16
    scale = float(np.abs(delta["w"].astype(np.float32)).max()) / 127.0
    # One quantization step plus one bf16 rounding step of headroom.
    np.testing.assert_allclose(
        out["w"].astype(np.float32), delta["w"].astype(np.float32),
        atol=scale + 0.01 * scale + 1e-4,
    )


def test_q8_refuses_wrong_template_and_mixed_payloads():
    delta = _delta_tree()
    payload = encode_delta_q8(delta, seed=0)
    bad = {"fc1": {"kernel": np.zeros((64, 32), np.float32),
                   "bias": np.zeros((999,), np.float32)},
           "head": np.zeros((32, 10), np.float32)}
    with pytest.raises(NanoFedError, match="shape mismatch"):
        decode_delta_q8(payload, like=bad)
    # A plain npz payload fed to the q8 decoder must be refused outright, not
    # misinterpreted as quantized data.
    with pytest.raises(NanoFedError, match="non-q8 entry"):
        decode_delta_q8(encode_params(delta), like=delta)


# ---------------------------------------------------------------------------
# topk8: sparsification + error feedback
# ---------------------------------------------------------------------------


def test_topk8_keeps_the_largest_coordinates_exactly():
    """The selected coordinates round-trip within one quantization step; every
    unselected coordinate decodes to exactly zero; selection is by magnitude."""
    delta = {"w": np.asarray([0.5, -0.001, 0.0, 0.3, -0.7, 0.002], np.float32)}
    out = decode_delta_topk8(encode_delta_topk8(delta, fraction=0.5, seed=0),
                             like=delta)
    w = out["w"]
    scale = 0.7 / 127.0
    for i in (0, 3, 4):  # the three largest magnitudes
        assert abs(w[i] - delta["w"][i]) <= scale * (1 + 1e-6)
    for i in (1, 2, 5):
        assert w[i] == 0.0


def test_topk8_payload_is_much_smaller():
    big = {"w": np.random.default_rng(0).normal(0, 0.01, (512, 256)).astype(np.float32)}
    sparse = encode_delta_topk8(big, fraction=0.05, seed=0)
    # ~20x fewer coordinates; indices cost u32 each, so expect >6x vs full npz.
    assert len(sparse) < len(encode_params(big)) / 6


def test_topk8_refuses_out_of_range_indices_and_bad_fraction():
    delta = {"w": np.zeros((8,), np.float32)}
    payload = encode_delta_topk8({"w": np.ones((16,), np.float32)}, fraction=0.5)
    with pytest.raises(NanoFedError, match="out of range"):
        decode_delta_topk8(payload, like=delta)
    with pytest.raises(NanoFedError, match="fraction"):
        encode_delta_topk8(delta, fraction=0.0)


def test_error_feedback_ships_every_coordinate_eventually():
    """The point of the residual: a coordinate too small to make any single round's
    top-k still reaches the server once its accumulated residual grows past the
    per-round winners.  A coordinate with |x| ships roughly every
    (sum|x| / k) / |x| rounds in steady state — the config below puts the small
    coordinate's period at ~20 rounds, well inside the 40 simulated.  Without the
    residual it would NEVER ship (it is never in any single round's top-k)."""
    rng = np.random.default_rng(0)
    true_delta = rng.uniform(0.5, 1.5, (64,)).astype(np.float32)
    true_delta[7] = 0.2  # too small for any single round's top 25%
    rounds, fraction = 40, 0.25
    residual = np.zeros_like(true_delta)
    total_received = np.zeros_like(true_delta)
    no_ef_received = np.zeros_like(true_delta)
    for r in range(rounds):
        d = {"w": true_delta + residual}
        sent = decode_delta_topk8(
            encode_delta_topk8(d, fraction=fraction, seed=r), like=d
        )["w"]
        residual = d["w"] - sent
        total_received += sent
        no_ef_received += decode_delta_topk8(
            encode_delta_topk8({"w": true_delta}, fraction=fraction, seed=r),
            like=d,
        )["w"]
    assert no_ef_received[7] == 0.0  # never top-k on its own — the bias is real
    assert total_received[7] > 0.0  # the residual pushed it through
    # And the time-averaged view tracks the true delta (residuals are bounded by
    # the steady-state shipping threshold, so the error shrinks like 1/rounds).
    np.testing.assert_allclose(total_received / rounds, true_delta, atol=0.35)


# ---------------------------------------------------------------------------
# Wire
# ---------------------------------------------------------------------------


def test_q8_submit_requires_a_fetched_base():
    async def main():
        async with HTTPClient("http://127.0.0.1:1", "c1", timeout_s=5,
                              update_encoding="q8-delta") as c:
            with pytest.raises(NanoFedError, match="fetch_global_model"):
                await c.submit_update({"w": np.zeros((2,), np.float32)}, {})

    asyncio.run(main())


def test_q8_round_trip_over_http_reconstructs_within_quantization_error():
    model = get_model("linear", in_features=8, num_classes=4)
    params = model.init(jax.random.key(0))
    trained = jax.tree.map(lambda p: p + 0.01 * jnp.ones_like(p), params)

    async def main():
        server = HTTPServer(port=PORT)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(f"http://127.0.0.1:{PORT}", "c1", timeout_s=10,
                                  update_encoding="q8-delta") as c:
                fetched, _, _ = await c.fetch_global_model(like=params)
                assert await c.submit_update(trained, {"loss": 0.1})
            assert server.num_updates() == 1
            (update,) = await server.drain_updates()
            for got, want, base in zip(
                jax.tree.leaves(update.params),
                jax.tree.leaves(trained),
                jax.tree.leaves(params),
            ):
                scale = float(np.abs(np.asarray(want) - np.asarray(base)).max()) / 127.0
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=scale * (1 + 1e-6)
                )
        finally:
            await server.stop()

    asyncio.run(main())


def test_q8_composes_with_signature_enforcement():
    """The client signs the server's exact reconstruction (base + dequantized delta),
    so require_signatures accepts a compressed update from the right key and still
    rejects an impostor."""
    pytest.importorskip("cryptography")
    from nanofed_tpu.security import SecurityManager

    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    trained = jax.tree.map(lambda p: p + 0.02 * jnp.ones_like(p), params)
    signer = SecurityManager(key_size=2048)
    impostor = SecurityManager(key_size=2048)
    port = PORT + 1

    async def main():
        server = HTTPServer(
            port=port,
            client_keys={"c1": signer.get_public_key()},
            require_signatures=True,
        )
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            url = f"http://127.0.0.1:{port}"
            async with HTTPClient(url, "c1", timeout_s=10, security_manager=impostor,
                                  update_encoding="q8-delta") as c:
                await c.fetch_global_model(like=params)
                assert not await c.submit_update(trained, {"loss": 0.1})
            assert server.num_updates() == 0
            async with HTTPClient(url, "c1", timeout_s=10, security_manager=signer,
                                  update_encoding="q8-delta") as c:
                await c.fetch_global_model(like=params)
                assert await c.submit_update(trained, {"loss": 0.1})
            assert server.num_updates() == 1
        finally:
            await server.stop()

    asyncio.run(main())


def test_topk8_over_http_with_error_feedback_state():
    """Two topk8 rounds through the real server: reconstruction lands only on the
    shipped coordinates, and the client's residual carries between submits."""
    model = get_model("linear", in_features=8, num_classes=4)
    params = model.init(jax.random.key(0))
    trained = jax.tree.map(lambda p: p + 0.01 * jnp.ones_like(p), params)
    port = PORT + 3

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                                  update_encoding="topk8-delta",
                                  topk_fraction=0.25) as c:
                await c.fetch_global_model(like=params)
                assert await c.submit_update(trained, {"loss": 0.1})
                assert c._residual is not None
                res1 = sum(float(np.abs(np.asarray(x)).sum())
                           for x in jax.tree.leaves(c._residual))
                assert res1 > 0  # 75% of coordinates went un-sent
                (u1,) = await server.drain_updates()
                # Round 1: same model resubmitted — the residual should push
                # previously-dropped coordinates through.
                await server.publish_model(params, round_number=1)
                await c.fetch_global_model(like=params)
                assert await c.submit_update(trained, {"loss": 0.1})
                (u2,) = await server.drain_updates()
                got1 = np.concatenate([np.asarray(x).ravel()
                                       for x in jax.tree.leaves(u1.params)])
                got2 = np.concatenate([np.asarray(x).ravel()
                                       for x in jax.tree.leaves(u2.params)])
                base = np.concatenate([np.asarray(x).ravel()
                                       for x in jax.tree.leaves(params)])
                want = np.concatenate([np.asarray(x).ravel()
                                       for x in jax.tree.leaves(trained)])
                # Cumulative view converges toward the true update direction.
                err1 = np.abs((got1 - base) - (want - base)).sum()
                err2 = np.abs(((got1 - base) + (got2 - base)) / 2
                              - (want - base)).sum()
                assert err2 < err1
        finally:
            await server.stop()

    asyncio.run(main())


def test_rejected_topk8_submit_folds_delta_into_residual():
    """True error feedback across a dropped round: a REJECTED submit applied
    nothing server-side, so the WHOLE combined delta (this round's progress + the
    accumulated tail) folds into the accumulator — the mass rides the next
    accepted delta instead of vanishing from both sides.  Retries are idempotent:
    a second rejection with the same params must not grow the accumulator (the
    fold's base is pinned in ``_pending_base``)."""
    model = get_model("linear", in_features=8, num_classes=4)
    params = model.init(jax.random.key(0))
    trained = jax.tree.map(lambda p: p + 0.01 * jnp.ones_like(p), params)
    port = PORT + 4

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            async with HTTPClient(f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                                  update_encoding="topk8-delta",
                                  topk_fraction=0.25) as c:
                await c.fetch_global_model(like=params)
                # Stale round: server rejects -> the full delta is now accumulated.
                c.current_round = 7
                assert not await c.submit_update(trained, {"loss": 0.1})
                full_delta = jax.tree.map(
                    lambda p, g: np.asarray(p, np.float32)
                    - np.asarray(g, np.float32),
                    trained, params,
                )
                for want, got in zip(jax.tree.leaves(full_delta),
                                     jax.tree.leaves(c._residual)):
                    np.testing.assert_allclose(np.asarray(got), want, atol=1e-7)
                # Idempotent retry: a SECOND rejection with the same params adds
                # nothing (delta is measured from the pinned fold base, = zero).
                assert not await c.submit_update(trained, {"loss": 0.1})
                for want, got in zip(jax.tree.leaves(full_delta),
                                     jax.tree.leaves(c._residual)):
                    np.testing.assert_allclose(np.asarray(got), want, atol=1e-7)
                # Accepted retry at the right round: conservation — what the server
                # applied plus what stayed accumulated is exactly ONE delta.
                c.current_round = 0
                assert await c.submit_update(trained, {"loss": 0.1})
                (update,) = await server.drain_updates()
                for got, base, res, want in zip(
                    jax.tree.leaves(update.params), jax.tree.leaves(params),
                    jax.tree.leaves(c._residual), jax.tree.leaves(full_delta),
                ):
                    sent = np.asarray(got, np.float32) - np.asarray(base, np.float32)
                    np.testing.assert_allclose(sent + np.asarray(res), want,
                                               atol=1e-3)
        finally:
            await server.stop()

    asyncio.run(main())


def test_unknown_encoding_header_rejected():
    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    port = PORT + 2

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            import aiohttp

            from nanofed_tpu.communication.http_server import (
                HEADER_CLIENT,
                HEADER_ENCODING,
                HEADER_ROUND,
            )

            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{port}/update",
                    data=b"garbage",
                    headers={HEADER_CLIENT: "c1", HEADER_ROUND: "0",
                             HEADER_ENCODING: "zstd-exotic"},
                ) as resp:
                    assert resp.status == 400
                    assert "unknown encoding" in (await resp.json())["message"]
                # q8-delta on a SecAgg MASKED payload: refused, not silently
                # interpreted as a masked uint32 vector.
                from nanofed_tpu.communication.http_server import HEADER_SECAGG

                async with s.post(
                    f"http://127.0.0.1:{port}/update",
                    data=b"garbage",
                    headers={HEADER_CLIENT: "c1", HEADER_ROUND: "0",
                             HEADER_SECAGG: "masked",
                             HEADER_ENCODING: "q8-delta"},
                ) as resp:
                    assert resp.status == 400
                    assert "cannot combine" in (await resp.json())["message"]
            assert server.num_updates() == 0
        finally:
            await server.stop()

    asyncio.run(main())
