"""SCAFFOLD (Karimireddy et al. 2020): control-variate math, cohort equivalence,
persistence, and the non-IID win itself.

The reference framework has no drift-corrected algorithm (its trainer surface is plain
SGD + DP-SGD, ``nanofed/trainer/``); SCAFFOLD is new capability, so these tests pin the
claims its docstrings make rather than parity with reference behavior: the option-II
control update IS the mean local gradient, zero controls ARE FedAvg, cohort gathering
IS invisible, controls survive checkpoint/resume, and the correction actually closes
the client-drift gap FedAvg suffers on pathological label skew.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.data import federate, pack_eval, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
from nanofed_tpu.persistence import FileStateStore
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.local import make_grad_fn
from nanofed_tpu.trainer.scaffold import make_scaffold_local_fit


@pytest.fixture(scope="module")
def mlp():
    return get_model("mlp", in_features=16, hidden=32, num_classes=4)


def _data(n=1024, classes=4, feat=16, seed=0):
    return synthetic_classification(n, classes, (feat,), seed=seed)


# ---------------------------------------------------------------------------
# The control-update math
# ---------------------------------------------------------------------------


def test_one_step_control_update_recovers_the_gradient(mlp, devices):
    """Option II with K=1: y = x - eta*(g + c - c_i), so dc_i = -c + (x-y)/eta
    = g - c_i, i.e. the client's NEW control c_i+ = c_i + dc_i is exactly the
    gradient at x.  This is the identity the whole algorithm rests on."""
    cd = federate(_data(n=32), num_clients=1, scheme="iid", batch_size=32)
    one = jax.tree.map(lambda x: jnp.asarray(x[0]), cd)
    params = mlp.init(jax.random.key(0))
    rng = jax.random.key(1)

    fit = make_scaffold_local_fit(
        mlp.apply, TrainingConfig(batch_size=32, local_epochs=1, learning_rate=0.1)
    )
    # Non-trivial controls so the test exercises the correction, not just zeros.
    c_global = jax.tree.map(lambda p: jnp.full_like(p, 0.05), params)
    c_client = jax.tree.map(lambda p: jnp.full_like(p, -0.03), params)
    result = fit(params, one, rng, c_global, c_client)

    # The single batch covers the whole (permuted) dataset, and the masked-mean loss
    # is permutation-invariant, so the expected gradient is computable directly.
    grads, _ = make_grad_fn(mlp.apply)(params, one.x, one.y, one.mask, rng)
    for dc, g, ci in zip(
        jax.tree.leaves(result.delta_c),
        jax.tree.leaves(grads),
        jax.tree.leaves(c_client),
    ):
        np.testing.assert_allclose(
            np.asarray(dc), np.asarray(g - ci), rtol=1e-5, atol=1e-6
        )


def test_all_padding_client_moves_nothing(mlp, devices):
    """A weight-0 cohort slot trains on pure padding: its params must not move and
    its control delta must be exactly zero (K=0 — the divide-by-steps guard)."""
    cd = federate(_data(n=64), num_clients=2, scheme="iid", batch_size=16)
    empty = jax.tree.map(lambda x: jnp.zeros_like(x[0]), cd)
    params = mlp.init(jax.random.key(0))
    fit = make_scaffold_local_fit(
        mlp.apply, TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.1)
    )
    c = jax.tree.map(lambda p: jnp.full_like(p, 0.05), params)
    result = fit(params, empty, jax.random.key(1), c, c)
    for p0, p1 in zip(jax.tree.leaves(params), jax.tree.leaves(result.params)):
        np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    for dc in jax.tree.leaves(result.delta_c):
        np.testing.assert_array_equal(np.asarray(dc), np.zeros_like(np.asarray(dc)))


def test_refuses_momentum_weight_decay_and_prox():
    """The option-II estimate equals the mean local gradient only for plain SGD;
    momentum/weight-decay/FedProx must be refused loudly, not silently biased."""
    apply = lambda p, x, **kw: x
    with pytest.raises(ValueError, match="plain SGD"):
        make_scaffold_local_fit(apply, TrainingConfig(momentum=0.9))
    with pytest.raises(ValueError, match="plain SGD"):
        make_scaffold_local_fit(apply, TrainingConfig(weight_decay=1e-4))
    with pytest.raises(ValueError, match="drift remedy"):
        make_scaffold_local_fit(apply, TrainingConfig(prox_mu=0.1))


# ---------------------------------------------------------------------------
# Round semantics
# ---------------------------------------------------------------------------


def _coord(mlp, cd, tmp_path, scaffold, rounds=1, epochs=2, **cfg_kw):
    return Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(
            num_rounds=rounds, seed=0, base_dir=tmp_path, save_metrics=False, **cfg_kw
        ),
        training=TrainingConfig(batch_size=32, local_epochs=epochs, learning_rate=0.1),
        scaffold=scaffold,
    )


def test_zero_controls_first_round_is_fedavg(mlp, tmp_path, devices):
    """Round 1 with all-zero controls applies a zero correction, and with equal-sized
    clients the uniform participant mean equals the sample-weighted mean — the first
    SCAFFOLD round must reproduce FedAvg's released params."""
    cd = federate(_data(n=256), num_clients=8, scheme="iid", batch_size=32)
    a = _coord(mlp, cd, tmp_path / "a", scaffold=False)
    b = _coord(mlp, cd, tmp_path / "b", scaffold=True)
    a.run()
    b.run()
    for pa, pb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), rtol=1e-6, atol=1e-7)


def test_cohort_scaffold_equals_forced_full_round(mlp, tmp_path, devices):
    """Cohort gathering must be invisible for SCAFFOLD exactly as for FedAvg — and it
    has MORE to get right here: control rows are gathered alongside data rows and the
    deltas scatter-added back.  Same seed => identical params, server control, and
    population control stack as the full-N masked path.

    Single-batch clients for the same reason as
    ``test_cohort_gather_equals_full_mask_round``: gathered vs full-N are different
    compiled programs, and the multi-batch epoch shuffle is not bit-stable across
    program structures on every jaxlib CPU backend (observed on 0.4.36)."""
    cd = federate(_data(n=256), num_clients=16, scheme="iid", batch_size=16)

    def make():
        return Coordinator(
            model=mlp,
            train_data=cd,
            config=CoordinatorConfig(
                num_rounds=3, participation_rate=0.25, seed=5, base_dir=tmp_path,
                save_metrics=False,
            ),
            training=TrainingConfig(batch_size=16, learning_rate=0.1),
            scaffold=True,
        )

    gathered = make()
    assert gathered._cohort_mode
    full = make()
    full._cohort_mode = False
    full._step_clients = full._padded_clients
    gathered.run()
    full.run()
    for name, ga, fu in (
        ("params", gathered.params, full.params),
        ("c_global", gathered.c_global, full.c_global),
        ("c_stack", gathered.c_stack, full.c_stack),
    ):
        for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(fu)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=f"{name} diverged between gathered and full-N paths",
            )


def test_nonparticipant_controls_do_not_move(mlp, tmp_path, devices):
    """Only the sampled cohort's control rows may change in a round."""
    cd = federate(_data(n=256), num_clients=16, scheme="iid", batch_size=8)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(
            num_rounds=1, participation_rate=0.25, seed=3, base_dir=tmp_path,
            save_metrics=False,
        ),
        training=TrainingConfig(batch_size=8, learning_rate=0.1),
        scaffold=True,
    )
    sampled = set(coord._sample_cohort(0).tolist())
    coord.run()
    stack = [np.asarray(x) for x in jax.tree.leaves(coord.c_stack)]
    for cid in range(coord.num_clients):
        row_norm = sum(float(np.abs(leaf[cid]).sum()) for leaf in stack)
        if cid in sampled:
            assert row_norm > 0, f"participant {cid}'s control never moved"
        else:
            assert row_norm == 0, f"non-participant {cid}'s control moved"


def test_chunked_scaffold_matches_unchunked(mlp, tmp_path, devices):
    """client_chunk bounds activation memory; it must not change the math."""
    cd = federate(_data(n=256), num_clients=16, scheme="iid", batch_size=8)

    def make(chunk):
        return Coordinator(
            model=mlp,
            train_data=cd,
            config=CoordinatorConfig(
                num_rounds=2, seed=0, base_dir=tmp_path, save_metrics=False
            ),
            training=TrainingConfig(batch_size=8, learning_rate=0.1),
            scaffold=True,
            client_chunk=chunk,
        )

    a, b = make(None), make(1)
    a.run()
    b.run()
    for pa, pb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), rtol=1e-5, atol=1e-6)
    for ca, cb in zip(jax.tree.leaves(a.c_stack), jax.tree.leaves(b.c_stack)):
        np.testing.assert_allclose(np.asarray(ca), np.asarray(cb), rtol=1e-5, atol=1e-6)


def test_scaffold_refuses_incompatible_features(mlp, tmp_path, devices):
    from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy.config import PrivacyConfig

    cd = federate(_data(n=64), num_clients=2, scheme="iid", batch_size=32)
    with pytest.raises(ValueError, match="central_privacy"):
        Coordinator(
            model=mlp,
            train_data=cd,
            config=CoordinatorConfig(num_rounds=1, base_dir=tmp_path),
            scaffold=True,
            central_privacy=PrivacyAwareAggregationConfig(
                privacy=PrivacyConfig(
                    epsilon=8.0, delta=1e-5, noise_multiplier=1.0, max_gradient_norm=1.0
                )
            ),
        )


# ---------------------------------------------------------------------------
# Persistence
# ---------------------------------------------------------------------------


def test_scaffold_resume_equals_uninterrupted(mlp, tmp_path, devices):
    """The controls ARE round state: a resumed run must continue with the SAME
    correction, matching the uninterrupted run's params bit-for-float."""
    cd = federate(_data(n=256), num_clients=8, scheme="iid", batch_size=32)
    full = _coord(mlp, cd, tmp_path / "full", scaffold=True, rounds=4)
    full.run()

    store = FileStateStore(tmp_path / "ckpt")
    first = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(num_rounds=2, seed=0, base_dir=tmp_path / "a",
                                 save_metrics=False),
        training=TrainingConfig(batch_size=32, local_epochs=2, learning_rate=0.1),
        scaffold=True, state_store=store,
    )
    first.run()
    resumed = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(num_rounds=4, seed=0, base_dir=tmp_path / "b",
                                 save_metrics=False),
        training=TrainingConfig(batch_size=32, local_epochs=2, learning_rate=0.1),
        scaffold=True, state_store=store,
    )
    assert resumed.current_round == 2
    resumed.run()
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree.leaves(full.c_global), jax.tree.leaves(resumed.c_global)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_resume_mode_mismatch_fails_loudly(mlp, tmp_path, devices):
    """Both directions of the scaffold/non-scaffold resume mismatch must raise a
    clear error, not feed the wrong pytree into the round step."""
    cd = federate(_data(n=64), num_clients=2, scheme="iid", batch_size=32)
    store = FileStateStore(tmp_path / "s")
    run = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(num_rounds=1, seed=0, base_dir=tmp_path / "a",
                                 save_metrics=False),
        training=TrainingConfig(batch_size=32, learning_rate=0.1),
        scaffold=True, state_store=store,
    )
    run.run()
    with pytest.raises(NanoFedError, match="scaffold=True"):
        Coordinator(
            model=mlp, train_data=cd,
            config=CoordinatorConfig(num_rounds=2, seed=0, base_dir=tmp_path / "b",
                                     save_metrics=False),
            training=TrainingConfig(batch_size=32, learning_rate=0.1),
            scaffold=False, state_store=store,
        )

    store2 = FileStateStore(tmp_path / "s2")
    plain = Coordinator(
        model=mlp, train_data=cd,
        config=CoordinatorConfig(num_rounds=1, seed=0, base_dir=tmp_path / "c",
                                 save_metrics=False),
        training=TrainingConfig(batch_size=32, learning_rate=0.1),
        state_store=store2,
    )
    plain.run()
    with pytest.raises(NanoFedError, match="no control state"):
        Coordinator(
            model=mlp, train_data=cd,
            config=CoordinatorConfig(num_rounds=2, seed=0, base_dir=tmp_path / "d",
                                     save_metrics=False),
            training=TrainingConfig(batch_size=32, learning_rate=0.1),
            scaffold=True, state_store=store2,
        )


# ---------------------------------------------------------------------------
# The point of the algorithm
# ---------------------------------------------------------------------------


def test_scaffold_beats_fedavg_under_partial_participation_drift(tmp_path, devices):
    """The regime SCAFFOLD is FOR: severe non-IID (Dirichlet alpha=0.05) with
    PARTIAL participation — each round's cohort is a biased sample of the
    population, and the stored controls carry the absent clients' directions into
    every round.  Same local lr for both arms (apples to apples); deterministic
    seeds keep the gap stable.  (Full participation is the wrong showcase: the
    round mean already sees every client, and at the aggressive lr that regime
    favors, the one-round-stale correction can even destabilize SCAFFOLD — the
    docstring's eta_l stability bound is real, and run_scaffold's evidence
    artifact records the divergent arm honestly.)"""
    from nanofed_tpu.data import load_digits_dataset

    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    model = get_model("digits_mlp", hidden=64)
    cd = federate(
        train, num_clients=30, scheme="dirichlet", batch_size=16, seed=1, alpha=0.05
    )
    finals = {}
    for scaffold in (False, True):
        coord = Coordinator(
            model=model,
            train_data=cd,
            config=CoordinatorConfig(
                num_rounds=25, seed=0, participation_rate=0.3, base_dir=tmp_path,
                save_metrics=False,
            ),
            training=TrainingConfig(batch_size=16, local_epochs=16, learning_rate=0.2),
            eval_data=pack_eval(test, batch_size=128),
            scaffold=scaffold,
        )
        coord.run()
        finals[scaffold] = coord.evaluate()["accuracy"]
    assert finals[True] > finals[False] + 0.01, (
        f"SCAFFOLD {finals[True]:.4f} should beat FedAvg {finals[False]:.4f} "
        "under Dirichlet(0.05) drift at 30% participation"
    )
