"""End-to-end federated training on the CPU mesh — the replacement for the reference's
``tests/integration/test_client_server_communication.py`` (which needed a live aiohttp
server; here the transport is the mesh itself)."""

import json

import jax
import numpy as np
import pytest

from nanofed_tpu.data import federate, pack_eval, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig, RoundStatus
from nanofed_tpu.trainer import TrainingConfig


@pytest.fixture(scope="module")
def mlp():
    return get_model("mlp", in_features=16, hidden=32, num_classes=4)


def _data(n=1024, classes=4, feat=16, seed=0):
    return synthetic_classification(n, classes, (feat,), seed=seed)


def test_full_training_run_learns_and_writes_metrics(mlp, tmp_path, devices):
    train = _data()
    test = _data(n=256, seed=9)
    cd = federate(train, num_clients=8, scheme="iid", batch_size=32)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(num_rounds=4, seed=0, base_dir=tmp_path, eval_every=2),
        training=TrainingConfig(batch_size=32, local_epochs=2),
        eval_data=pack_eval(test, batch_size=64),
    )
    rounds = coord.run()
    assert len(rounds) == 4
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)
    # Learning happened and generalized.
    assert rounds[-1].agg_metrics["loss"] < rounds[0].agg_metrics["loss"]
    final = coord.evaluate()
    assert final["accuracy"] > 0.9

    # Per-round metrics JSON parity (coordinator.py:247-280).
    f = tmp_path / "metrics" / "metrics_round_2.json"
    payload = json.loads(f.read_text())
    assert payload["round_id"] == 2
    assert payload["status"] == "completed"
    assert len(payload["clients"]["weights"]) == 8
    # round ids are 0-based; eval_every=2 evaluates after rounds 1 and 3, not 2.
    assert payload["eval_metrics"] == {}
    f3 = json.loads((tmp_path / "metrics" / "metrics_round_3.json").read_text())
    assert "accuracy" in f3["eval_metrics"]


def test_eval_every_schedule(mlp, tmp_path, devices):
    cd = federate(_data(n=256), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(num_rounds=2, base_dir=tmp_path, eval_every=2),
        training=TrainingConfig(batch_size=16),
        eval_data=pack_eval(_data(n=128, seed=5), batch_size=64),
    )
    rounds = coord.run()
    assert rounds[0].eval_metrics == {}
    assert "accuracy" in rounds[1].eval_metrics


def test_partial_participation_and_dropout_failed_rounds(mlp, tmp_path, devices):
    cd = federate(_data(n=512), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(
            num_rounds=6,
            participation_rate=0.5,  # cohort of 4
            dropout_rate=0.9,  # nearly everyone "times out"
            min_completion_rate=0.75,  # needs 3/4 to survive
            base_dir=tmp_path,
        ),
        training=TrainingConfig(batch_size=16),
    )
    rounds = coord.run()
    failed = [r for r in rounds if r.status == RoundStatus.FAILED]
    assert failed, "with 90% dropout some rounds must fail"
    # Failed rounds leave the model untouched and carry no agg metrics.
    assert all(r.agg_metrics == {} for r in failed)
    progress = coord.training_progress
    assert progress.failed_rounds == len(failed)
    assert progress.completed_rounds == 6 - len(failed)


def test_unequal_client_sizes(mlp, tmp_path, devices):
    """The reference example's 12k/8k/4k pattern, scaled down: weights ∝ samples."""
    from nanofed_tpu.data import iid_partition, pack_clients

    ds = _data(n=700)
    parts = iid_partition(700, 3, seed=0, proportions=[0.5, 0.3, 0.2])
    cd = pack_clients(ds, parts, batch_size=16)
    coord = Coordinator(
        model=get_model("mlp", in_features=16, hidden=32, num_classes=4),
        train_data=cd,  # 3 clients on 8 devices -> padded to 8
        config=CoordinatorConfig(num_rounds=2, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16),
    )
    rounds = coord.run()
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)
    assert rounds[0].agg_metrics["participating_clients"] == 3
    payload = json.loads((tmp_path / "metrics" / "metrics_round_0.json").read_text())
    w = np.asarray(payload["clients"]["weights"])
    assert w[0] > w[1] > w[2] > 0
    assert np.all(w[3:] == 0)  # padded dummy clients


def test_label_skew_noniid_run(mlp, tmp_path, devices):
    """Benchmark config #2 shape: non-IID label-skew with partial participation."""
    cd = federate(
        _data(n=512), num_clients=16, scheme="label_skew", batch_size=16, shards_per_client=2
    )
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(
            num_rounds=3, participation_rate=0.25, base_dir=tmp_path, seed=1
        ),
        training=TrainingConfig(batch_size=16),
    )
    rounds = coord.run()
    assert all(r.status == RoundStatus.COMPLETED for r in rounds)
    assert all(r.agg_metrics["participating_clients"] == 4 for r in rounds)


def test_run_experiment_cli_engine(tmp_path, devices):
    from nanofed_tpu.experiments import run_experiment

    out = run_experiment(
        model="mlp",
        num_clients=8,
        num_rounds=2,
        local_epochs=1,
        batch_size=32,
        out_dir=tmp_path,
        train_size=512,
    )
    assert out["rounds_completed"] == 2
    assert "accuracy" in out["final_eval_metrics"]


def test_central_privacy_accounting_surfaces_epsilon(mlp, tmp_path, devices):
    """The coordinator owns an accountant when central DP is configured: ε/δ spend shows
    up in every completed round's metrics and accumulates monotonically."""
    from nanofed_tpu.aggregation import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy import PrivacyConfig

    cd = federate(_data(n=256), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(num_rounds=3, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16),
        central_privacy=PrivacyAwareAggregationConfig(
            privacy=PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
        ),
    )
    rounds = coord.run()
    eps = [r.agg_metrics["privacy_epsilon"] for r in rounds]
    assert all(e > 0 for e in eps)
    assert eps == sorted(eps) and eps[0] < eps[-1]  # cumulative across rounds
    assert rounds[-1].agg_metrics["privacy_delta"] == 1e-5
    assert coord.privacy_spent.epsilon_spent == pytest.approx(eps[-1])
    # And it lands in the persisted per-round metrics JSON.
    payload = json.loads((tmp_path / "metrics" / "metrics_round_2.json").read_text())
    assert payload["agg_metrics"]["privacy_epsilon"] == pytest.approx(eps[-1])


def test_central_privacy_accounts_at_realized_cohort_rate(mlp, tmp_path, devices):
    """Accounting must use the REALIZED inclusion probability cohort/N, not the nominal
    participation_rate: ceil + the floor-at-1 make cohort/N >= rate, and accounting at
    the smaller nominal q would under-report ε (q² amplification ⇒ ~25× at the extreme)."""
    from nanofed_tpu.aggregation import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy import PrivacyConfig

    cd = federate(_data(n=256), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        # nominal q=0.02 -> cohort = max(1, ceil(0.16)) = 1 -> realized q = 1/8
        config=CoordinatorConfig(
            num_rounds=2, participation_rate=0.02, base_dir=tmp_path, seed=3
        ),
        training=TrainingConfig(batch_size=16),
        central_privacy=PrivacyAwareAggregationConfig(
            privacy=PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
        ),
    )
    assert coord.cohort_size == 1
    coord.run()
    events = coord.privacy_accountant.state_dict()["events"]
    assert events == [[1.0, 1 / 8, 2.0]]


def test_cohort_gather_equals_full_mask_round(mlp, tmp_path, devices):
    """Partial participation runs the round step over the GATHERED cohort (K_pad
    clients) instead of all N zero-weighted — at q=0.1 that is 10x less compute.
    The optimization must be invisible: same seed, same cohorts, identical released
    params as the full-N masked path.

    Single-batch clients (batch_size == the 16-sample per-client capacity): the
    gathered and full-N rounds are different compiled programs, and some jaxlib CPU
    backends (observed on 0.4.36) draw a context-DEPENDENT (valid, deterministic,
    but program-specific) epoch-shuffle permutation inside fused shard_map programs.
    One batch per client makes the shuffle a within-batch permutation, which every
    sum-reduction is invariant to — the equivalence this test pins (gather indices,
    client-stable keys, weighting) stays exact on every backend."""
    cd = federate(_data(n=256), num_clients=16, scheme="iid", batch_size=16)

    def make():
        return Coordinator(
            model=mlp,
            train_data=cd,
            config=CoordinatorConfig(
                num_rounds=3, participation_rate=0.25, seed=5, base_dir=tmp_path,
                save_metrics=False,
            ),
            training=TrainingConfig(batch_size=16),
        )

    gathered = make()
    assert gathered._cohort_mode and gathered._step_clients < gathered._padded_clients
    full = make()
    # Force the legacy full-N masked path on the second coordinator.
    full._cohort_mode = False
    full._step_clients = full._padded_clients
    gathered.run()
    full.run()
    for a, b in zip(jax.tree.leaves(gathered.params), jax.tree.leaves(full.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # Same cohorts were drawn (deterministic non-DP sampling), so the weighted train
    # metrics agree too.
    for ga, fu in zip(gathered.history, full.history):
        assert ga.agg_metrics["loss"] == pytest.approx(fu.agg_metrics["loss"], abs=1e-5)


def test_dp_cohort_sampling_uses_secret_randomness(mlp, tmp_path, devices):
    """Amplification-by-subsampling requires SECRET sampling randomness: under central
    DP the cohort must NOT be a deterministic function of the persisted config seed
    (two identically-seeded coordinators draw different cohorts), while the no-DP path
    stays reproducible from the seed."""
    from nanofed_tpu.aggregation import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy import PrivacyConfig

    cd = federate(_data(n=256), num_clients=64, scheme="iid", batch_size=4)

    def make(dp: bool, participation: float = 0.25):
        return Coordinator(
            model=mlp,
            train_data=cd,
            config=CoordinatorConfig(
                num_rounds=1, participation_rate=participation, base_dir=tmp_path,
                seed=7,
            ),
            training=TrainingConfig(batch_size=4),
            central_privacy=PrivacyAwareAggregationConfig(
                privacy=PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
            ) if dp else None,
        )

    # No-DP: deterministic in the config seed.
    plain = [sorted(make(False)._sample_cohort(0)) for _ in range(2)]
    assert plain[0] == plain[1]
    # DP: 16-of-64 cohorts from two identically-configured coordinators collide with
    # probability 1/C(64,16) ~ 2e-15 — a match means the seed leaked into sampling.
    dp = [sorted(make(True)._sample_cohort(0)) for _ in range(2)]
    assert dp[0] != dp[1]
    # And the DP draw is not the seed-derived draw either.
    assert dp[0] != plain[0] and dp[1] != plain[0]

    # The NOISE must be secret too: noise regenerable from the persisted seed could be
    # subtracted from the released aggregate, voiding DP outright.  Full participation
    # pins the cohort (all clients), so the noise key is the ONLY nondeterminism — two
    # identically-seeded DP coordinators must still release different params.
    a, b = make(True, participation=1.0), make(True, participation=1.0)
    list(a.start_training())
    list(b.start_training())
    leaves_a, leaves_b = (np.asarray(jax.tree.leaves(c.params)[0]) for c in (a, b))
    assert not np.array_equal(leaves_a, leaves_b)
    # And the per-client detail block (weights = cohort membership; un-noised update
    # norms) must not be persisted under DP.
    payload = json.loads((tmp_path / "metrics" / "metrics_round_0.json").read_text())
    assert "clients" not in payload


def test_no_privacy_no_accounting(mlp, tmp_path, devices):
    cd = federate(_data(n=128), num_clients=8, scheme="iid", batch_size=16)
    coord = Coordinator(
        model=mlp,
        train_data=cd,
        config=CoordinatorConfig(num_rounds=1, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16),
    )
    rounds = coord.run()
    assert coord.privacy_spent is None
    assert "privacy_epsilon" not in rounds[0].agg_metrics
