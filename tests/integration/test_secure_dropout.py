"""Dropout-tolerant secure aggregation over the real HTTP transport, and mask-backend
negotiation at registration.

The reference gestures at threshold tolerance (``nanofed/server/aggregator/
privacy.py:72-110``: Shamir-style share verification) but its transport cannot carry a
masked round at all.  Here the full Bonawitz double-masking protocol (CCS 2017, §4)
runs over real aiohttp sockets: enroll -> deposit sealed Shamir shares -> mask (pairwise
+ self) -> POST -> unmask round (survivors reveal shares) -> reconstruct orphaned masks
-> weighted FedAvg of the survivors.  One flaky client no longer kills the cohort's
round, while a delivered-but-presumed-dropped update stays private behind its self mask.
"""

import asyncio
import json

import jax
import numpy as np

from nanofed_tpu.aggregation.fedavg import fedavg_combine
from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
)
from nanofed_tpu.communication.network_coordinator import stack_model_updates
from nanofed_tpu.core.types import ModelUpdate
from nanofed_tpu.models import get_model
from nanofed_tpu.security.secure_agg import (
    ClientKeyPair,
    SecureAggregationConfig,
    build_unmask_reveals,
    make_dropout_shares,
    mask_update,
    open_share_inbox,
)

PORT = 18560


def _client_params(model, seed):
    return model.init(jax.random.key(seed))


async def _fetch_model_retry(client, like, attempts=100, delay=0.05):
    from nanofed_tpu.core.exceptions import NanoFedError

    for _ in range(attempts):
        try:
            return await client.fetch_global_model(like=like)
        except NanoFedError:
            await asyncio.sleep(delay)
    raise TimeoutError("model never published")


async def _participate_once(client, identity, roster, cid, local_params,
                            num_samples, cfg, rnd, drop_after_shares=False,
                            pre_deposit_hook=None):
    """ONE round of dropout-tolerant participation (the wire protocol, shared by the
    single-round and multi-round drivers so it exists in exactly one place): fetch the
    active roster, distribute fresh ephemeral secrets, mask (pairwise + self), submit,
    answer the unmask round.  Returns 'evicted', 'dropped', or 'done'.

    ``drop_after_shares`` vanishes AFTER the share barrier (its pairwise masks are
    baked into the survivors' vectors — the case recovery exists for);
    ``pre_deposit_hook(client, rnd, mask_key, sealed, commitment)`` runs before the
    honest deposit (e.g. to attempt a forged one)."""
    import hashlib

    participants = await client.fetch_secagg_participants()
    if cid not in participants:
        return "evicted"
    mask_key = ClientKeyPair.generate()
    context = f"{client.secagg_session}:{rnd}"
    self_seed, sealed = make_dropout_shares(
        identity, mask_key, participants,
        {c: roster.public_keys[c] for c in participants}, cfg.threshold,
        my_id=cid, context=context,
    )
    commitment = hashlib.sha256(self_seed).digest()
    if pre_deposit_hook is not None:
        await pre_deposit_hook(client, rnd, mask_key, sealed, commitment)
    assert await client.deposit_secagg_shares(
        rnd, mask_key.public_bytes(), sealed, self_seed_commitment=commitment,
    )
    epks, inbox = await client.fetch_secagg_inbox(rnd)
    held = open_share_inbox(identity, cid, roster.public_keys, inbox, epks, context)
    if drop_after_shares:
        return "dropped"
    masked = mask_update(
        local_params,
        participants.index(cid),
        mask_key,
        [epks[c] for c in participants],
        rnd,
        cfg,
        weight=roster.weights[cid],
        self_seed=self_seed,
    )
    assert await client.submit_masked_update(masked, {"num_samples": num_samples})
    # Unmask round: poll until the server publishes the request, then reveal (or the
    # round resolves without needing this reveal / training ends).
    for _ in range(400):
        request = await client.poll_unmask_request()
        if (request is not None and request["round"] == rnd
                and cid in request["survivors"]):
            reveals = build_unmask_reveals(request, cid, held)
            assert await client.submit_unmask_reveals(rnd, reveals)
            return "done"
        status = await client.check_server_status()
        if not status.get("training_active", True) or status["round"] != rnd:
            return "done"
        await asyncio.sleep(0.05)
    return "done"


async def _run_tolerant_client(
    port, cid, local_params, num_samples, cfg, drop_before_submit=False,
    security_manager=None, pre_deposit_hook=None,
):
    """Single-round dropout-tolerant client: enroll, then one _participate_once."""
    identity = ClientKeyPair.generate()
    async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30,
                          security_manager=security_manager) as client:
        assert await client.register_secagg(identity.public_bytes(), num_samples)
        roster = await client.fetch_secagg_roster()
        params, rnd, active = await _fetch_model_retry(client, local_params)
        assert active
        await _participate_once(
            client, identity, roster, cid, local_params, num_samples, cfg, rnd,
            drop_after_shares=drop_before_submit, pre_deposit_hook=pre_deposit_hook,
        )


async def _run_multi_round_client(port, cid, local_params, num_samples, cfg,
                                  drop_at_round=None, tolerate_failed_rounds=False):
    """Multi-round dropout-tolerant client: loops rounds via _participate_once,
    honoring eviction.  Model fetches are bounded (a persistent fetch failure must
    surface HERE, not as a far-away round-status assert).  With
    ``tolerate_failed_rounds`` a participation error is swallowed ONLY when the
    server has actually moved past the round (a stalled/failed round being cleaned
    up); an error during a live round always surfaces."""
    identity = ClientKeyPair.generate()
    async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30) as client:
        assert await client.register_secagg(identity.public_bytes(), num_samples)
        roster = await client.fetch_secagg_roster()
        seen_round = -1
        fetch_failures = 0
        while True:
            try:
                params, rnd, active = await client.fetch_global_model(
                    like=local_params
                )
                fetch_failures = 0
            except Exception:
                fetch_failures += 1
                if fetch_failures > 100:
                    raise
                await asyncio.sleep(0.05)
                continue
            if not active:
                return
            if rnd == seen_round:
                await asyncio.sleep(0.05)
                continue
            seen_round = rnd
            try:
                outcome = await _participate_once(
                    client, identity, roster, cid, local_params, num_samples,
                    cfg, rnd,
                    drop_after_shares=(drop_at_round is not None
                                       and rnd >= drop_at_round),
                )
            except Exception:
                if not tolerate_failed_rounds:
                    raise
                status = await client.check_server_status()
                if status.get("training_active", True) and status.get("round") == rnd:
                    raise  # a live-round failure, not a failed round's cleanup
                continue
            if outcome in ("evicted", "dropped"):
                return


def _run_round(port, cfg, clients, num_rounds=1, min_clients=None,
               completion_rate=1.0, timeout=3.0):
    """clients: list of (cid, params, num_samples, drops)."""
    model_like = clients[0][1]

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, model_like,
                NetworkRoundConfig(
                    num_rounds=num_rounds,
                    min_clients=min_clients or len(clients),
                    min_completion_rate=completion_rate,
                    round_timeout_s=timeout,
                ),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                *(
                    _run_tolerant_client(port, cid, p, n, cfg, drop)
                    for cid, p, n, drop in clients
                ),
            )
            return coordinator
        finally:
            await server.stop()

    return asyncio.run(main())


def test_dropout_round_completes_with_survivor_fedavg():
    """THE VERDICT scenario: 1 of 5 enrolled clients drops mid-round (after its
    pairwise masks are baked into everyone's vectors); the round still COMPLETES and
    the aggregate equals the plain weighted FedAvg of the 4 survivors."""
    model = get_model("linear", in_features=6, num_classes=2)
    # min_clients=4 is the privacy floor: the recovered sum after one dropout still
    # covers a crowd of 4, which every client consented to.
    cfg = SecureAggregationConfig(
        min_clients=4, frac_bits=16, threshold=3, dropout_tolerant=True
    )
    num_samples = {"c1": 30.0, "c2": 10.0, "c3": 20.0, "c4": 40.0, "c5": 25.0}
    local = {c: _client_params(model, s) for s, c in enumerate(num_samples, start=1)}
    clients = [(c, local[c], num_samples[c], c == "c3") for c in num_samples]

    coordinator = _run_round(PORT, cfg, clients, completion_rate=0.5, timeout=2.5)
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_clients"] == 4
    assert record["num_dropped"] == 1

    survivors = [c for c in num_samples if c != "c3"]
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in survivors
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_tolerant_mode_without_dropout_matches_fedavg():
    """Zero dropouts in tolerant mode: the unmask round removes only self masks and
    the aggregate equals plain weighted FedAvg of the full cohort."""
    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=3, frac_bits=16, threshold=2, dropout_tolerant=True
    )
    num_samples = {"c1": 12.0, "c2": 24.0, "c3": 6.0}
    local = {c: _client_params(model, s) for s, c in enumerate(num_samples, start=4)}
    clients = [(c, local[c], num_samples[c], False) for c in num_samples]

    coordinator = _run_round(PORT + 1, cfg, clients, timeout=3.0)
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_dropped"] == 0
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in num_samples
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_too_many_dropouts_fail_closed():
    """Survivors below max(required, threshold) must FAIL the round and leave params
    untouched — recovery never degrades below the Shamir threshold."""
    model = get_model("linear", in_features=4, num_classes=2)
    init = _client_params(model, 0)
    cfg = SecureAggregationConfig(
        min_clients=5, frac_bits=16, threshold=4, dropout_tolerant=True
    )
    num_samples = {f"c{i}": 10.0 for i in range(1, 6)}
    # 2 of 5 drop -> 3 survivors < threshold=4.
    clients = [(c, init, num_samples[c], c in ("c2", "c4")) for c in num_samples]

    coordinator = _run_round(PORT + 2, cfg, clients, completion_rate=0.5, timeout=1.5)
    record = coordinator.history[0]
    assert record["status"] == "FAILED"
    assert record["num_dropped"] == 2
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(init)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mixed_backend_cohort_refused_at_registration():
    """Mask-backend negotiation (host-Philox vs device-PRNG streams are
    wire-incompatible): the first enrollment pins the cohort backend and a mismatched
    registration is refused with 409 AT REGISTRATION — not discovered post-hoc as a
    garbage aggregate at dequantize."""

    async def scenario():
        server = HTTPServer(port=PORT + 3)
        server.open_secagg(3)
        await server.start()
        try:
            k1, k2 = ClientKeyPair.generate(), ClientKeyPair.generate()
            async with HTTPClient(f"http://127.0.0.1:{PORT + 3}", "c1",
                                  timeout_s=10) as c1:
                assert await c1.register_secagg(k1.public_bytes(), 10.0,
                                                backend="host")
            async with HTTPClient(f"http://127.0.0.1:{PORT + 3}", "c2",
                                  timeout_s=10) as c2:
                # Mismatched backend -> refused at registration.
                assert not await c2.register_secagg(k2.public_bytes(), 10.0,
                                                    backend="device")
                # Same client re-enrolls with the negotiated backend -> accepted.
                assert await c2.register_secagg(k2.public_bytes(), 10.0,
                                                backend="host")
                roster_resp = await c2.check_server_status()
                assert roster_resp["status"] == "success"
            assert server.secagg_backend() == "host"
            assert len(server.secagg_client_order()) == 2
        finally:
            await server.stop()

    asyncio.run(scenario())


def test_evicted_client_cannot_submit_or_deposit():
    """Eviction is enforced at the wire: an evicted client's masked update and share
    deposit are refused with 403 (its round secrets were revealed — accepting its
    vector would let it push slow-but-alive members past the round barrier)."""
    import base64

    from aiohttp.test_utils import TestClient, TestServer

    async def scenario():
        server = HTTPServer(port=0)
        server.open_secagg(3)
        model = get_model("linear", in_features=3, num_classes=2)
        await server.publish_model(_client_params(model, 0), 0)
        client = TestClient(TestServer(server._app))
        await client.start_server()
        try:
            for cid in ("c1", "c2", "c3"):
                pk = ClientKeyPair.generate().public_bytes()
                r = await client.post(
                    "/secagg/register",
                    json={"public_key": base64.b64encode(pk).decode(),
                          "num_samples": 10.0},
                    headers={"X-NanoFed-Client": cid},
                )
                assert r.status == 200
            server.evict_secagg_clients(["c2"])
            assert server.secagg_active_order() == ["c1", "c3"]
            # Masked update from the evicted client: refused.
            r = await client.post(
                "/update", data=b"whatever",
                headers={"X-NanoFed-Client": "c2", "X-NanoFed-Round": "0",
                         "X-NanoFed-SecAgg": "masked"},
            )
            assert r.status == 403
            assert "evicted" in (await r.json())["message"]
            # Share deposit from the evicted client: refused (not in active cohort).
            r = await client.post(
                "/secagg/shares",
                data=json.dumps({"epk": base64.b64encode(bytes(32)).decode(),
                                 "blobs": {"c1": "x", "c3": "x"}}).encode(),
                headers={"X-NanoFed-Client": "c2", "X-NanoFed-Round": "0",
                         "Content-Type": "application/json"},
            )
            assert r.status == 403
        finally:
            await client.close()

    asyncio.run(scenario())


def test_signed_tolerant_round_with_dropout():
    """require_signatures=True covers the dropout-tolerant aux endpoints too: share
    deposits sign over session:round, unmask reveals over session:round — and the
    full signed round with a dropout still completes.  An unsigned deposit from an
    enrolled id bounces with 403."""
    from nanofed_tpu.security.signing import SecurityManager

    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=3, frac_bits=16, threshold=3, dropout_tolerant=True
    )
    ids = ["c1", "c2", "c3", "c4"]
    managers = {c: SecurityManager(key_size=1024) for c in ids}
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 30 + i) for i, c in enumerate(ids)}
    deposit_rejected = {}

    async def forge_deposit(client, rnd, mask_key, sealed, commitment):
        # Same payload, no signature: must bounce 403 and never count toward the
        # share barrier.  finally: an exception here (e.g. transient socket error)
        # must not leave the client unsigned for its HONEST requests.
        manager = client.security_manager
        client.security_manager = None
        try:
            ok = await client.deposit_secagg_shares(
                rnd, mask_key.public_bytes(), sealed,
                self_seed_commitment=commitment,
            )
            deposit_rejected[client.client_id] = not ok
        finally:
            client.security_manager = manager

    async def main():
        server = HTTPServer(
            port=PORT + 5,
            client_keys={c: m.get_public_key() for c, m in managers.items()},
            require_signatures=True,
        )
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=1, min_clients=4,
                                   min_completion_rate=0.5, round_timeout_s=2.5),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                _run_tolerant_client(PORT + 5, "c1", local["c1"], num_samples["c1"],
                                     cfg, security_manager=managers["c1"],
                                     pre_deposit_hook=forge_deposit),
                _run_tolerant_client(PORT + 5, "c2", local["c2"], num_samples["c2"],
                                     cfg, security_manager=managers["c2"]),
                _run_tolerant_client(PORT + 5, "c3", local["c3"], num_samples["c3"],
                                     cfg, security_manager=managers["c3"]),
                _run_tolerant_client(PORT + 5, "c4", local["c4"], num_samples["c4"],
                                     cfg, security_manager=managers["c4"],
                                     drop_before_submit=True),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    assert deposit_rejected == {"c1": True}
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_clients"] == 3
    assert record["num_dropped"] == 1
    survivors = ["c1", "c2", "c3"]
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in survivors
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params),
                         jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_multiround_eviction_keeps_later_rounds_fast():
    """Across rounds: round 0 completes with the full cohort, the round-1 dropout is
    EVICTED, and round 2 completes promptly with the shrunk cohort (no stall waiting
    for the corpse).  Pins the per-round fresh-secrets + eviction lifecycle the
    example demonstrates."""
    import time

    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=2, frac_bits=16, threshold=2, dropout_tolerant=True
    )
    ids = ["c1", "c2", "c3"]
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 40 + i) for i, c in enumerate(ids)}

    durations = {}

    async def main():
        server = HTTPServer(port=PORT + 6)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=3, min_clients=3,
                                   min_completion_rate=0.5, round_timeout_s=2.0),
                secure=cfg,
            )

            async def run_and_time():
                original = coordinator.train_round

                async def wrapped(round_number):
                    t = time.monotonic()
                    record = await original(round_number)
                    durations[round_number] = time.monotonic() - t
                    return record

                coordinator.train_round = wrapped
                return await coordinator.run()

            await asyncio.gather(
                run_and_time(),
                _run_multi_round_client(PORT + 6, "c1", local["c1"],
                                        num_samples["c1"], cfg),
                _run_multi_round_client(PORT + 6, "c2", local["c2"],
                                        num_samples["c2"], cfg),
                _run_multi_round_client(PORT + 6, "c3", local["c3"],
                                        num_samples["c3"], cfg, drop_at_round=1),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    statuses = [(h["round"], h["status"], h["num_dropped"])
                for h in coordinator.history]
    assert statuses == [(0, "COMPLETED", 0), (1, "COMPLETED", 1),
                        (2, "COMPLETED", 0)]
    # Round 1 pays the detection timeout for the dropped client; round 2 must NOT
    # (c3 was evicted, so the shrunk cohort completes well under the 2s timeout).
    assert durations[1] >= 2.0
    assert durations[2] < durations[1]


def test_drop_before_share_barrier_fails_round_and_evicts():
    """A client that vanishes BEFORE depositing its round shares stalls the share
    barrier (nobody can mask), so that round FAILS — but the non-depositor is
    evicted and the NEXT round completes from the shrunk cohort.  (Dropping after
    the barrier is the recoverable case covered elsewhere.)"""
    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=2, frac_bits=16, threshold=2, dropout_tolerant=True
    )
    ids = ["c1", "c2", "c3"]
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 50 + i) for i, c in enumerate(ids)}

    async def vanishing_client(cid):
        """Enrolls, then never deposits round shares (crash before the barrier)."""
        identity = ClientKeyPair.generate()
        async with HTTPClient(f"http://127.0.0.1:{PORT + 7}", cid,
                              timeout_s=30) as client:
            assert await client.register_secagg(
                identity.public_bytes(), num_samples[cid]
            )
            await client.fetch_secagg_roster()

    async def main():
        server = HTTPServer(port=PORT + 7)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=2, min_clients=3,
                                   min_completion_rate=0.5, round_timeout_s=2.0),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                _run_multi_round_client(PORT + 7, "c1", local["c1"],
                                        num_samples["c1"], cfg,
                                        tolerate_failed_rounds=True),
                _run_multi_round_client(PORT + 7, "c2", local["c2"],
                                        num_samples["c2"], cfg,
                                        tolerate_failed_rounds=True),
                vanishing_client("c3"),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    assert [h["status"] for h in coordinator.history] == ["FAILED", "COMPLETED"]
    # Round 0's failure record names the eviction; round 1 ran without c3.
    assert "evicted" in coordinator.history[0]["reason"]
    assert coordinator.history[1]["num_clients"] == 2
    assert coordinator.history[1]["num_dropped"] == 0
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=1, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in ["c1", "c2"]
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params),
                         jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)
