"""Dropout-tolerant secure aggregation over the real HTTP transport, and mask-backend
negotiation at registration.

The reference gestures at threshold tolerance (``nanofed/server/aggregator/
privacy.py:72-110``: Shamir-style share verification) but its transport cannot carry a
masked round at all.  Here the full Bonawitz double-masking protocol (CCS 2017, §4)
runs over real aiohttp sockets: enroll -> deposit sealed Shamir shares -> mask (pairwise
+ self) -> POST -> unmask round (survivors reveal shares) -> reconstruct orphaned masks
-> weighted FedAvg of the survivors.  One flaky client no longer kills the cohort's
round, while a delivered-but-presumed-dropped update stays private behind its self mask.
"""

import pytest

pytest.importorskip(
    "cryptography", reason="secure-aggregation protocol tests need the optional crypto dependency"
)

import asyncio
import json

import jax
import numpy as np

from nanofed_tpu.aggregation.fedavg import fedavg_combine
from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
)
from nanofed_tpu.communication.network_coordinator import stack_model_updates
from nanofed_tpu.core.types import ModelUpdate
from nanofed_tpu.models import get_model
from nanofed_tpu.security.secure_agg import (
    ClientKeyPair,
    SecureAggregationConfig,
    build_unmask_reveals,
    make_dropout_shares,
    mask_update,
    open_share_inbox,
)

PORT = 18560


def _client_params(model, seed):
    return model.init(jax.random.key(seed))


async def _fetch_model_retry(client, like, attempts=100, delay=0.05):
    from nanofed_tpu.core.exceptions import NanoFedError

    for _ in range(attempts):
        try:
            return await client.fetch_global_model(like=like)
        except NanoFedError:
            await asyncio.sleep(delay)
    raise TimeoutError("model never published")


async def _participate_once(client, identity, roster, cid, local_params,
                            num_samples, cfg, rnd, drop_after_shares=False,
                            pre_deposit_hook=None):
    """ONE round of dropout-tolerant participation (the wire protocol, shared by the
    single-round and multi-round drivers so it exists in exactly one place): fetch the
    active roster, distribute fresh ephemeral secrets, mask (pairwise + self), submit,
    answer the unmask round.  Returns 'evicted', 'dropped', or 'done'.

    ``drop_after_shares`` vanishes AFTER the share barrier (its pairwise masks are
    baked into the survivors' vectors — the case recovery exists for);
    ``pre_deposit_hook(client, rnd, mask_key, sealed, commitment)`` runs before the
    honest deposit (e.g. to attempt a forged one)."""
    import hashlib

    participants, round_threshold = await client.fetch_secagg_round_info()
    if cid not in participants:
        return "evicted"
    mask_key = ClientKeyPair.generate()
    context = f"{client.secagg_session}:{rnd}"
    self_seed, sealed = make_dropout_shares(
        identity, mask_key, participants,
        {c: roster.public_keys[c] for c in participants},
        # Window enrollment announces the per-round cohort-derived threshold;
        # exact-cohort servers announce none and the shared config applies.
        round_threshold or cfg.threshold,
        my_id=cid, context=context,
    )
    commitment = hashlib.sha256(self_seed).digest()
    if pre_deposit_hook is not None:
        await pre_deposit_hook(client, rnd, mask_key, sealed, commitment)
    assert await client.deposit_secagg_shares(
        rnd, mask_key.public_bytes(), sealed, self_seed_commitment=commitment,
    )
    epks, inbox = await client.fetch_secagg_inbox(rnd)
    held = open_share_inbox(identity, cid, roster.public_keys, inbox, epks, context)
    if drop_after_shares:
        return "dropped"
    masked = mask_update(
        local_params,
        participants.index(cid),
        mask_key,
        [epks[c] for c in participants],
        rnd,
        cfg,
        weight=roster.weights[cid],
        self_seed=self_seed,
    )
    assert await client.submit_masked_update(masked, {"num_samples": num_samples})
    # Unmask round: poll until the server publishes the request, then reveal (or the
    # round resolves without needing this reveal / training ends).
    for _ in range(400):
        request = await client.poll_unmask_request()
        if (request is not None and request["round"] == rnd
                and cid in request["survivors"]):
            reveals = build_unmask_reveals(request, cid, held)
            assert await client.submit_unmask_reveals(rnd, reveals)
            return "done"
        status = await client.check_server_status()
        if not status.get("training_active", True) or status["round"] != rnd:
            return "done"
        await asyncio.sleep(0.05)
    return "done"


async def _run_tolerant_client(
    port, cid, local_params, num_samples, cfg, drop_before_submit=False,
    security_manager=None, pre_deposit_hook=None,
):
    """Single-round dropout-tolerant client: enroll, then one _participate_once."""
    identity = ClientKeyPair.generate()
    async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30,
                          security_manager=security_manager) as client:
        assert await client.register_secagg(identity.public_bytes(), num_samples)
        roster = await client.fetch_secagg_roster()
        params, rnd, active = await _fetch_model_retry(client, local_params)
        assert active
        await _participate_once(
            client, identity, roster, cid, local_params, num_samples, cfg, rnd,
            drop_after_shares=drop_before_submit, pre_deposit_hook=pre_deposit_hook,
        )


async def _run_multi_round_client(port, cid, local_params, num_samples, cfg,
                                  drop_at_round=None, tolerate_failed_rounds=False):
    """Multi-round dropout-tolerant client: loops rounds via _participate_once,
    honoring eviction.  Model fetches are bounded (a persistent fetch failure must
    surface HERE, not as a far-away round-status assert).  With
    ``tolerate_failed_rounds`` a participation error is swallowed ONLY when the
    server has actually moved past the round (a stalled/failed round being cleaned
    up); an error during a live round always surfaces."""
    identity = ClientKeyPair.generate()
    async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30) as client:
        assert await client.register_secagg(identity.public_bytes(), num_samples)
        roster = await client.fetch_secagg_roster()
        seen_round = -1
        fetch_failures = 0
        while True:
            try:
                params, rnd, active = await client.fetch_global_model(
                    like=local_params
                )
                fetch_failures = 0
            except Exception:
                fetch_failures += 1
                if fetch_failures > 100:
                    raise
                await asyncio.sleep(0.05)
                continue
            if not active:
                return
            if rnd == seen_round:
                await asyncio.sleep(0.05)
                continue
            seen_round = rnd
            try:
                outcome = await _participate_once(
                    client, identity, roster, cid, local_params, num_samples,
                    cfg, rnd,
                    drop_after_shares=(drop_at_round is not None
                                       and rnd >= drop_at_round),
                )
            except Exception:
                if not tolerate_failed_rounds:
                    raise
                status = await client.check_server_status()
                if status.get("training_active", True) and status.get("round") == rnd:
                    raise  # a live-round failure, not a failed round's cleanup
                continue
            if outcome in ("evicted", "dropped"):
                return


def _run_round(port, cfg, clients, num_rounds=1, min_clients=None,
               completion_rate=1.0, timeout=3.0):
    """clients: list of (cid, params, num_samples, drops)."""
    model_like = clients[0][1]

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, model_like,
                NetworkRoundConfig(
                    num_rounds=num_rounds,
                    min_clients=min_clients or len(clients),
                    min_completion_rate=completion_rate,
                    round_timeout_s=timeout,
                ),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                *(
                    _run_tolerant_client(port, cid, p, n, cfg, drop)
                    for cid, p, n, drop in clients
                ),
            )
            return coordinator
        finally:
            await server.stop()

    return asyncio.run(main())


def test_dropout_round_completes_with_survivor_fedavg():
    """THE VERDICT scenario: 1 of 5 enrolled clients drops mid-round (after its
    pairwise masks are baked into everyone's vectors); the round still COMPLETES and
    the aggregate equals the plain weighted FedAvg of the 4 survivors."""
    model = get_model("linear", in_features=6, num_classes=2)
    # min_clients=4 is the privacy floor: the recovered sum after one dropout still
    # covers a crowd of 4, which every client consented to.
    cfg = SecureAggregationConfig(
        min_clients=4, frac_bits=16, threshold=3, dropout_tolerant=True
    )
    num_samples = {"c1": 30.0, "c2": 10.0, "c3": 20.0, "c4": 40.0, "c5": 25.0}
    local = {c: _client_params(model, s) for s, c in enumerate(num_samples, start=1)}
    clients = [(c, local[c], num_samples[c], c == "c3") for c in num_samples]

    coordinator = _run_round(PORT, cfg, clients, completion_rate=0.5, timeout=2.5)
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_clients"] == 4
    assert record["num_dropped"] == 1

    survivors = [c for c in num_samples if c != "c3"]
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in survivors
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_tolerant_mode_without_dropout_matches_fedavg():
    """Zero dropouts in tolerant mode: the unmask round removes only self masks and
    the aggregate equals plain weighted FedAvg of the full cohort."""
    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=3, frac_bits=16, threshold=2, dropout_tolerant=True
    )
    num_samples = {"c1": 12.0, "c2": 24.0, "c3": 6.0}
    local = {c: _client_params(model, s) for s, c in enumerate(num_samples, start=4)}
    clients = [(c, local[c], num_samples[c], False) for c in num_samples]

    coordinator = _run_round(PORT + 1, cfg, clients, timeout=3.0)
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_dropped"] == 0
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in num_samples
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_too_many_dropouts_fail_closed():
    """Survivors below max(required, threshold) must FAIL the round and leave params
    untouched — recovery never degrades below the Shamir threshold."""
    model = get_model("linear", in_features=4, num_classes=2)
    init = _client_params(model, 0)
    cfg = SecureAggregationConfig(
        min_clients=5, frac_bits=16, threshold=4, dropout_tolerant=True
    )
    num_samples = {f"c{i}": 10.0 for i in range(1, 6)}
    # 2 of 5 drop -> 3 survivors < threshold=4.
    clients = [(c, init, num_samples[c], c in ("c2", "c4")) for c in num_samples]

    coordinator = _run_round(PORT + 2, cfg, clients, completion_rate=0.5, timeout=1.5)
    record = coordinator.history[0]
    assert record["status"] == "FAILED"
    assert record["num_dropped"] == 2
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(init)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mixed_backend_cohort_refused_at_registration():
    """Mask-backend negotiation (host-Philox vs device-PRNG streams are
    wire-incompatible): the first enrollment pins the cohort backend and a mismatched
    registration is refused with 409 AT REGISTRATION — not discovered post-hoc as a
    garbage aggregate at dequantize."""

    async def scenario():
        server = HTTPServer(port=PORT + 3)
        await server.open_secagg(3)
        await server.start()
        try:
            k1, k2 = ClientKeyPair.generate(), ClientKeyPair.generate()
            async with HTTPClient(f"http://127.0.0.1:{PORT + 3}", "c1",
                                  timeout_s=10) as c1:
                assert await c1.register_secagg(k1.public_bytes(), 10.0,
                                                backend="host")
            async with HTTPClient(f"http://127.0.0.1:{PORT + 3}", "c2",
                                  timeout_s=10) as c2:
                # Mismatched backend -> refused at registration.
                assert not await c2.register_secagg(k2.public_bytes(), 10.0,
                                                    backend="device")
                # Same client re-enrolls with the negotiated backend -> accepted.
                assert await c2.register_secagg(k2.public_bytes(), 10.0,
                                                backend="host")
                roster_resp = await c2.check_server_status()
                assert roster_resp["status"] == "success"
            assert server.secagg_backend() == "host"
            assert len(server.secagg_client_order()) == 2
        finally:
            await server.stop()

    asyncio.run(scenario())


def test_evicted_client_cannot_submit_or_deposit():
    """Eviction is enforced at the wire: an evicted client's masked update and share
    deposit are refused with 403 (its round secrets were revealed — accepting its
    vector would let it push slow-but-alive members past the round barrier)."""
    import base64

    from aiohttp.test_utils import TestClient, TestServer

    async def scenario():
        server = HTTPServer(port=0)
        await server.open_secagg(3)
        model = get_model("linear", in_features=3, num_classes=2)
        await server.publish_model(_client_params(model, 0), 0)
        client = TestClient(TestServer(server._app))
        await client.start_server()
        try:
            for cid in ("c1", "c2", "c3"):
                pk = ClientKeyPair.generate().public_bytes()
                r = await client.post(
                    "/secagg/register",
                    json={"public_key": base64.b64encode(pk).decode(),
                          "num_samples": 10.0},
                    headers={"X-NanoFed-Client": cid},
                )
                assert r.status == 200
            await server.evict_secagg_clients(["c2"])
            assert server.secagg_active_order() == ["c1", "c3"]
            # Masked update from the evicted client: refused.
            r = await client.post(
                "/update", data=b"whatever",
                headers={"X-NanoFed-Client": "c2", "X-NanoFed-Round": "0",
                         "X-NanoFed-SecAgg": "masked"},
            )
            assert r.status == 403
            assert "evicted" in (await r.json())["message"]
            # Share deposit from the evicted client: refused (not in active cohort).
            r = await client.post(
                "/secagg/shares",
                data=json.dumps({"epk": base64.b64encode(bytes(32)).decode(),
                                 "blobs": {"c1": "x", "c3": "x"}}).encode(),
                headers={"X-NanoFed-Client": "c2", "X-NanoFed-Round": "0",
                         "Content-Type": "application/json"},
            )
            assert r.status == 403
        finally:
            await client.close()

    asyncio.run(scenario())


def test_signed_tolerant_round_with_dropout():
    """require_signatures=True covers the dropout-tolerant aux endpoints too: share
    deposits sign over session:round, unmask reveals over session:round — and the
    full signed round with a dropout still completes.  An unsigned deposit from an
    enrolled id bounces with 403."""
    from nanofed_tpu.security.signing import SecurityManager

    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=3, frac_bits=16, threshold=3, dropout_tolerant=True
    )
    ids = ["c1", "c2", "c3", "c4"]
    managers = {c: SecurityManager(key_size=1024) for c in ids}
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 30 + i) for i, c in enumerate(ids)}
    deposit_rejected = {}

    async def forge_deposit(client, rnd, mask_key, sealed, commitment):
        # Same payload, no signature: must bounce 403 and never count toward the
        # share barrier.  finally: an exception here (e.g. transient socket error)
        # must not leave the client unsigned for its HONEST requests.
        manager = client.security_manager
        client.security_manager = None
        try:
            ok = await client.deposit_secagg_shares(
                rnd, mask_key.public_bytes(), sealed,
                self_seed_commitment=commitment,
            )
            deposit_rejected[client.client_id] = not ok
        finally:
            client.security_manager = manager

    async def main():
        server = HTTPServer(
            port=PORT + 5,
            client_keys={c: m.get_public_key() for c, m in managers.items()},
            require_signatures=True,
        )
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=1, min_clients=4,
                                   min_completion_rate=0.5, round_timeout_s=2.5),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                _run_tolerant_client(PORT + 5, "c1", local["c1"], num_samples["c1"],
                                     cfg, security_manager=managers["c1"],
                                     pre_deposit_hook=forge_deposit),
                _run_tolerant_client(PORT + 5, "c2", local["c2"], num_samples["c2"],
                                     cfg, security_manager=managers["c2"]),
                _run_tolerant_client(PORT + 5, "c3", local["c3"], num_samples["c3"],
                                     cfg, security_manager=managers["c3"]),
                _run_tolerant_client(PORT + 5, "c4", local["c4"], num_samples["c4"],
                                     cfg, security_manager=managers["c4"],
                                     drop_before_submit=True),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    assert deposit_rejected == {"c1": True}
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_clients"] == 3
    assert record["num_dropped"] == 1
    survivors = ["c1", "c2", "c3"]
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in survivors
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params),
                         jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_enrollment_window_derives_threshold_from_actual_cohort():
    """THE round-4 verdict scenario (`serve --dropout-tolerant --min-clients 3` with 6
    enrolling clients): min_clients is a true MINIMUM — all 6 join the window, the
    roster freezes with a threshold derived from the REAL cohort (max(cfg, 6//2+1)=4,
    announced in the roster payload), and a round with one dropout still COMPLETES.
    Under the old static wiring (threshold = min_clients//2+1 = 2) a 6-cohort could
    never share at all: 2*2 <= 6 trips the split-view guard client-side."""
    model = get_model("linear", in_features=4, num_classes=2)
    # Exactly what the CLI wires for --min-clients 3: privacy floor min_clients-1,
    # threshold LEFT AT ITS DEFAULT (2) — the window derivation must override it.
    cfg = SecureAggregationConfig(min_clients=2, dropout_tolerant=True)
    ids = [f"c{i}" for i in range(1, 7)]
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 60 + i) for i, c in enumerate(ids)}
    clients = [(c, local[c], num_samples[c], c == "c4") for c in ids]

    coordinator = _run_round(PORT + 8, cfg, clients, min_clients=3,
                             completion_rate=1.0, timeout=4.0)
    # c4 was evicted after its dropout, so the post-round ACTIVE cohort is 5 and
    # the per-round threshold re-derivation reads 5//2+1 (the round itself ran at
    # the full 6-cohort's threshold 4 — pinned by completing with 5 reveals).
    assert coordinator.server.secagg_threshold() == 3
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_clients"] == 5
    assert record["num_dropped"] == 1
    survivors = [c for c in ids if c != "c4"]
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in survivors
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params),
                         jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_enrollment_window_refuses_late_joiners_after_freeze():
    """Once the window freezes (grace elapsed / max reached), a late registration is
    refused — the cohort AND the threshold derived from its size are fixed, and a
    late joiner would desynchronize every client's mask order."""

    async def scenario():
        server = HTTPServer(port=PORT + 9)
        await server.open_secagg(2, window=True, max_clients=3,
                           threshold_for=lambda n: n // 2 + 1)
        await server.start()
        try:
            keys = {c: ClientKeyPair.generate() for c in ("c1", "c2", "c3", "late")}
            for cid in ("c1", "c2", "c3"):
                async with HTTPClient(f"http://127.0.0.1:{PORT + 9}", cid,
                                      timeout_s=10) as c:
                    assert await c.register_secagg(keys[cid].public_bytes(), 10.0)
            # max_clients reached -> frozen implicitly, threshold derived from n=3.
            assert server.secagg_roster_complete()
            assert server.secagg_threshold() == 2
            async with HTTPClient(f"http://127.0.0.1:{PORT + 9}", "late",
                                  timeout_s=10) as c:
                assert not await c.register_secagg(keys["late"].public_bytes(), 10.0)
                # The frozen roster is served WITH the threshold clients share at.
                roster = await c.fetch_secagg_roster(timeout_s=2.0)
            assert roster.threshold == 2
            assert roster.client_order == ["c1", "c2", "c3"]
            # The round threshold tracks the ACTIVE cohort: after an eviction the
            # derivation re-runs over the survivors (a threshold frozen at the
            # enrollment size would brick every round once m < t).
            await server.evict_secagg_clients(["c3"])
            assert server.secagg_active_order() == ["c1", "c2"]
            assert server.secagg_threshold() == 2  # 2//2+1
        finally:
            await server.stop()

    asyncio.run(scenario())


def test_window_cap_below_minimum_is_refused_at_open():
    """A max_clients below the enrollment minimum would freeze the roster at a size
    the coordinator then waits on forever — open_secagg must refuse the
    configuration outright."""
    import pytest

    server = HTTPServer(port=0)
    with pytest.raises(ValueError, match="max_clients"):
        asyncio.run(server.open_secagg(5, window=True, max_clients=3,
                                       threshold_for=lambda n: n // 2 + 1))


def test_unsatisfiable_threshold_fails_fast_on_implicit_freeze_too():
    """The startup threshold>cohort validation must run on BOTH freeze paths: here
    max_clients freezes the roster implicitly at enrollment (no grace timer), and
    run() must still raise the configuration ValueError instead of burning
    num_rounds timeouts on rounds no client can ever share for."""
    import pytest

    model = get_model("linear", in_features=3, num_classes=2)
    cfg = SecureAggregationConfig(min_clients=2, threshold=10, dropout_tolerant=True)

    async def enroll_only(cid):
        identity = ClientKeyPair.generate()
        async with HTTPClient(f"http://127.0.0.1:{PORT + 12}", cid,
                              timeout_s=10) as client:
            assert await client.register_secagg(identity.public_bytes(), 10.0)

    async def main():
        server = HTTPServer(port=PORT + 12)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=2, min_clients=3, max_clients=3,
                                   round_timeout_s=5.0),
                secure=cfg,
            )
            enrollments = asyncio.gather(*(enroll_only(f"c{i}") for i in range(3)))
            with pytest.raises(ValueError, match="threshold 10 exceeds"):
                await asyncio.gather(coordinator.run(), enrollments)
        finally:
            await server.stop()

    asyncio.run(main())


def test_window_threshold_tracks_evictions_across_rounds():
    """5 enroll through the window (round threshold 3); two drop at round 1 and are
    evicted; round 2's 3-client active cohort re-derives threshold 2 and COMPLETES.
    With a threshold frozen at enrollment (3 < 4... still 3 here, but at 6 enrolled
    it would be 4 > 3 survivors) a shrunk cohort could never share again — this
    pins the per-round re-derivation end-to-end."""
    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(min_clients=2, dropout_tolerant=True)
    ids = [f"c{i}" for i in range(1, 7)]  # 6 clients: frozen-threshold would be 4
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 70 + i) for i, c in enumerate(ids)}

    async def main():
        server = HTTPServer(port=PORT + 11)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=3, min_clients=3,
                                   min_completion_rate=0.5, round_timeout_s=2.5),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                *(
                    _run_multi_round_client(
                        PORT + 11, c, local[c], num_samples[c], cfg,
                        drop_at_round=(1 if c in ("c5", "c6") else None),
                    )
                    for c in ids
                ),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    statuses = [(h["round"], h["status"], h["num_dropped"])
                for h in coordinator.history]
    assert statuses == [(0, "COMPLETED", 0), (1, "COMPLETED", 2),
                        (2, "COMPLETED", 0)]
    # Round 0/1 ran at the 6-cohort threshold; round 2's active cohort is 4, so the
    # announced threshold must have dropped to 4//2+1 = 3 (a frozen 4 would demand
    # 4 reveals from 4 survivors every round — fragile — and a frozen threshold
    # with one more eviction would be permanently unsatisfiable).
    assert coordinator.server.secagg_active_order() == ["c1", "c2", "c3", "c4"]
    assert coordinator.server.secagg_threshold() == 3


def test_wire_epk_substitution_aborts_client_side_before_masking():
    """The epk-substitution attack over the REAL wire: three clients enroll and
    deposit round shares through HTTP, then the server (actively malicious here)
    swaps its own ephemeral key into the relayed epk map for c2.  c1's inbox open
    must refuse with the attestation error — before masking anything — and the
    honest map must still open fine (the refusal is the attack's, not a false
    positive)."""
    import hashlib

    from nanofed_tpu.core.exceptions import AggregationError

    async def scenario():
        server = HTTPServer(port=PORT + 10)
        await server.open_secagg(3)
        model = get_model("linear", in_features=3, num_classes=2)
        await server.publish_model(_client_params(model, 0), 0)
        await server.start()
        cfg = SecureAggregationConfig(
            min_clients=2, frac_bits=16, threshold=2, dropout_tolerant=True
        )
        ids = ["c1", "c2", "c3"]
        identity = {c: ClientKeyPair.generate() for c in ids}
        try:
            clients = {}
            for cid in ids:
                clients[cid] = HTTPClient(f"http://127.0.0.1:{PORT + 10}", cid,
                                          timeout_s=10)
                await clients[cid].__aenter__()
                assert await clients[cid].register_secagg(
                    identity[cid].public_bytes(), 10.0
                )
            roster = await clients["c1"].fetch_secagg_roster()
            context = f"{clients['c1'].secagg_session}:0"
            for cid in ids:
                mask_key = ClientKeyPair.generate()
                self_seed, sealed = make_dropout_shares(
                    identity[cid], mask_key, roster.client_order,
                    roster.public_keys, cfg.threshold, my_id=cid, context=context,
                )
                assert await clients[cid].deposit_secagg_shares(
                    0, mask_key.public_bytes(), sealed,
                    self_seed_commitment=hashlib.sha256(self_seed).digest(),
                )
            # --- the attack: the server swaps c2's relayed ephemeral key ---
            honest_epks, inbox = await clients["c1"].fetch_secagg_inbox(0)
            server._round_share_epks["c2"] = ClientKeyPair.generate().public_bytes()
            forged_epks, inbox2 = await clients["c1"].fetch_secagg_inbox(0)
            try:
                open_share_inbox(identity["c1"], "c1", roster.public_keys,
                                 inbox2, forged_epks, context)
                raise AssertionError("substituted epk map was accepted")
            except AggregationError as e:
                assert "epk substitution" in str(e)
            # Honest map (captured before the swap): opens clean.
            held = open_share_inbox(identity["c1"], "c1", roster.public_keys,
                                    inbox, honest_epks, context)
            assert set(held) == set(ids)
        finally:
            for c in clients.values():
                await c.__aexit__(None, None, None)
            await server.stop()

    asyncio.run(scenario())


def test_multiround_eviction_keeps_later_rounds_fast():
    """Across rounds: round 0 completes with the full cohort, the round-1 dropout is
    EVICTED, and round 2 completes promptly with the shrunk cohort (no stall waiting
    for the corpse).  Pins the per-round fresh-secrets + eviction lifecycle the
    example demonstrates."""
    import time

    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=2, frac_bits=16, threshold=2, dropout_tolerant=True
    )
    ids = ["c1", "c2", "c3"]
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 40 + i) for i, c in enumerate(ids)}

    durations = {}

    async def main():
        server = HTTPServer(port=PORT + 6)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=3, min_clients=3,
                                   min_completion_rate=0.5, round_timeout_s=2.0),
                secure=cfg,
            )

            async def run_and_time():
                original = coordinator.train_round

                async def wrapped(round_number):
                    t = time.monotonic()
                    record = await original(round_number)
                    durations[round_number] = time.monotonic() - t
                    return record

                coordinator.train_round = wrapped
                return await coordinator.run()

            await asyncio.gather(
                run_and_time(),
                _run_multi_round_client(PORT + 6, "c1", local["c1"],
                                        num_samples["c1"], cfg),
                _run_multi_round_client(PORT + 6, "c2", local["c2"],
                                        num_samples["c2"], cfg),
                _run_multi_round_client(PORT + 6, "c3", local["c3"],
                                        num_samples["c3"], cfg, drop_at_round=1),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    statuses = [(h["round"], h["status"], h["num_dropped"])
                for h in coordinator.history]
    assert statuses == [(0, "COMPLETED", 0), (1, "COMPLETED", 1),
                        (2, "COMPLETED", 0)]
    # Round 1 pays the detection timeout for the dropped client; round 2 must NOT
    # (c3 was evicted, so the shrunk cohort completes well under the 2s timeout).
    assert durations[1] >= 2.0
    assert durations[2] < durations[1]


def test_drop_before_share_barrier_fails_round_and_evicts():
    """A client that vanishes BEFORE depositing its round shares stalls the share
    barrier (nobody can mask), so that round FAILS — but the non-depositor is
    evicted and the NEXT round completes from the shrunk cohort.  (Dropping after
    the barrier is the recoverable case covered elsewhere.)"""
    model = get_model("linear", in_features=4, num_classes=2)
    cfg = SecureAggregationConfig(
        min_clients=2, frac_bits=16, threshold=2, dropout_tolerant=True
    )
    ids = ["c1", "c2", "c3"]
    num_samples = {c: 10.0 * (i + 1) for i, c in enumerate(ids)}
    local = {c: _client_params(model, 50 + i) for i, c in enumerate(ids)}

    async def vanishing_client(cid):
        """Enrolls, then never deposits round shares (crash before the barrier)."""
        identity = ClientKeyPair.generate()
        async with HTTPClient(f"http://127.0.0.1:{PORT + 7}", cid,
                              timeout_s=30) as client:
            assert await client.register_secagg(
                identity.public_bytes(), num_samples[cid]
            )
            await client.fetch_secagg_roster()

    async def main():
        server = HTTPServer(port=PORT + 7)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, _client_params(model, 0),
                NetworkRoundConfig(num_rounds=2, min_clients=3,
                                   min_completion_rate=0.5, round_timeout_s=2.0),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                _run_multi_round_client(PORT + 7, "c1", local["c1"],
                                        num_samples["c1"], cfg,
                                        tolerate_failed_rounds=True),
                _run_multi_round_client(PORT + 7, "c2", local["c2"],
                                        num_samples["c2"], cfg,
                                        tolerate_failed_rounds=True),
                vanishing_client("c3"),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    assert [h["status"] for h in coordinator.history] == ["FAILED", "COMPLETED"]
    # Round 0's failure record names the eviction; round 1 ran without c3.
    assert "evicted" in coordinator.history[0]["reason"]
    assert coordinator.history[1]["num_clients"] == 2
    assert coordinator.history[1]["num_dropped"] == 0
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=1, params=local[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in ["c1", "c2"]
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params),
                         jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)
