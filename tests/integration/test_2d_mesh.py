"""Acceptance: the 2-D ``clients x model`` mesh through the full Coordinator.

On the virtual 8-device CPU mesh, a ``(4, 2)`` run — single rounds AND fused
round blocks — produces params within numerical tolerance of the 1-D run,
params are verifiably model-sharded between rounds (``.sharding``, not shape),
and ``check_input_shardings`` + strict mode pass on the 2-D layout.

Single-batch clients throughout: the comparisons cross program structures and
the multi-batch epoch-shuffle PRNG is not bit-stable across those on every
jaxlib CPU backend (see ``tests/unit/parallel/test_round_step.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.analysis.contracts import ContractViolation, check_input_shardings
from nanofed_tpu.data import federate, pack_eval, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration.coordinator import Coordinator, CoordinatorConfig
from nanofed_tpu.orchestration.types import RoundStatus
from nanofed_tpu.parallel import MODEL_AXIS, make_mesh, shard_params
from nanofed_tpu.trainer import TrainingConfig


def _coordinator(tmp_path, mesh_shape=None, **cfg_kw):
    m = get_model("mlp", in_features=8, hidden=16, num_classes=4)
    ds = synthetic_classification(512, 4, (8,), seed=0)
    cd = federate(ds, num_clients=8, scheme="iid", batch_size=64, seed=0)
    _, test = ds, synthetic_classification(128, 4, (8,), seed=1)
    cfg = CoordinatorConfig(
        num_rounds=4, seed=0, base_dir=tmp_path, save_metrics=False, **cfg_kw
    )
    return Coordinator(
        m, cd, cfg,
        training=TrainingConfig(batch_size=64, local_epochs=1),
        eval_data=pack_eval(test, batch_size=64),
        mesh_shape=mesh_shape,
        strict=True,
    )


def _assert_params_close(got, want, atol=2e-5):
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol)


def test_2d_single_round_trajectory_matches_1d(tmp_path, devices):
    c1 = _coordinator(tmp_path / "a")
    h1 = c1.run()
    c2 = _coordinator(tmp_path / "b", mesh_shape=(4, 2))
    h2 = c2.run()
    assert [m.status for m in h2] == [RoundStatus.COMPLETED] * 4
    for m1, m2 in zip(h1, h2):
        assert m1.agg_metrics["loss"] == pytest.approx(m2.agg_metrics["loss"], rel=1e-5)
    _assert_params_close(c2.params, c1.params)
    # The acceptance assertion: params are MODEL-SHARDED between rounds, proven
    # via the arrays' shardings (every MLP leaf has an even dim -> all sharded).
    for leaf in jax.tree.leaves(c2.params):
        assert not leaf.sharding.is_fully_replicated
        assert MODEL_AXIS in {
            a for e in leaf.sharding.spec if e is not None
            for a in ((e,) if isinstance(e, str) else tuple(e))
        }
    # Server opt state lives in the same layout family (replicated-or-model-sharded).
    check_input_shardings(c2._data, c2.server_state)


def test_2d_fused_round_block_trajectory_matches_1d(tmp_path, devices):
    c1 = _coordinator(tmp_path / "a", rounds_per_block=2)
    h1 = c1.run()
    c2 = _coordinator(tmp_path / "b", mesh_shape=(4, 2), rounds_per_block=2)
    h2 = c2.run()
    for m1, m2 in zip(h1, h2):
        assert m1.agg_metrics["loss"] == pytest.approx(m2.agg_metrics["loss"], rel=1e-5)
    _assert_params_close(c2.params, c1.params)
    for leaf in jax.tree.leaves(c2.params):
        assert not leaf.sharding.is_fully_replicated


def test_2d_cohort_sampling_matches_1d(tmp_path, devices):
    c1 = _coordinator(tmp_path / "a", participation_rate=0.5, rounds_per_block=2)
    h1 = c1.run()
    c2 = _coordinator(
        tmp_path / "b", mesh_shape=(4, 2), participation_rate=0.5, rounds_per_block=2
    )
    h2 = c2.run()
    assert [m.num_clients for m in h1] == [m.num_clients for m in h2]
    _assert_params_close(c2.params, c1.params)


def test_2d_eval_runs_on_sharded_params(tmp_path, devices):
    c = _coordinator(tmp_path, mesh_shape=(4, 2), eval_every=2)
    history = c.run()
    evaled = [m for m in history if m.eval_metrics]
    assert len(evaled) == 2
    final = c.evaluate()
    assert np.isfinite(final["loss"])


def test_check_input_shardings_accepts_2d_layout(devices):
    mesh = make_mesh(shape=(4, 2))
    params = {"k": jnp.zeros((8, 16)), "odd": jnp.zeros((3,))}
    placed = shard_params(params, mesh)
    data = jax.device_put(
        jnp.zeros((8, 4)), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("clients"))
    )
    check_input_shardings({"x": data}, placed)  # must not raise


def test_check_input_shardings_rejects_client_sharded_params(devices):
    mesh = make_mesh(shape=(4, 2))
    bad = jax.device_put(
        jnp.zeros((8, 16)),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("clients")),
    )
    with pytest.raises(ContractViolation, match="model"):
        check_input_shardings({}, {"k": bad})


def test_check_input_shardings_rejects_model_sharded_data(devices):
    mesh = make_mesh(shape=(4, 2))
    bad = jax.device_put(
        jnp.zeros((8, 4)),
        jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("clients", "model")
        ),
    )
    with pytest.raises(ContractViolation, match="replicated"):
        check_input_shardings({"x": bad}, {})


def test_2d_checkpoint_gathers_whole_params(tmp_path, devices):
    """The publish path gathers the model shards once at the block boundary:
    what lands in the store is whole host arrays, resumable on ANY mesh."""

    class Store:
        def __init__(self):
            self.checkpoints = []

        def checkpoint(self, **kw):
            self.checkpoints.append(kw)

        def restore_latest(self):
            return None

    m = get_model("mlp", in_features=8, hidden=16, num_classes=4)
    ds = synthetic_classification(512, 4, (8,), seed=0)
    cd = federate(ds, num_clients=8, scheme="iid", batch_size=64, seed=0)
    cfg = CoordinatorConfig(num_rounds=2, seed=0, base_dir=tmp_path, save_metrics=False)
    store = Store()
    c = Coordinator(
        m, cd, cfg, training=TrainingConfig(batch_size=64, local_epochs=1),
        mesh_shape=(4, 2), state_store=store,
    )
    c.run()
    assert store.checkpoints
    for kw in store.checkpoints:
        for leaf in jax.tree.leaves(kw["params"]):
            assert isinstance(leaf, np.ndarray)
        for leaf in jax.tree.leaves(kw["server_state"]):
            assert isinstance(leaf, np.ndarray)
    # The device copy is still model-sharded after publishing.
    assert any(
        not leaf.sharding.is_fully_replicated for leaf in jax.tree.leaves(c.params)
    )
