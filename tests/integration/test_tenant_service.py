"""Multi-tenant federation service end to end (tier-1-safe smoke + slow leg).

``test_tenants_smoke`` is the `make tenants-smoke` / CI gate: two tenants
(different models, different serving paths) run CONCURRENTLY on one shared
transport and one VirtualClock while a seeded wire-fault storm targets
exactly one of them — the untargeted tenant must complete every round and
lose zero submits, and the chaos counters must show the storm actually fired
against the targeted tenant only.  The 3-tenant leg (three distinct models)
is marked ``slow`` per the tier-1 budget policy and runs in the tenants-smoke
CI job instead."""

import json
import math

import pytest

from nanofed_tpu.observability.telemetry import summarize_telemetry
from nanofed_tpu.service import (
    TenantQuota,
    TenantSpec,
    default_tenant_specs,
    run_tenant_service,
)


def _specs_2tenant(rounds=3):
    return [
        TenantSpec(
            name="alpha", model="digits_mlp", algorithm="fedbuff",
            rounds=rounds, async_buffer_k=8,
            quota=TenantQuota(ingest_capacity=32, ingest_batch=8),
        ),
        TenantSpec(
            name="bravo", model="mlp", algorithm="fedbuff",
            rounds=rounds, async_buffer_k=8,
        ),
    ]


def test_tenants_smoke(tmp_path):
    telemetry_dir = tmp_path / "telemetry"
    # One submit per client: the update buffers are latest-wins PER CLIENT,
    # so distinct clients (not repeat submits) are the aggregatable supply —
    # 32 clients comfortably feed 3 aggregations of K=8.
    artifact = run_tenant_service(
        _specs_2tenant(),
        clients_per_tenant=32,
        submits_per_client=1,
        chaos_tenant="alpha",
        virtual_clock=True,
        sequential_baseline=False,
        out_dir=tmp_path,
        telemetry_dir=telemetry_dir,
        tag="smoke",
    )
    # The artifact landed and parses.
    on_disk = json.loads((tmp_path / "tenants_smoke.json").read_text())
    assert on_disk["record_type"] == "tenants"

    alpha = artifact["tenants"]["alpha"]
    bravo = artifact["tenants"]["bravo"]
    # The storm fired against alpha — and ONLY alpha.
    assert alpha["chaos_injected_total"] > 0
    assert bravo["chaos_injected_total"] == 0
    # Isolation: the untargeted tenant completed EVERY round and lost no
    # submits while its neighbor absorbed a drop/ack-drop/delay storm.
    assert bravo["rounds_completed"] == bravo["rounds_target"]
    assert bravo["failed_submits"] == 0
    assert artifact["isolation"]["zero_rounds_lost"]
    assert artifact["isolation"]["zero_failed_submits"]
    # The targeted tenant still made progress (drops are retried past).
    assert alpha["rounds_completed"] > 0
    # Finite p99 on both tenants.
    for t in (alpha, bravo):
        assert t["submit_latency_s"]["p99_s"] is not None
        assert math.isfinite(t["submit_latency_s"]["p99_s"])
    # The scheduler actually multiplexed the pool: both tenants held leases.
    sched = artifact["scheduler"]["tenants"]
    assert sched["alpha"]["leases"] > 0
    assert sched["bravo"]["leases"] > 0

    # metrics-summary digests the per-tenant telemetry records.
    summary = summarize_telemetry(telemetry_dir / "telemetry.jsonl")
    assert set(summary["tenants"]) == {"alpha", "bravo"}
    assert summary["tenants"]["bravo"]["rounds_completed"] == \
        bravo["rounds_completed"]
    assert summary["tenants"]["alpha"]["chaos_injected_total"] > 0


def test_fedavg_sync_tenant_completes(tmp_path):
    """A synchronous FedAvg tenant (cohort barrier) behind the same service
    machinery: rounds complete from swarm traffic alone."""
    # Uniform arrivals at a low rate spread the population across both
    # cohort rounds: the barrier closes on count, so late arrivals stamp —
    # and fill — round 1.
    artifact = run_tenant_service(
        [TenantSpec(name="sync", model="linear", algorithm="fedavg",
                    rounds=2, min_clients=3)],
        clients_per_tenant=12,
        submits_per_client=2,
        arrival="uniform",
        arrival_rate=100.0,
        chaos_tenant=None,
        virtual_clock=True,
        sequential_baseline=False,
        out_dir=None,
        profile_programs=False,
    )
    t = artifact["tenants"]["sync"]
    assert t["rounds_completed"] == 2
    assert t["failed_submits"] == 0


def test_admission_error_surfaces_at_add_tenant():
    """A tenant whose footprint cannot pack onto the pool is refused at
    admission — with the packing math — and nothing is mounted."""
    import asyncio

    from nanofed_tpu.service import AdmissionError, FederationService

    async def scenario():
        service = FederationService(
            port=0, hbm_budget_bytes=1024, profile_programs=False
        )
        with pytest.raises(AdmissionError) as e:
            service.add_tenant(TenantSpec(name="fat", model="digits_mlp"))
        assert "budget 1,024 B" in str(e.value)
        assert service.tenants() == []
        assert service.transport.tenants() == []

    asyncio.new_event_loop().run_until_complete(scenario())


def test_failed_construction_unmounts_the_tenant():
    """A spec that fails AFTER the HTTP session mounted (bad round config)
    must not leave a half-configured session occupying the tenant name."""
    import asyncio

    from nanofed_tpu.service import FederationService

    async def scenario():
        service = FederationService(port=0, profile_programs=False)
        with pytest.raises(ValueError):
            # async_buffer_k=0 passes TenantSpec validation but fails
            # NetworkRoundConfig's post-init — after the session mounted.
            service.add_tenant(TenantSpec(name="alpha", algorithm="fedbuff",
                                          async_buffer_k=0))
        assert service.transport.tenants() == []
        # The name is free again: a corrected retry mounts cleanly.
        service.add_tenant(TenantSpec(name="alpha", rounds=1))
        assert service.tenants() == ["alpha"]

    asyncio.new_event_loop().run_until_complete(scenario())


@pytest.mark.slow
def test_three_tenants_concurrent_vs_sequential(tmp_path):
    """Three distinct (model, algorithm, path) tenants concurrent vs the
    sequential baseline — the artifact's full shape.  Slow (compiles the
    ingest ladder + profiles three aggregation programs); the tenants-smoke
    CI job covers it un-filtered."""
    # Sizing rule (sync tenants): clients >= ~2 x rounds x min_clients with
    # spread arrivals, since update buffers are latest-wins per client.
    artifact = run_tenant_service(
        default_tenant_specs(3, rounds=3, async_buffer_k=8, min_clients=4),
        clients_per_tenant=24,
        submits_per_client=2,
        arrival="uniform",
        arrival_rate=100.0,
        chaos_tenant=True,
        virtual_clock=True,
        sequential_baseline=True,
        out_dir=tmp_path,
        tag="3t",
    )
    assert len(artifact["tenants"]) == 3
    models = {t["model"] for t in artifact["tenants"].values()}
    algos = {t["algorithm"] for t in artifact["tenants"].values()}
    assert len(models) == 3  # genuinely distinct jobs
    assert algos == {"fedbuff", "fedavg"}
    assert artifact["isolation"]["zero_rounds_lost"]
    assert artifact["sequential"]["aggregate_rounds_per_sec"] is not None
    assert artifact["concurrent"]["aggregate_rounds_per_sec"] is not None
