"""End-to-end parameter-efficient federation (ISSUE 15 tentpole).

Three layers of evidence:

* the **Coordinator** federates adapter trees over the transformer workload —
  loss descends, strict mode passes on a 2-D mesh, fused blocks reproduce
  single rounds, checkpoints resume, the program catalog carries the adapter
  program (compile-heavy transformer legs are marked ``slow``: they run in the
  dedicated adapter-smoke CI job, not tier-1 — see ROADMAP budget note);
* the **wire** carries only adapter deltas — the q8/topk codecs and the
  ``_pending_base`` error-feedback contract hold on adapter-shaped trees
  under chaos drops/duplicates (fast: no model compiles, pure wire);
* the **CLI/experiments** surface: ``run_experiment(adapter_rank=)`` summary
  fields and refusals.
"""

import asyncio
import json

import jax
import numpy as np
import pytest

from nanofed_tpu.adapters import AdapterSpec, init_adapters, merge_adapters
from nanofed_tpu.data import federate, pack_eval, synthetic_token_streams
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration.coordinator import Coordinator, CoordinatorConfig
from nanofed_tpu.orchestration.types import RoundStatus
from nanofed_tpu.trainer import TrainingConfig

VOCAB, SEQ, WIDTH, DEPTH, HEADS = 32, 8, 32, 2, 2
C = 8
PORT = 8931


def _model():
    return get_model(
        "transformer_lm", vocab=VOCAB, seq_len=SEQ, width=WIDTH,
        depth=DEPTH, heads=HEADS,
    )


def _data(seed=0):
    ds = synthetic_token_streams(64 * C, vocab=VOCAB, seq_len=SEQ, seed=seed)
    return federate(ds, num_clients=C, batch_size=16, seed=seed)


def _training():
    return TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.5)


def _coordinator(tmp_path, data, **kw):
    cfg_kw = kw.pop("cfg", {})
    return Coordinator(
        model=_model(), train_data=data,
        config=CoordinatorConfig(
            num_rounds=kw.pop("num_rounds", 4), seed=0, base_dir=tmp_path,
            **cfg_kw,
        ),
        training=_training(), adapter=kw.pop("adapter", AdapterSpec(rank=4)),
        **kw,
    )


# ---------------------------------------------------------------------------
# Coordinator legs (transformer compiles -> slow: adapter-smoke CI job)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_strict_2d_adapter_federation_trains(tmp_path):
    """The headline integration: strict mode + FSDP model axis + frozen base.
    Every dispatch runs under transfer_guard('disallow'); the contract check
    accepts the frozen-base + trainable-adapter split."""
    data = _data()
    test = synthetic_token_streams(128, vocab=VOCAB, seq_len=SEQ, seed=9)
    coord = _coordinator(
        tmp_path, data, strict=True, mesh_shape=(4, 2),
        eval_data=pack_eval(test, batch_size=64), cfg={"eval_every": 4},
    )
    hist = coord.run()
    assert all(h.status == RoundStatus.COMPLETED for h in hist)
    losses = [h.agg_metrics["loss"] for h in hist]
    assert losses[-1] < losses[0], losses
    # adapter state is genuinely model-sharded on the 2-D mesh
    assert any(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(coord.params)
    )
    # base params were bit-stable across the whole run
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(coord.base_params)["tok_emb"]),
        np.asarray(coord._adapter_base_host["tok_emb"]),
    )
    # eval consumed the merged model (merge counter moved)
    assert coord._merge_count >= 1


@pytest.mark.slow
def test_fused_adapter_blocks_reproduce_single_rounds(tmp_path):
    data = _data()
    fused = _coordinator(tmp_path / "f", data, cfg={"rounds_per_block": 2})
    assert fused._round_block is not None  # adapter mode IS fused-capable
    single = _coordinator(tmp_path / "s", data)
    lf = [h.agg_metrics["loss"] for h in fused.run()]
    ls = [h.agg_metrics["loss"] for h in single.run()]
    np.testing.assert_allclose(lf, ls, atol=1e-5)


@pytest.mark.slow
def test_adapter_checkpoint_resume(tmp_path):
    from nanofed_tpu.persistence.state_store import FileStateStore

    data = _data()
    store = FileStateStore(tmp_path / "store")
    c1 = _coordinator(tmp_path, data, num_rounds=2, state_store=store)
    c1.run()
    mid = jax.device_get(c1.params)
    c2 = _coordinator(
        tmp_path, data, num_rounds=4,
        state_store=FileStateStore(tmp_path / "store"),
    )
    assert c2.current_round == 2  # resumed
    for a, b in zip(jax.tree.leaves(jax.device_get(c2.params)),
                    jax.tree.leaves(mid)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    hist = c2.run()
    assert [h.round_id for h in hist] == [2, 3]


@pytest.mark.slow
def test_adapter_program_in_catalog_and_profiles(tmp_path):
    data = _data()
    coord = _coordinator(tmp_path, data, cfg={"rounds_per_block": 2})
    names = coord.program_catalog.names()
    assert "adapter_round_step" in names
    assert "adapter_round_block" in names
    reports = coord.profile_programs()
    by_name = {r.program: r for r in reports}
    step = by_name["adapter_round_step"]
    assert step.flops > 0 and step.peak_bytes > 0
    assert step.attrs["adapter_rank"] == 4


@pytest.mark.slow
def test_run_experiment_adapter_summary(tmp_path):
    from nanofed_tpu.experiments import run_experiment

    summary = run_experiment(
        model="transformer_lm", num_clients=4, num_rounds=2, local_epochs=1,
        batch_size=16, train_size=256, out_dir=tmp_path, adapter_rank=2,
        telemetry_dir=tmp_path / "tel",
    )
    assert summary["adapter"]["rank"] == 2
    assert summary["adapter"]["adapter_params"] > 0
    assert summary["adapter"]["base_params"] > summary["adapter"]["adapter_params"]
    # the summary's merge count includes the post-run final evaluation
    assert summary["adapter"]["merges"] >= 1
    assert summary["rounds_completed"] == 2
    # metrics-summary digests the adapter telemetry record (the stream closes
    # at run() end, BEFORE the summary's final eval — merges is present, and
    # counts only in-run merges)
    from nanofed_tpu.observability import summarize_telemetry

    digest = summarize_telemetry(tmp_path / "tel" / "telemetry.jsonl")
    assert digest["adapter"]["rank"] == 2
    assert digest["adapter"]["merges"] >= 0
    assert digest["adapter"]["adapter_params"] > 0


@pytest.mark.slow
def test_cli_run_adapter_rank(tmp_path, capsys):
    from nanofed_tpu.cli import main

    rc = main([
        "run", "--model", "transformer_lm", "--clients", "4", "--rounds", "1",
        "--epochs", "1", "--batch-size", "16", "--train-size", "256",
        "--adapter-rank", "2", "--out-dir", str(tmp_path),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["adapter"]["rank"] == 2


# ---------------------------------------------------------------------------
# Fast legs (tier-1): refusals + small-model adapter coordinator
# ---------------------------------------------------------------------------


def test_adapter_refuses_scaffold_and_custom_fit(tmp_path):
    data = _data()
    with pytest.raises(ValueError, match="scaffold"):
        _coordinator(tmp_path, data, scaffold=True)
    with pytest.raises(ValueError, match="local_fit"):
        _coordinator(tmp_path, data, local_fit=lambda g, d, r: None)


def test_adapter_alpha_requires_rank():
    from nanofed_tpu.core.exceptions import NanoFedError
    from nanofed_tpu.experiments import run_experiment

    with pytest.raises(NanoFedError, match="adapter_alpha"):
        run_experiment(model="mlp", adapter_alpha=8.0, train_size=64)


def test_mlp_adapter_federation_fast(tmp_path):
    """Tier-1 adapter coverage without a transformer compile: adapters are
    model-agnostic, so a small-MLP adapter federation exercises the same
    frozen-base round program in seconds."""
    from nanofed_tpu.data import synthetic_classification

    model = get_model("mlp", in_features=16, hidden=32, num_classes=4)
    ds = synthetic_classification(256, num_classes=4, shape=(16,), seed=0)
    data = federate(ds, num_clients=C, batch_size=16, seed=0)
    spec = AdapterSpec(rank=2, min_dim=4)
    coord = Coordinator(
        model=model, train_data=data,
        config=CoordinatorConfig(num_rounds=3, seed=0, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.5),
        adapter=spec, strict=True,
    )
    hist = coord.run()
    losses = [h.agg_metrics["loss"] for h in hist]
    assert losses[-1] < losses[0]
    # merged model == base + merged adapter deltas, reconstructible host-side
    merged = jax.device_get(coord.merged_params())
    want = merge_adapters(
        coord._adapter_base_host, jax.device_get(coord.params), spec
    )
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


# ---------------------------------------------------------------------------
# Wire legs (fast: no model compiles): adapter deltas over HTTP + chaos
# ---------------------------------------------------------------------------


def _adapter_wire_fixture():
    model = get_model(
        "transformer_lm", vocab=64, seq_len=8, width=16, depth=1, heads=2
    )
    base = model.init(jax.random.key(0))
    spec = AdapterSpec(rank=2)
    adapters = init_adapters(spec, base, rng=0)
    rng = np.random.default_rng(3)
    trained = jax.tree.map(
        lambda x: np.asarray(x, np.float32)
        + rng.normal(0, 0.01, x.shape).astype(np.float32),
        adapters,
    )
    return adapters, trained


def test_adapter_deltas_ride_q8_over_http():
    """Only the adapter tree crosses the wire, on the existing q8 codec —
    the server reconstructs within quantization error."""
    from nanofed_tpu.communication.http_client import HTTPClient
    from nanofed_tpu.communication.http_server import HTTPServer

    adapters, trained = _adapter_wire_fixture()

    async def main():
        server = HTTPServer(port=PORT)
        await server.start()
        try:
            await server.publish_model(adapters, round_number=0)
            async with HTTPClient(
                f"http://127.0.0.1:{PORT}", "c1", timeout_s=10,
                update_encoding="q8-delta",
            ) as c:
                fetched = await c.fetch_global_model(like=adapters)
                for a, b in zip(jax.tree.leaves(fetched),
                                jax.tree.leaves(adapters)):
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
                assert await c.submit_update(trained, {"loss": 1.0})
            (update,) = await server.drain_updates()
            for got, want, start in zip(
                jax.tree.leaves(update.params), jax.tree.leaves(trained),
                jax.tree.leaves(adapters),
            ):
                step = float(
                    np.max(np.abs(np.asarray(want) - np.asarray(start, np.float32)))
                ) / 127.0
                np.testing.assert_allclose(
                    np.asarray(got, np.float32), np.asarray(want),
                    atol=step + 1e-7,
                )
        finally:
            await server.stop()

    asyncio.run(main())


def test_pending_base_error_feedback_holds_for_adapter_deltas():
    """The ``_pending_base`` contract on adapter-shaped trees: a rejected
    topk8 submit folds the WHOLE adapter delta into the residual exactly once
    (idempotent through a duplicate retry), and the accepted retry conserves
    mass — sent + residual == one delta."""
    from nanofed_tpu.communication.http_client import HTTPClient
    from nanofed_tpu.communication.http_server import HTTPServer

    adapters, trained = _adapter_wire_fixture()
    port = PORT + 1

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            await server.publish_model(adapters, round_number=0)
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
                update_encoding="topk8-delta", topk_fraction=0.25,
            ) as c:
                await c.fetch_global_model(like=adapters)
                full_delta = jax.tree.map(
                    lambda p, g: np.asarray(p, np.float32)
                    - np.asarray(g, np.float32),
                    trained, adapters,
                )
                # Stale round -> rejection -> whole delta accumulated.
                c.current_round = 7
                assert not await c.submit_update(trained, {"loss": 1.0})
                for want, got in zip(jax.tree.leaves(full_delta),
                                     jax.tree.leaves(c._residual)):
                    np.testing.assert_allclose(np.asarray(got), want, atol=1e-7)
                # Duplicate rejection: the fold is pinned, nothing double-counts.
                assert not await c.submit_update(trained, {"loss": 1.0})
                for want, got in zip(jax.tree.leaves(full_delta),
                                     jax.tree.leaves(c._residual)):
                    np.testing.assert_allclose(np.asarray(got), want, atol=1e-7)
                # Accepted retry: conservation on every adapter leaf.
                c.current_round = 0
                assert await c.submit_update(trained, {"loss": 1.0})
                (update,) = await server.drain_updates()
                for got, start, res, want in zip(
                    jax.tree.leaves(update.params), jax.tree.leaves(adapters),
                    jax.tree.leaves(c._residual), jax.tree.leaves(full_delta),
                ):
                    sent = (np.asarray(got, np.float32)
                            - np.asarray(start, np.float32))
                    np.testing.assert_allclose(
                        sent + np.asarray(res), want, atol=1e-3
                    )
        finally:
            await server.stop()

    asyncio.run(main())


def test_fedbuff_duplicate_storm_changes_adapters_exactly_once():
    """Chaos duplicates on the adapter wire: a same-key duplicate storm into
    the async FedBuff engine must move the aggregated adapter state exactly
    once — the idempotent-submit dedup window holds for adapter payloads."""
    from nanofed_tpu.communication.http_client import HTTPClient
    from nanofed_tpu.communication.http_server import HTTPServer
    from nanofed_tpu.communication.network_coordinator import (
        NetworkCoordinator,
        NetworkRoundConfig,
    )

    adapters, trained = _adapter_wire_fixture()
    port = PORT + 2

    async def main():
        server = HTTPServer(port=port)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, adapters,
                NetworkRoundConfig(
                    num_rounds=1, async_buffer_k=2, round_timeout_s=20,
                    poll_interval_s=0.01,
                ),
            )
            run_task = asyncio.create_task(coordinator.run())
            async with HTTPClient(
                f"http://127.0.0.1:{port}", "c1", timeout_s=10,
            ) as c1, HTTPClient(
                f"http://127.0.0.1:{port}", "c2", timeout_s=10,
            ) as c2:
                await c1.fetch_global_model(like=adapters)
                await c2.fetch_global_model(like=adapters)
                assert await c1.submit_update(trained, {"loss": 1.0})
                # duplicate storm: same logical submit re-sent 3x
                for _ in range(3):
                    assert await c1.resend_last_update()
                other = jax.tree.map(
                    lambda x: np.asarray(x, np.float32) + 0.005, adapters
                )
                assert await c2.submit_update(other, {"loss": 1.0})
            history = await asyncio.wait_for(run_task, timeout=30)
            assert history[0]["status"] == "COMPLETED"
            # exactly one aggregation from exactly two distinct updates
            assert history[0]["num_clients"] == 2
            got = jax.device_get(coordinator.params)
            want = jax.tree.map(
                lambda a, b: (np.asarray(a, np.float32)
                              + np.asarray(b, np.float32)) / 2,
                trained, other,
            )
            for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
                np.testing.assert_allclose(np.asarray(g, np.float32), w,
                                           atol=1e-5)
        finally:
            await server.stop()

    asyncio.run(main())
