"""CLI surface: info / run (incl. budget-calibrated DP) / serve (incl. secure mode).

The reference's CLI entry point dangles (``pyproject.toml:22-23`` names a module that
does not exist); these tests pin that ours actually drives the stack end-to-end.
"""

import asyncio
import json
import threading

import jax
import numpy as np
import pytest

from nanofed_tpu.cli import main


def test_info(capsys):
    assert main(["info"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert "mnist_cnn" in payload["models"]
    assert payload["devices"]


def test_run_with_calibrated_dp(tmp_path, capsys):
    rc = main([
        "run", "--model", "digits_mlp", "--clients", "8", "--rounds", "2",
        "--epochs", "1", "--batch-size", "16", "--lr", "0.3",
        "--out-dir", str(tmp_path), "--dp-epsilon", "4.0",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["rounds_completed"] == 2
    # Budget calibration: the spend must land within the requested epsilon.
    assert 0 < summary["privacy_spent"]["epsilon_spent"] <= 4.0 + 1e-6


def test_serve_secure_round(capsys):
    """`nanofed-tpu serve --secure` hosts a masked round that real clients complete."""
    pytest.importorskip("cryptography")
    from nanofed_tpu.communication import HTTPClient
    from nanofed_tpu.models import get_model
    from nanofed_tpu.security.secure_agg import (
        ClientKeyPair,
        SecureAggregationConfig,
        mask_update,
    )

    import socket

    with socket.socket() as sock:  # free port: parallel/leaked runs can't collide
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
    model = get_model("digits_mlp")  # default hidden must match serve's
    init = model.init(jax.random.key(0))
    cfg = SecureAggregationConfig(min_clients=3)
    rc_holder = {}

    def run_server():
        rc_holder["rc"] = main([
            "serve", "--model", "digits_mlp", "--port", str(port), "--rounds", "1",
            "--min-clients", "3", "--timeout", "30", "--secure",
        ])

    async def run_client(cid):
        kp = ClientKeyPair.generate()
        async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30) as c:
            for _ in range(200):
                try:
                    if await c.register_secagg(kp.public_bytes(), 10.0):
                        break
                except OSError:
                    pass  # server thread still binding the port
                await asyncio.sleep(0.05)
            roster = await c.fetch_secagg_roster()
            params = None
            for _ in range(200):
                try:
                    params, rnd, active = await c.fetch_global_model(like=init)
                    break
                except Exception:
                    await asyncio.sleep(0.05)
            assert params is not None
            masked = mask_update(
                model.init(jax.random.key(3)), roster.index_of(cid), kp,
                roster.ordered_keys(), rnd, cfg, weight=roster.weights[cid],
            )
            assert await c.submit_masked_update(masked, {})

    async def clients():
        await asyncio.gather(*(run_client(f"c{i}") for i in range(3)))

    # serve's default digits_mlp init must match the clients' template shapes.
    server_thread = threading.Thread(target=run_server, daemon=True)
    server_thread.start()
    asyncio.run(clients())
    server_thread.join(timeout=60)
    assert not server_thread.is_alive()
    assert rc_holder["rc"] == 0
    history = json.loads(capsys.readouterr().out)
    assert history[0]["status"] == "COMPLETED" and history[0]["secure"] is True


def test_serve_async_buffer_round(capsys):
    """`serve --async-buffer K` hosts FedBuff aggregations that real clients feed
    with no cohort barrier."""
    import socket

    from nanofed_tpu.communication import HTTPClient
    from nanofed_tpu.models import get_model

    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
    model = get_model("digits_mlp")
    init = model.init(jax.random.key(0))
    rc_holder = {}

    def run_server():
        rc_holder["rc"] = main([
            "serve", "--model", "digits_mlp", "--port", str(port), "--rounds", "3",
            "--timeout", "30", "--async-buffer", "2", "--staleness-window", "4",
        ])

    async def run_client(cid, seed):
        async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30) as c:
            params = None
            for _ in range(200):
                try:
                    params, rnd, active = await c.fetch_global_model(like=init)
                    break
                except Exception:
                    await asyncio.sleep(0.05)
            assert params is not None
            while True:
                try:
                    params, rnd, active = await c.fetch_global_model(like=init)
                    if not active:
                        return
                    fake = jax.tree.map(
                        lambda p, s=seed: p + 0.01 * (s + 1) * np.ones_like(p),
                        params,
                    )
                    await c.submit_update(fake, {"loss": 0.5, "num_samples": 10.0})
                except Exception:
                    return  # server already tore the socket down after the run
                await asyncio.sleep(0.01)

    async def clients():
        await asyncio.gather(*(run_client(f"c{i}", i) for i in range(3)))

    server_thread = threading.Thread(target=run_server, daemon=True)
    server_thread.start()
    asyncio.run(clients())
    server_thread.join(timeout=60)
    assert not server_thread.is_alive()
    assert rc_holder["rc"] == 0
    history = json.loads(capsys.readouterr().out)
    completed = [h for h in history if h["status"] == "COMPLETED"]
    assert len(completed) == 3
    assert all(h["num_clients"] == 2 for h in completed)  # exactly K per step


def test_serve_async_refuses_secure(capsys):
    rc = main(["serve", "--async-buffer", "2", "--secure"])
    assert rc == 2
    assert "--async-buffer" in capsys.readouterr().err


def test_serve_async_refuses_sync_only_cohort_flags(capsys):
    """Satellite regression: the sync-only cohort flags (--min-clients,
    --completion-rate, --max-clients) error when explicitly combined with
    --async-buffer, matching the --staleness-window refusal — FedBuff has no
    cohort barrier, so nothing would read them."""
    rc = main(["serve", "--async-buffer", "2", "--min-clients", "3"])
    assert rc == 2
    assert "--min-clients" in capsys.readouterr().err
    rc = main(["serve", "--async-buffer", "2", "--completion-rate", "0.5"])
    assert rc == 2
    assert "--completion-rate" in capsys.readouterr().err
    rc = main(["serve", "--async-buffer", "2", "--max-clients", "5"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "--max-clients" in err and "async" in err
    rc = main(["serve", "--async-buffer", "2",
               "--min-clients", "3", "--completion-rate", "0.5"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "--min-clients" in err and "--completion-rate" in err


def test_serve_async_flag_validation(capsys):
    """Mode-scoped flags fail fast instead of being silently ignored or escaping
    as coordinator tracebacks."""
    rc = main(["serve", "--staleness-window", "8"])
    assert rc == 2
    assert "--async-buffer" in capsys.readouterr().err
    rc = main(["serve", "--async-buffer", "0"])
    assert rc == 2
    assert "must be >= 1" in capsys.readouterr().err
    rc = main(["serve", "--async-buffer", "2", "--staleness-window", "0"])
    assert rc == 2
    assert "staleness-window" in capsys.readouterr().err


def test_metrics_summary_subcommand(tmp_path, capsys):
    """`nanofed-tpu metrics-summary` digests a run's telemetry.jsonl; a tree with
    none exits 1 with a pointer at --telemetry-dir."""
    import json as _json

    from nanofed_tpu.observability import MetricsRegistry, RunTelemetry

    tel = RunTelemetry(tmp_path / "run1", registry=MetricsRegistry())
    with tel.span("round", round=0):
        pass
    tel.record("round", round=0, status="COMPLETED", duration_s=0.125)
    tel.close()
    assert main(["metrics-summary", str(tmp_path)]) == 0
    summary = _json.loads(capsys.readouterr().out)
    assert summary["rounds"] == {"COMPLETED": 1}
    assert summary["phases"]["round"]["count"] == 1

    assert main(["metrics-summary", str(tmp_path / "empty")]) == 1
    assert "--telemetry-dir" in capsys.readouterr().err


def test_profile_subcommand_compiles_without_running(tmp_path, capsys):
    """`nanofed-tpu profile` compiles single-step, fused-block, and SCAFFOLD
    round programs on CPU WITHOUT running a federation, and the reports reach
    stdout + telemetry with compiler FLOPs, peak bytes, intensity, verdict."""
    rc = main([
        "profile", "--model", "digits_mlp", "--clients", "8",
        "--batch-size", "16", "--rounds-per-block", "2", "--json",
        "--telemetry-dir", str(tmp_path),
    ])
    assert rc == 0
    reports = json.loads(capsys.readouterr().out)
    assert {r["program"] for r in reports} == {
        "round_step", "round_block", "scaffold_round_step"
    }
    for r in reports:
        assert r["flops"] > 0
        assert r["peak_bytes"] > 0
        assert r["arithmetic_intensity"] > 0
        assert r["verdict"] == "no peak basis"  # CPU: no fabricated roofline
    (block,) = [r for r in reports if r["program"] == "round_block"]
    assert block["rounds"] == 2

    # Telemetry carries program_profile records — and NO round records: the
    # whole point is that nothing federated ran.
    telemetry = (tmp_path / "telemetry.jsonl").read_text()
    assert '"type": "program_profile"' in telemetry
    assert '"type": "round"' not in telemetry
    # metrics-summary digests them.
    assert main(["metrics-summary", str(tmp_path)]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert set(summary["program_profiles"]) == {
        "round_step", "round_block", "scaffold_round_step"
    }


def test_profile_table_output(capsys):
    rc = main([
        "profile", "--model", "digits_mlp", "--clients", "8",
        "--batch-size", "16", "--rounds-per-block", "1", "--no-scaffold",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "round_step" in out
    assert "roofline basis" in out
    assert "flops/round" in out


def test_unknown_benchmark_name_errors():
    with pytest.raises(KeyError):
        main(["bench", "not_a_benchmark"])


def test_run_robust_with_dp_fails_fast(capsys):
    rc = main(["run", "--robust-trim", "1", "--dp-epsilon", "4.0"])
    assert rc == 2
    assert "different sensitivity" in capsys.readouterr().err


def test_serve_flag_combinations_fail_fast(capsys):
    """Misconfigurations exit 2 with a pointed message BEFORE binding anything:
    --max-clients without the tolerant window (it would be silently ignored),
    and a cap below the minimum (the implicit freeze would close enrollment at a
    size the coordinator then waits on forever)."""
    rc = main(["serve", "--secure", "--min-clients", "3", "--max-clients", "10"])
    assert rc == 2
    assert "--dropout-tolerant" in capsys.readouterr().err
    rc = main(["serve", "--secure", "--dropout-tolerant",
               "--min-clients", "5", "--max-clients", "3"])
    assert rc == 2
    assert "must be >=" in capsys.readouterr().err


def test_chaos_plan_generates_host_and_client_faults(tmp_path, capsys):
    # stdout form: a valid, seeded plan with the requested host fault.
    rc = main(["chaos-plan", "--seed", "9", "--hosts", "3",
               "--host-crashes", "1", "--rounds", "6"])
    assert rc == 0
    plan = json.loads(capsys.readouterr().out)
    assert plan["seed"] == 9
    assert [e["kind"] for e in plan["events"]] == ["host_crash"]
    assert "host" in plan["events"][0]

    # file form round-trips through the loader serve/hostchaos use.
    from nanofed_tpu.faults import FaultPlan

    out = tmp_path / "plan.json"
    rc = main(["chaos-plan", "--seed", "1", "--clients", "8",
               "--crash-fraction", "0.25", "--hosts", "2",
               "--host-stalls", "1", "--out", str(out)])
    assert rc == 0
    loaded = FaultPlan.load(out)
    kinds = sorted(e.kind for e in loaded.events)
    assert kinds == ["crash", "crash", "host_stall"]

    # misconfiguration and empty plans are refusals, not silent successes.
    assert main(["chaos-plan", "--host-crashes", "1"]) == 2
    assert main(["chaos-plan", "--clients", "8"]) == 2
