"""Program auditor end to end: the seven-variant reference catalog audits clean
at the jaxpr/AOT level, every seeded mutant trips exactly its check (no check
is vacuous), the Coordinator wires audits into strict mode and telemetry, and
``metrics-summary`` digests the ``audit`` records into an ``audits`` block."""

import json
import subprocess
import sys

import pytest

from nanofed_tpu.analysis import AUDIT_CHECKS, run_mutation_suite
from nanofed_tpu.analysis.program_audit import reference_catalog
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.observability import summarize_telemetry
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
from nanofed_tpu.trainer import TrainingConfig

VARIANTS = {
    "single_step": {"clients"},
    "fused_block": {"clients"},
    "scaffold": {"clients"},
    "fsdp_2d": {"clients", "model"},
    "hier_3axis": {"hosts", "clients", "model"},
    "adapter": {"clients"},
    "drained_ingest": {"hosts", "clients", "model"},
}


@pytest.fixture(scope="module")
def catalog(devices):
    return reference_catalog()


@pytest.fixture(scope="module")
def reports(catalog):
    """One compile pass for the whole module: every test reads these."""
    return {r.program: r for r in catalog.audit_all(compile=True)}


def test_all_variants_audit_clean(reports):
    assert set(reports) == set(VARIANTS)
    for name, rep in reports.items():
        assert rep.ok, f"{name}: {[f.render() for f in rep.findings]}"
        assert rep.compiled
        assert set(rep.checks) == set(AUDIT_CHECKS)


def test_schedules_and_mesh_axes_are_real(reports):
    for name, rep in reports.items():
        # Zero-execution does not mean zero insight: the walker must surface
        # the actual collective schedule and the mesh axes it runs over.
        assert rep.schedule, f"{name}: empty collective schedule"
        assert set(rep.mesh_axes) == VARIANTS[name]
        assert rep.attrs["variant"] == name


def test_hierarchical_variant_orders_its_reduces(reports):
    # The 3-axis program reduces over hosts somewhere AND passes the
    # hosts-after-clients hierarchy check (rep.ok above); assert the hosts
    # reduce is really in the schedule so the check had something to order.
    hier = reports["hier_3axis"]
    assert any("hosts" in entry for entry in hier.schedule)
    assert any("clients" in entry for entry in hier.schedule)


def test_trace_only_audit_skips_donation(catalog):
    rep = catalog.audit("single_step", compile=False)
    assert not rep.compiled
    assert set(rep.checks) == set(AUDIT_CHECKS) - {"donation"}
    assert rep.ok


def test_mutation_suite_proves_every_check(devices):
    results = run_mutation_suite()
    assert set(r["expected"] for r in results.values()) == set(AUDIT_CHECKS)
    for name, r in results.items():
        assert r["ok"], f"mutant {name}: expected [{r['expected']}], fired {r['fired']}"


def _tiny_coordinator(tmp_path, **kw):
    ds = synthetic_classification(256, 3, (8,), seed=0)
    return Coordinator(
        model=get_model("mlp", in_features=8, hidden=16, num_classes=3),
        train_data=federate(ds, num_clients=8, scheme="iid", batch_size=16),
        config=CoordinatorConfig(num_rounds=1, base_dir=tmp_path),
        training=TrainingConfig(batch_size=16, local_epochs=1,
                                learning_rate=0.1),
        **kw,
    )


def test_coordinator_audit_reaches_telemetry_and_summary(tmp_path, devices):
    coord = _tiny_coordinator(tmp_path)
    reports = coord.audit_programs()
    assert [r.program for r in reports] == ["round_step"]
    assert all(r.ok for r in reports)

    records = {}
    with (tmp_path / "telemetry.jsonl").open() as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "audit":
                records[rec["program"]] = rec
    assert set(records) == {"round_step"}
    assert records["round_step"]["ok"] is True
    assert records["round_step"]["schedule"]

    summary = summarize_telemetry(tmp_path / "telemetry.jsonl")
    audits = summary["audits"]
    assert audits["clean"] == 1 and audits["dirty"] == 0
    assert audits["programs"]["round_step"]["ok"] is True


def test_strict_coordinator_audits_at_construction(tmp_path, devices):
    # strict=True runs the trace-level audit during construction: a clean
    # build must come up (and still run), a dirty program would raise
    # ContractViolation — the mutation suite proves the raising side.
    coord = _tiny_coordinator(tmp_path, strict=True)
    coord.run()
    assert all(m.status.name == "COMPLETED" for m in coord.history)


def test_module_entry_point_exit_contract(tmp_path):
    # `python -m nanofed_tpu.analysis --mutants` shares the lint exit-code
    # contract: 0 only when every seeded mutant fires exactly its check.
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "nanofed_tpu.analysis", "--mutants",
         "--format", "json", str(clean)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["fedlint"] == []
    assert set(out["mutants"]) and all(
        r["ok"] for r in out["mutants"].values()
    )


def test_audit_records_last_wins(tmp_path):
    # Pure summarize path: a re-audit record supersedes the first one.
    tel = tmp_path / "telemetry.jsonl"
    rows = [
        {"type": "audit", "program": "round_step", "ok": False,
         "findings": [{"check": "donation", "message": "stale"}],
         "schedule": [], "mesh_axes": [], "checks": [], "compiled": True},
        {"type": "audit", "program": "round_step", "ok": True,
         "findings": [], "schedule": ["psum@clients"],
         "mesh_axes": ["clients"], "checks": list(AUDIT_CHECKS),
         "compiled": True},
    ]
    tel.write_text("".join(json.dumps(r) + "\n" for r in rows))
    summary = summarize_telemetry(tel)
    audits = summary["audits"]
    assert audits == {
        "programs": {"round_step": {
            "ok": True, "findings": [], "schedule": ["psum@clients"],
            "mesh_axes": ["clients"], "checks": list(AUDIT_CHECKS),
            "compiled": True,
        }},
        "clean": 1,
        "dirty": 0,
    }
