"""Acceptance: the 3-axis ``hosts x clients x model`` mesh through the full
Coordinator (single-process virtual hosts on the 8-device CPU mesh — the REAL
2-process ``jax.distributed`` parity run is ``make multihost-smoke``).

A ``(2, 2, 2)`` run — single rounds AND fused round blocks, strict mode on —
produces params within numerical tolerance of the 1-D run (hierarchical
aggregation is a re-association of the same weighted sum), host-local cohort
sampling keeps every host's slot segment inside its resident client range,
``check_input_shardings`` accepts the joint ``(hosts, clients)`` data layout
and rejects host-sharded params, and the telemetry stream carries the
``topology`` record metrics-summary surfaces.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.analysis.contracts import (
    ContractViolation,
    check_input_shardings,
)
from nanofed_tpu.data import federate, pack_eval, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration.coordinator import Coordinator, CoordinatorConfig
from nanofed_tpu.orchestration.types import RoundStatus
from nanofed_tpu.parallel import (
    CLIENT_AXIS,
    HOST_AXIS,
    MODEL_AXIS,
    make_mesh,
    shard_params,
)
from nanofed_tpu.trainer import TrainingConfig


def _coordinator(tmp_path, mesh_shape=None, num_clients=8, strict=True,
                 telemetry_dir=None, **cfg_kw):
    m = get_model("mlp", in_features=8, hidden=16, num_classes=4)
    ds = synthetic_classification(64 * num_clients, 4, (8,), seed=0)
    cd = federate(ds, num_clients=num_clients, scheme="iid", batch_size=64,
                  seed=0)
    test = synthetic_classification(128, 4, (8,), seed=1)
    cfg = CoordinatorConfig(
        num_rounds=4, seed=0, base_dir=tmp_path, save_metrics=False, **cfg_kw
    )
    return Coordinator(
        m, cd, cfg,
        training=TrainingConfig(batch_size=64, local_epochs=1),
        eval_data=pack_eval(test, batch_size=64),
        mesh_shape=mesh_shape,
        strict=strict,
        telemetry_dir=telemetry_dir,
    )


def _assert_params_close(got, want, atol=2e-5):
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol)


def test_3d_single_round_trajectory_matches_1d(tmp_path, devices):
    c1 = _coordinator(tmp_path / "a")
    h1 = c1.run()
    c3 = _coordinator(tmp_path / "b", mesh_shape=(2, 2, 2))
    h3 = c3.run()
    assert [m.status for m in h3] == [RoundStatus.COMPLETED] * 4
    for m1, m3 in zip(h1, h3):
        assert m1.agg_metrics["loss"] == pytest.approx(
            m3.agg_metrics["loss"], rel=1e-5
        )
    _assert_params_close(c3.params, c1.params)
    # Model axis still FSDP-shards params on the 3-axis mesh.
    for leaf in jax.tree.leaves(c3.params):
        assert not leaf.sharding.is_fully_replicated
        assert MODEL_AXIS in {
            a for e in leaf.sharding.spec if e is not None
            for a in ((e,) if isinstance(e, str) else tuple(e))
        }
    # Data rides the joint (hosts, clients) layout; strict construction
    # already ran check_input_shardings — re-run it on the server state too.
    assert tuple(c3._data.x.sharding.spec)[0] == (HOST_AXIS, CLIENT_AXIS)
    check_input_shardings(c3._data, c3.server_state)


def test_3d_fused_round_block_matches_single_rounds(tmp_path, devices):
    c1 = _coordinator(tmp_path / "a", mesh_shape=(2, 2, 2))
    h1 = c1.run()
    cf = _coordinator(tmp_path / "b", mesh_shape=(2, 2, 2), rounds_per_block=2)
    hf = cf.run()
    for m1, mf in zip(h1, hf):
        assert m1.agg_metrics["loss"] == pytest.approx(
            mf.agg_metrics["loss"], rel=1e-6
        )
    _assert_params_close(cf.params, c1.params, atol=1e-7)


def test_3d_no_model_axis_replicates_params(tmp_path, devices):
    c = _coordinator(tmp_path, mesh_shape=(2, 4, 1))
    history = c.run()
    assert [m.status for m in history] == [RoundStatus.COMPLETED] * 4
    for leaf in jax.tree.leaves(c.params):
        assert leaf.sharding.is_fully_replicated
    assert np.isfinite(c.evaluate()["loss"])


def test_3d_host_local_cohort_slots_stay_resident(tmp_path, devices):
    """Every sampled slot in host h's segment indexes a client resident on
    host h — the property that makes the cohort gather host-local."""
    c = _coordinator(
        tmp_path, mesh_shape=(2, 2, 2), num_clients=16, participation_rate=0.5
    )
    assert c._cohort_mode and c._n_hosts == 2
    slots = c._slots_per_host
    rows_per_host = c._rows_per_host
    for r in range(6):
        survived = c._sample_cohort(r)
        idx, mask = c._place_cohort(survived)
        for h in range(2):
            seg = idx[h * slots : (h + 1) * slots]
            assert ((seg >= h * rows_per_host)
                    & (seg < (h + 1) * rows_per_host)).all(), (r, h, seg)
        # The draw is seed-deterministic and fills the proportional quota.
        idx2, mask2 = c._place_cohort(c._sample_cohort(r))
        np.testing.assert_array_equal(idx, idx2)
        assert int(mask.sum()) == c.cohort_size


def test_3d_partial_participation_trains(tmp_path, devices):
    c = _coordinator(
        tmp_path, mesh_shape=(2, 2, 2), num_clients=16,
        participation_rate=0.5, rounds_per_block=2,
    )
    history = c.run()
    assert [m.status for m in history] == [RoundStatus.COMPLETED] * 4
    assert all(m.num_clients == 8 for m in history)
    # Fused blocks reproduce the single-round hosts-mesh trajectory exactly.
    c2 = _coordinator(
        tmp_path / "single", mesh_shape=(2, 2, 2), num_clients=16,
        participation_rate=0.5,
    )
    h2 = c2.run()
    for mf, ms in zip(history, h2):
        assert mf.agg_metrics["loss"] == pytest.approx(
            ms.agg_metrics["loss"], rel=1e-6
        )


def test_topology_record_lands_in_metrics_summary(tmp_path, devices):
    from nanofed_tpu.observability import summarize_telemetry

    c = _coordinator(
        tmp_path, mesh_shape=(2, 2, 2), telemetry_dir=tmp_path, strict=False
    )
    c.run()
    c.telemetry.close()
    summary = summarize_telemetry(tmp_path / "telemetry.jsonl")
    topo = summary["topology"]
    assert topo["process_count"] == 1  # single-host says 1, never absent
    assert topo["hosts"] == 2
    assert topo["mesh_shape"] == [2, 2, 2]


def test_check_input_shardings_accepts_3d_layout(devices):
    mesh = make_mesh(shape=(2, 2, 2))
    from nanofed_tpu.parallel import client_sharding

    data = jax.device_put(jnp.zeros((8, 4, 2)), client_sharding(mesh))
    params = shard_params({"k": jnp.zeros((8, 16)), "odd": jnp.zeros((3,))},
                          mesh)
    check_input_shardings({"x": data}, params)  # must not raise


def test_check_input_shardings_rejects_host_sharded_params(devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(shape=(2, 2, 2))
    bad = jax.device_put(
        jnp.zeros((8, 16)), NamedSharding(mesh, P(HOST_AXIS))
    )
    with pytest.raises(ContractViolation, match="host-sharded"):
        check_input_shardings({}, {"k": bad})


def test_check_input_shardings_rejects_hosts_only_data(devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(shape=(2, 2, 2))
    bad = jax.device_put(
        jnp.zeros((8, 4)), NamedSharding(mesh, P(HOST_AXIS))
    )
    with pytest.raises(ContractViolation, match="hosts-major"):
        check_input_shardings({"x": bad}, {})


def test_run_experiment_hosts_summary(tmp_path, devices):
    """The CLI-facing path: run_experiment(hosts=2) realizes the 3-axis mesh
    and the summary records it."""
    from nanofed_tpu.experiments import run_experiment

    summary = run_experiment(
        model="digits_mlp", num_clients=8, num_rounds=1, local_epochs=1,
        batch_size=8, train_size=128, out_dir=tmp_path, hosts=2,
        model_shards=2, client_metrics_every=0,
    )
    assert summary["mesh_shape"] == [2, 2, 2]
    assert summary["rounds_completed"] == 1


def _cohort_stub(n_hosts, rows_per_host, slots_per_host, num_clients,
                 cohort_size):
    """Bare stand-in exposing exactly the state _sample_host_local reads —
    the clipped-quota geometries below need device counts a CPU test host
    doesn't have, so the draw is exercised directly."""
    from types import SimpleNamespace

    ns = SimpleNamespace(
        _n_hosts=n_hosts, _rows_per_host=rows_per_host,
        _slots_per_host=slots_per_host, num_clients=num_clients,
        cohort_size=cohort_size,
    )
    ns._host_populations = lambda: Coordinator._host_populations(ns)
    ns._sample_host_local = (
        lambda rng: Coordinator._sample_host_local(ns, rng)
    )
    return ns


def test_host_local_sampling_redistributes_clipped_quota():
    """A host whose proportional quota is clipped by its slot segment hands
    the WHOLE shortfall to hosts with free capacity — the cohort comes back
    full, never silently smaller (regression: the redistribution loop used to
    give up after 2*n_hosts iterations, returning 44 of 48 here)."""
    # pops [40, 25] over 2 hosts, 24 slots each: exact quotas [29.5, 18.5]
    # clip to [24, 18], shortfall 6 must all land on host 1.
    c = _cohort_stub(n_hosts=2, rows_per_host=40, slots_per_host=24,
                     num_clients=65, cohort_size=48)
    sampled = c._sample_host_local(np.random.default_rng(0))
    assert len(sampled) == 48
    assert len(np.unique(sampled)) == 48
    per_host = [int(((sampled >= 0) & (sampled < 40)).sum()),
                int(((sampled >= 40) & (sampled < 65)).sum())]
    assert per_host == [24, 24]


def test_host_local_sampling_raises_when_caps_cannot_hold_cohort():
    """cohort_size beyond the summed per-host caps is a sizing error, raised
    like _place_cohort's overflow — not a silently degraded cohort."""
    from nanofed_tpu.core.exceptions import NanoFedError

    c = _cohort_stub(n_hosts=2, rows_per_host=40, slots_per_host=10,
                     num_clients=65, cohort_size=48)
    with pytest.raises(NanoFedError, match="hosts-axis capacity"):
        c._sample_host_local(np.random.default_rng(0))


def test_host_local_sampling_tie_break_rotates_across_rounds():
    """Equal largest-remainder ties must not always favor low-indexed hosts:
    over many rounds every host sometimes wins the leftover slot, keeping
    long-run inclusion probability at cohort/N (regression: a stable sort on
    remainder alone handed the extras to hosts 0..k-1 every single round)."""
    c = _cohort_stub(n_hosts=4, rows_per_host=25, slots_per_host=25,
                     num_clients=100, cohort_size=10)
    # quotas floor to 2 everywhere with remainder 0.5 each: 2 extra slots.
    extra_winners = set()
    for r in range(40):
        sampled = c._sample_host_local(np.random.default_rng(r))
        assert len(sampled) == 10
        counts = [int(((sampled >= h * 25) & (sampled < (h + 1) * 25)).sum())
                  for h in range(4)]
        assert sorted(counts) == [2, 2, 3, 3], counts
        extra_winners.update(h for h in range(4) if counts[h] == 3)
    assert extra_winners == {0, 1, 2, 3}, extra_winners


def test_host_local_sampling_never_starves_clipped_hosts():
    """Uneven per-host populations (padding always clips the last hosts) must
    not permanently exclude anyone: randomized largest-remainder rounding
    gives every positive-remainder host a win some rounds (regression: a
    deterministic remainder sort handed the extras to hosts 0/1 EVERY round,
    so host 2's lone client was never sampled and the central-DP accountant's
    cohort/N sampling rate was wrong)."""
    c = _cohort_stub(n_hosts=4, rows_per_host=4, slots_per_host=4,
                     num_clients=9, cohort_size=4)
    # pops [4, 4, 1, 0] -> exact quotas [1.78, 1.78, 0.44, 0], 2 extras.
    host2_rounds = 0
    for r in range(80):
        sampled = c._sample_host_local(np.random.default_rng(r))
        assert len(sampled) == 4
        host2_rounds += int(((sampled >= 8) & (sampled < 9)).sum() > 0)
        assert not ((sampled >= 9) | (sampled < 0)).any()  # host 3 is empty
    # E[inclusion] ~ 0.44/round; over 80 rounds "never" is the bug signature.
    assert 10 < host2_rounds < 70, host2_rounds


def test_infeasible_cohort_refused_at_construction(tmp_path, devices):
    """cohort_size beyond the hosts-axis capacity fails in __init__ — before
    any program compiles — not at round 1's first draw."""
    from nanofed_tpu.core.exceptions import NanoFedError

    # 9 clients pad to 12 over 4 client shards: pops [6, 3]; a cohort of 8
    # steps at 8 slots (4 per host), caps [4, 3] = 7 < 8.
    with pytest.raises(NanoFedError, match="hosts-axis capacity"):
        _coordinator(tmp_path, mesh_shape=(2, 2, 2), num_clients=9,
                     participation_rate=0.86)
