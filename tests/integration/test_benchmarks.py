"""Benchmark-suite smoke tests: every named BASELINE.json config must run end-to-end on
the CPU mesh with tiny synthetic data.

CNN/ResNet XLA compiles take minutes on the single-core CPU mesh, so the routine smoke
runs override the model with a small MLP — it exercises the harness plumbing (schemes,
participation, DP path, metrics), while the true benchmark models are covered by unit
forward tests and run on real hardware via ``nanofed-tpu bench``. Set NANOFED_RUN_SLOW=1
to smoke the real models here too."""

import os

import pytest

from nanofed_tpu.benchmarks import BENCHMARKS, run_benchmark

_REAL_MODELS = bool(os.environ.get("NANOFED_RUN_SLOW"))

# Tiny overrides per benchmark: enough samples for every client to get a shard.
_SMOKE = {
    "mnist_iid": dict(train_size=640, num_rounds=2),
    "mnist_labelskew": dict(train_size=1600, num_rounds=2, num_clients=16),
    "fedprox_cifar10": dict(train_size=512, num_rounds=1, num_clients=8),
    "dp_fedavg_mnist": dict(train_size=640, num_rounds=2),
    "cross_silo": dict(train_size=256, num_rounds=1),
    # 32 clients >> 8 devices with client_chunk=2: exercises the sequential-chunk path
    # and bf16 mixed precision through the PUBLIC harness (the flagship configuration,
    # scaled down for the 1-core CPU mesh).
    "mnist_1000": dict(train_size=640, num_rounds=2, num_clients=32, client_chunk=2),
}


def test_benchmark_names_covered():
    assert set(_SMOKE) == set(BENCHMARKS)


@pytest.mark.parametrize("name", sorted(_SMOKE))
def test_benchmark_smoke(name, tmp_path):
    overrides = dict(_SMOKE[name])
    if not _REAL_MODELS:
        overrides["model"] = "mlp"
    summary = run_benchmark(name, out_dir=str(tmp_path), **overrides)
    assert summary["benchmark"] == name
    assert summary["rounds_failed"] == 0
    assert summary["rounds_completed"] >= 1
    assert "accuracy" in summary["final_eval_metrics"]
    assert summary["rounds_per_sec"] > 0
    if name == "dp_fedavg_mnist":
        # The CLI/experiment summary surfaces cumulative DP spend (VERDICT r2 item 6).
        spent = summary["privacy_spent"]
        assert spent["epsilon_spent"] > 0
        assert 0 < spent["delta_spent"] <= 1e-5
    else:
        assert "privacy_spent" not in summary


def test_unknown_benchmark_raises():
    with pytest.raises(KeyError):
        run_benchmark("nope")
