"""Compiled-program cost profiling through the Coordinator: the catalog is
populated at program-build time, ``profile_programs`` compiles every round
program on the CPU backend, and the SAME numbers land in all three surfaces —
the returned reports, the ``nanofed_program_*`` registry gauges (what
``GET /metrics`` renders), and ``telemetry.jsonl`` ``program_profile`` records
(what ``metrics-summary`` digests)."""

import json

import jax
import pytest

from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.observability import summarize_telemetry
from nanofed_tpu.observability.profiling import (
    PROGRAM_COMPILE_HISTOGRAM,
    PROGRAM_FLOPS_GAUGE,
    PROGRAM_PEAK_BYTES_GAUGE,
)
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
from nanofed_tpu.trainer import TrainingConfig


def _client_data(num_clients=8, samples=256):
    ds = synthetic_classification(samples, 3, (8,), seed=0)
    return federate(ds, num_clients=num_clients, scheme="iid", batch_size=16)


def _training():
    return TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1)


def _read_profiles(tmp_path):
    """The program_profile records flushed to telemetry.jsonl so far (the sink
    streams per record — no close() needed to observe them)."""
    records = {}
    with (tmp_path / "telemetry.jsonl").open() as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "program_profile":
                records[rec["program"]] = rec
    return records


def test_step_and_block_profiles_reach_every_surface(tmp_path, devices):
    coord = Coordinator(
        model=get_model("mlp", in_features=8, hidden=16, num_classes=3),
        train_data=_client_data(),
        config=CoordinatorConfig(
            num_rounds=4, rounds_per_block=2, base_dir=tmp_path,
            profile_programs=True,
        ),
        training=_training(),
    )
    # Both programs the coordinator built are catalogued and profiled.
    assert coord.program_catalog.names() == ["round_block", "round_step"]
    reports = {r.program: r for r in coord.program_catalog.reports()}
    assert set(reports) == {"round_block", "round_step"}
    step, block = reports["round_step"], reports["round_block"]
    assert step.flops > 0 and step.bytes_accessed > 0 and step.peak_bytes > 0
    assert block.rounds == 2
    # A 2-round block does at least one round's work more than a single step
    # shares: its total FLOPs must exceed the single step's per-round count
    # is NOT guaranteed (scan-level CSE), but positivity and the per-round
    # accounting are.
    assert block.flops > 0
    assert step.verdict == "no peak basis"  # CPU: stated, never fabricated

    # Surface 2: registry gauges (what /metrics renders), same numbers.
    reg = coord.program_catalog.registry
    for name, rep in reports.items():
        assert reg.gauge(PROGRAM_FLOPS_GAUGE, labels=("program",)).value(
            program=name
        ) == rep.flops
        assert reg.gauge(PROGRAM_PEAK_BYTES_GAUGE, labels=("program",)).value(
            program=name
        ) == rep.peak_bytes
    # >= 1: the registry is the PROCESS-wide default (telemetry attaches it),
    # so earlier tests' compiles may already sit in the histogram.
    assert reg.histogram(
        PROGRAM_COMPILE_HISTOGRAM, labels=("program",)
    ).sample_count(program="round_step") >= 1
    text = reg.render_prometheus()
    assert f'{PROGRAM_FLOPS_GAUGE}{{program="round_block"}}' in text
    assert f'{PROGRAM_FLOPS_GAUGE}{{program="round_step"}}' in text

    # Surface 3: telemetry program_profile records, same numbers again.
    recs = _read_profiles(tmp_path)
    assert set(recs) == {"round_block", "round_step"}
    assert recs["round_step"]["flops"] == step.flops
    assert recs["round_block"]["rounds"] == 2
    assert recs["round_block"]["flops_per_round"] == pytest.approx(
        block.flops / 2
    )

    # And the federation still RUNS after profiling (lowering must not have
    # consumed the donated params), with the metrics-summary digest carrying
    # the profiles end to end.
    coord.run()
    summary = summarize_telemetry(tmp_path / "telemetry.jsonl")
    assert set(summary["program_profiles"]) == {"round_block", "round_step"}
    assert summary["program_profiles"]["round_step"]["verdict"] == "no peak basis"
    assert summary["rounds"] == {"COMPLETED": 4}


def test_cohort_mode_profiles_the_gathered_program(tmp_path, devices):
    """participation < 1: the profiled program must be the cohort-width program
    the rounds actually dispatch, not the full-population one."""
    coord = Coordinator(
        model=get_model("mlp", in_features=8, hidden=16, num_classes=3),
        train_data=_client_data(num_clients=16),
        config=CoordinatorConfig(
            num_rounds=1, participation_rate=0.5, base_dir=tmp_path,
        ),
        training=_training(),
    )
    assert coord._cohort_mode
    (report,) = coord.profile_programs()
    assert report.program == "round_step"
    assert report.attrs["step_clients"] == coord._step_clients
    assert report.flops > 0
    # Second call is cached — no recompile, same object.
    (again,) = coord.profile_programs()
    assert again is report
    coord.run()  # profiled program == dispatched program: the round still runs


def test_scaffold_program_profile(tmp_path, devices):
    coord = Coordinator(
        model=get_model("mlp", in_features=8, hidden=16, num_classes=3),
        train_data=_client_data(),
        config=CoordinatorConfig(
            num_rounds=1, base_dir=tmp_path, profile_programs=True,
        ),
        training=_training(),
        scaffold=True,
    )
    reports = coord.program_catalog.reports()
    assert [r.program for r in reports] == ["scaffold_round_step"]
    assert reports[0].flops > 0 and reports[0].peak_bytes > 0
    assert _read_profiles(tmp_path)["scaffold_round_step"]["flops"] == (
        reports[0].flops
    )


def test_2d_mesh_program_profile(tmp_path, devices):
    """The FSDP (clients x model) programs profile too — the lowered program
    carries the model-axis collectives, so its cost is the 2-D cost."""
    coord = Coordinator(
        model=get_model("mlp", in_features=8, hidden=16, num_classes=3),
        train_data=_client_data(),
        config=CoordinatorConfig(
            num_rounds=2, rounds_per_block=2, base_dir=tmp_path,
            profile_programs=True,
        ),
        training=_training(),
        mesh_shape=(4, 2),
    )
    recs = _read_profiles(tmp_path)
    assert set(recs) == {"round_block", "round_step"}
    for rec in recs.values():
        assert rec["flops"] > 0
        assert rec["attrs"]["mesh_shape"] == [4, 2]
    # The profiled layout is dispatchable: run the fused block for real.
    coord.run()
    assert all(
        m.status.name == "COMPLETED" for m in coord.history
    )


def test_occupancy_gauge_lands_after_rounds(tmp_path, devices):
    from nanofed_tpu.observability.profiling import DEVICE_OCCUPANCY_GAUGE

    coord = Coordinator(
        model=get_model("mlp", in_features=8, hidden=16, num_classes=3),
        train_data=_client_data(),
        config=CoordinatorConfig(num_rounds=2, base_dir=tmp_path),
        training=_training(),
    )
    coord.run()
    ratio = coord.program_catalog.registry.gauge(DEVICE_OCCUPANCY_GAUGE).value()
    assert 0.0 < ratio <= 1.0
