"""Real-network federation: an aiohttp server on localhost + HTTPClient coroutines doing
real local training — parity with ``tests/integration/
test_client_server_communication.py:17-75``, but over binary payloads and with a real
aggregation round."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
    decode_params,
    encode_params,
)
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.local import make_local_fit

PORT = 18432


def test_codec_roundtrip():
    params = {"a": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
              "b": jnp.ones((4,), jnp.bfloat16)}
    out = decode_params(encode_params(params), like=params)
    for x, y in zip(jax.tree.leaves(params), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(x).astype(np.float32),
                                      np.asarray(y).astype(np.float32))


def test_codec_template_mismatch():
    from nanofed_tpu.core.exceptions import NanoFedError

    payload = encode_params({"w": jnp.zeros((2,))})
    with pytest.raises(NanoFedError):
        decode_params(payload, like={"w": jnp.zeros((3,))})
    with pytest.raises(NanoFedError):
        decode_params(payload, like={"other": jnp.zeros((2,))})


async def _run_client(client_id: str, model, local_fit, data: ClientData, port: int):
    async with HTTPClient(f"http://127.0.0.1:{port}", client_id, timeout_s=30) as client:
        while True:
            params, rnd, active = await client.fetch_global_model(
                like=model.init(jax.random.key(0))
            )
            if not active:
                return
            result = local_fit(jax.tree.map(jnp.asarray, params), data,
                               jax.random.key(hash(client_id) % 2**31))
            await client.submit_update(
                result.params,
                {
                    "loss": float(result.metrics.loss),
                    "accuracy": float(result.metrics.accuracy),
                    "num_samples": float(result.metrics.samples),
                },
            )
            # Wait for the next round (or termination).
            status = await client.check_server_status()
            while status["training_active"] and status["round"] == rnd:
                await asyncio.sleep(0.05)
                status = await client.check_server_status()
            if not status["training_active"]:
                return


def test_full_network_federation_two_rounds():
    model = get_model("linear", in_features=8, num_classes=2)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    local_fit = jax.jit(make_local_fit(model.apply, training))
    rng = np.random.default_rng(0)

    def client_data(seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(16, 8)).astype(np.float32)
        w = r.normal(size=(8,))
        y = (x @ w > 0).astype(np.int32)
        return ClientData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.ones((16,)))

    async def main():
        server = HTTPServer(port=PORT)
        await server.start()
        try:
            init = model.init(jax.random.key(0))
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=2, min_clients=3, round_timeout_s=30),
            )
            results = await asyncio.gather(
                coordinator.run(),
                _run_client("c1", model, local_fit, client_data(1), PORT),
                _run_client("c2", model, local_fit, client_data(2), PORT),
                _run_client("c3", model, local_fit, client_data(3), PORT),
            )
            return results[0], init, coordinator
        finally:
            await server.stop()

    history, init, coordinator = asyncio.run(main())
    assert [h["status"] for h in history] == ["COMPLETED", "COMPLETED"]
    assert all(h["num_clients"] == 3 for h in history)
    # The aggregate actually moved.
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(init), jax.tree.leaves(coordinator.params))
    )
    assert moved


def test_robust_aggregation_over_the_wire():
    """A Byzantine client POSTs a poisoned update (1e6 on every coordinate, huge
    claimed sample count and loss) through the real HTTP transport; with
    robust=trim_k=1 the aggregate stays in the honest clients' range and the round
    metrics ignore the attacker's claimed loss."""
    from nanofed_tpu.aggregation import RobustAggregationConfig

    model = get_model("linear", in_features=8, num_classes=2)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    local_fit = jax.jit(make_local_fit(model.apply, training))

    def client_data(seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(16, 8)).astype(np.float32)
        w = r.normal(size=(8,))
        y = (x @ w > 0).astype(np.int32)
        return ClientData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.ones((16,)))

    async def byzantine_client(port):
        init = get_model("linear", in_features=8, num_classes=2).init(
            jax.random.key(0)
        )
        async with HTTPClient(f"http://127.0.0.1:{port}", "attacker",
                              timeout_s=30) as client:
            params, rnd, active = await client.fetch_global_model(like=init)
            assert active
            poisoned = jax.tree.map(lambda p: jnp.full_like(p, 1e6), params)
            # Huge claimed sample count: weighting would amplify it; the trimmed
            # mean must not care.
            await client.submit_update(
                poisoned, {"loss": 1e9, "accuracy": 1.0, "num_samples": 1e9}
            )

    async def main():
        server = HTTPServer(port=PORT + 60)
        await server.start()
        try:
            init = model.init(jax.random.key(0))
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=1, min_clients=4,
                                   round_timeout_s=30),
                robust=RobustAggregationConfig(trim_k=1),
            )
            results = await asyncio.gather(
                coordinator.run(),
                _run_client("c1", model, local_fit, client_data(1), PORT + 60),
                _run_client("c2", model, local_fit, client_data(2), PORT + 60),
                _run_client("c3", model, local_fit, client_data(3), PORT + 60),
                byzantine_client(PORT + 60),
            )
            return results[0], init, coordinator
        finally:
            await server.stop()

    history, init, coordinator = asyncio.run(main())
    assert history[0]["status"] == "COMPLETED"
    assert history[0]["num_clients"] == 4
    # The attacker's 1e6 coordinates were trimmed: the aggregate stays sane.
    for leaf in jax.tree.leaves(coordinator.params):
        assert np.abs(np.asarray(leaf)).max() < 100.0
    # And its claimed 1e9 loss never reached the round record.
    assert history[0]["metrics"]["loss"] < 100.0


def test_robust_refuses_secure_mode():
    pytest.importorskip("cryptography")
    from nanofed_tpu.aggregation import RobustAggregationConfig
    from nanofed_tpu.security.secure_agg import SecureAggregationConfig

    async def scenario():
        server = HTTPServer(port=0)
        with pytest.raises(ValueError, match="masked"):
            NetworkCoordinator(
                server, {"w": jnp.zeros(3)},
                NetworkRoundConfig(num_rounds=1),
                secure=SecureAggregationConfig(min_clients=3),
                robust=RobustAggregationConfig(trim_k=1),
            )

    asyncio.run(scenario())


def test_stale_round_rejected_and_status():
    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))

    async def main():
        server = HTTPServer(port=PORT + 1)
        await server.start()
        try:
            await server.publish_model(params, round_number=5)
            async with HTTPClient(f"http://127.0.0.1:{PORT + 1}", "c1", timeout_s=10) as c:
                status = await c.check_server_status()
                assert status["round"] == 5 and status["training_active"]
                fetched, rnd, active = await c.fetch_global_model(like=params)
                assert rnd == 5 and active
                # Submitting against a stale round number must be rejected.
                c.current_round = 3
                ok = await c.submit_update(fetched, {"loss": 0.1})
                assert not ok
                assert server.num_updates() == 0
                # Correct round is accepted.
                c.current_round = 5
                ok = await c.submit_update(fetched, {"loss": 0.1})
                assert ok and server.num_updates() == 1
                # Termination propagates to fetch.
                server.stop_training()
                none_params, _, active = await c.fetch_global_model(like=params)
                assert none_params is None and not active
        finally:
            await server.stop()

    asyncio.run(main())


def test_metrics_coercion_survives_malicious_values():
    """A client sending non-numeric / non-finite metrics must not kill the round
    (the server validates params strictly but metrics only as parseable JSON)."""
    from nanofed_tpu.communication.network_coordinator import stack_model_updates
    from nanofed_tpu.core.types import ModelUpdate

    def upd(cid, metrics):
        return ModelUpdate(
            client_id=cid, round_number=0, params={"w": jnp.ones((2,))},
            metrics=metrics, timestamp="t",
        )

    stacked = stack_model_updates([
        upd("good", {"loss": 0.5, "accuracy": 0.9, "num_samples": 10}),
        upd("evil", {"loss": "oops", "accuracy": None, "num_samples": "NaN"}),
        upd("str-numeric", {"loss": "0.25", "num_samples": "4"}),
    ])
    np.testing.assert_allclose(np.asarray(stacked.weights), [10.0, 1.0, 4.0])
    np.testing.assert_allclose(np.asarray(stacked.metrics.loss), [0.5, 0.0, 0.25])
    np.testing.assert_allclose(np.asarray(stacked.metrics.accuracy), [0.9, 0.0, 0.0])


def test_signature_enforcement_end_to_end():
    """require_signatures: unsigned and wrong-key updates are rejected with 403, a
    properly signed update is buffered (INVALID_SIGNATURE wire parity)."""
    pytest.importorskip("cryptography")
    from nanofed_tpu.security import SecurityManager

    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    signer = SecurityManager(key_size=2048)
    impostor = SecurityManager(key_size=2048)
    port = PORT + 2

    async def main():
        server = HTTPServer(
            port=port,
            client_keys={"c1": signer.get_public_key()},
            require_signatures=True,
        )
        await server.start()
        try:
            await server.publish_model(params, round_number=0)
            url = f"http://127.0.0.1:{port}"
            # Unsigned update from a registered client: rejected.
            async with HTTPClient(url, "c1", timeout_s=10) as c:
                assert not await c.submit_update(params, {"loss": 0.1})
            assert server.num_updates() == 0
            # Signed with the WRONG key: rejected.
            async with HTTPClient(url, "c1", timeout_s=10,
                                  security_manager=impostor) as c:
                assert not await c.submit_update(params, {"loss": 0.1})
            assert server.num_updates() == 0
            # Unregistered client id: rejected even with a signature.
            async with HTTPClient(url, "mallory", timeout_s=10,
                                  security_manager=signer) as c:
                assert not await c.submit_update(params, {"loss": 0.1})
            assert server.num_updates() == 0
            # Correctly signed: accepted.
            async with HTTPClient(url, "c1", timeout_s=10,
                                  security_manager=signer) as c:
                assert await c.submit_update(params, {"loss": 0.1})
            assert server.num_updates() == 1
        finally:
            await server.stop()

    asyncio.run(main())


def test_negative_num_samples_rejected():
    """A negative num_samples could zero the cohort weight sum and blow up the mean —
    coercion must fall back to the default weight."""
    from nanofed_tpu.communication.network_coordinator import stack_model_updates
    from nanofed_tpu.core.types import ModelUpdate

    def upd(cid, n):
        return ModelUpdate(client_id=cid, round_number=0, params={"w": jnp.ones((2,))},
                           metrics={"num_samples": n}, timestamp="t")

    stacked = stack_model_updates([upd("good", 10), upd("evil", -10), upd("zero", 0)])
    np.testing.assert_allclose(np.asarray(stacked.weights), [10.0, 1.0, 1.0])
