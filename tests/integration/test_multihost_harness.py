"""Launcher-side units of scripts/multihost_harness.py — no JAX workers, just
real subprocesses: the orphan-reaping contract of ``_wait``/``_reap`` (a
failed parity run must never leave a worker holding the rendezvous port) and
the supervisor's progress/plan plumbing."""

import importlib.util
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from nanofed_tpu.parallel.resilience import no_orphans

REPO = Path(__file__).resolve().parent.parent.parent


@pytest.fixture(scope="module")
def harness():
    spec = importlib.util.spec_from_file_location(
        "multihost_harness", REPO / "scripts" / "multihost_harness.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _sleeper(seconds=60):
    return subprocess.Popen([sys.executable, "-c",
                             f"import time; time.sleep({seconds})"])


def _crasher(rc=3, after_s=0.0):
    return subprocess.Popen([sys.executable, "-c",
                             f"import sys, time; time.sleep({after_s}); "
                             f"sys.exit({rc})"])


def test_wait_reaps_survivors_when_a_worker_crashes(harness):
    # One worker crashes fast while its peer would happily block for a
    # minute (the jax.distributed-rendezvous shape of the bug): _wait must
    # surface the crash rc AND terminate+reap the survivor before raising.
    survivor = _sleeper()
    crasher = _crasher(rc=3, after_s=0.2)
    procs = [survivor, crasher]
    with pytest.raises(SystemExit, match="rc=3"):
        harness._wait(procs, timeout_s=30.0)
    # Reaped, not just signalled: returncode is set (wait() happened), and
    # the pid no longer exists in the process table.
    assert all(p.returncode is not None for p in procs)
    assert no_orphans([p.pid for p in procs]) == []


def test_wait_reaps_everyone_on_timeout(harness):
    procs = [_sleeper(), _sleeper()]
    t0 = time.monotonic()
    with pytest.raises(SystemExit, match="timed out"):
        harness._wait(procs, timeout_s=0.5)
    assert time.monotonic() - t0 < 10
    assert all(p.returncode is not None for p in procs)
    assert no_orphans([p.pid for p in procs]) == []


def test_wait_returns_when_all_exit_cleanly(harness):
    procs = [_crasher(rc=0), _crasher(rc=0)]
    harness._wait(procs, timeout_s=30.0)
    assert [p.returncode for p in procs] == [0, 0]


def test_reap_escalates_sigterm_to_sigkill(harness):
    # A worker that ignores SIGTERM (a hung gloo collective does) must still
    # die within the grace window.
    stubborn = subprocess.Popen([sys.executable, "-c",
                                 "import signal, time; "
                                 "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
                                 "time.sleep(60)"])
    time.sleep(0.3)  # let the handler install
    harness._reap([stubborn], grace_s=0.5)
    assert stubborn.returncode is not None
    assert no_orphans([stubborn.pid]) == []


def test_read_progress_skips_torn_tail(harness, tmp_path):
    p = tmp_path / "progress.jsonl"
    p.write_text(
        json.dumps({"round": 0, "loss": 2.0, "wall_t": 1.0}) + "\n"
        + json.dumps({"round": 1, "loss": 1.9, "wall_t": 2.0}) + "\n"
        + '{"round": 2, "los'  # killed mid-write
    )
    rows = harness._read_progress(p)
    assert [r["round"] for r in rows] == [0, 1]
    assert harness._read_progress(tmp_path / "missing.jsonl") == []
