"""Strict-mode integration: the fused round block runs under
``jax.transfer_guard("disallow")`` without tripping — the runtime proof that the
hot path performs zero implicit transfers — and ``Coordinator(strict=True)``
changes nothing about the math."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
from nanofed_tpu.data import pack_clients, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.orchestration.coordinator import Coordinator, CoordinatorConfig
from nanofed_tpu.orchestration.types import RoundStatus
from nanofed_tpu.parallel import (
    build_round_block,
    build_round_step,
    init_server_state,
    make_mesh,
    pad_client_count,
    pad_clients,
    replicated_sharding,
    shard_client_data,
    stack_round_keys,
)
from nanofed_tpu.trainer import TrainingConfig, stack_rngs

N_CLIENTS = 4
SAMPLES = 8


def _client_data(mesh):
    ds = synthetic_classification(N_CLIENTS * SAMPLES, 3, (6,), seed=0)
    parts = [np.arange(i * SAMPLES, (i + 1) * SAMPLES) for i in range(N_CLIENTS)]
    data = pack_clients(ds, parts, batch_size=SAMPLES)
    padded = pad_client_count(N_CLIENTS, len(mesh.devices.flat))
    return shard_client_data(pad_clients(data, padded), mesh), padded


def test_fused_round_block_under_transfer_guard():
    """The acceptance-criteria test: a fused R-round block dispatched with
    device-resident inputs completes under ``jax.transfer_guard("disallow")`` —
    any implicit host transfer inside dispatch/execution would raise."""
    model = get_model("linear", in_features=6, num_classes=3)
    mesh = make_mesh()
    repl = replicated_sharding(mesh)
    strategy = fedavg_strategy()
    data, padded = _client_data(mesh)
    num_samples = jnp.asarray(np.asarray(data.mask).sum(axis=1), jnp.float32)
    block = build_round_block(
        model.apply, TrainingConfig(batch_size=SAMPLES, local_epochs=1), mesh,
        strategy, num_clients=N_CLIENTS, padded_clients=padded,
    )
    params = jax.device_put(model.init(jax.random.key(0)), repl)
    sos = jax.device_put(init_server_state(strategy, params), repl)
    rpb = 3
    # Every input COMMITTED to its mesh placement BEFORE the guard — the
    # contract the Coordinator's strict dispatch follows.  The warm-up call
    # then compiles for exactly these shardings, so the guarded dispatch has
    # nothing left to move in ANY direction.
    num_samples = jax.device_put(num_samples, repl)
    keys = jax.device_put(stack_round_keys(0, list(range(rpb))), repl)
    lr = jax.device_put(jnp.ones((rpb,), jnp.float32), repl)
    mask = jax.device_put(
        jnp.asarray(np.tile(np.asarray(num_samples > 0, np.float32), (rpb, 1))),
        repl,
    )
    # Warm-up compiles outside the guard (compilation may transfer constants).
    res = block(params, sos, data, num_samples, keys, lr, cohort_mask=mask)
    jax.block_until_ready(res.params)
    with jax.transfer_guard("disallow"):
        res = block(res.params, res.server_opt_state, data, num_samples,
                    keys, lr, cohort_mask=mask)
    jax.block_until_ready(res.params)
    assert res.metrics["loss"].shape == (rpb,)
    assert int(res.survivors[0]) == N_CLIENTS


def test_single_round_step_under_transfer_guard():
    model = get_model("linear", in_features=6, num_classes=3)
    mesh = make_mesh()
    repl = replicated_sharding(mesh)
    strategy = fedavg_strategy()
    data, padded = _client_data(mesh)
    num_samples = jnp.asarray(np.asarray(data.mask).sum(axis=1), jnp.float32)
    step = build_round_step(
        model.apply, TrainingConfig(batch_size=SAMPLES, local_epochs=1), mesh,
        strategy,
    )
    params = jax.device_put(model.init(jax.random.key(0)), repl)
    sos = jax.device_put(init_server_state(strategy, params), repl)
    weights = jax.device_put(compute_weights(num_samples) * (num_samples > 0), repl)
    rngs = jax.device_put(stack_rngs(jax.random.key(1), padded), repl)
    lr = jax.device_put(jnp.float32(1.0), repl)
    res = step(params, sos, data, weights, rngs, lr)
    jax.block_until_ready(res.params)
    with jax.transfer_guard("disallow"):
        res = step(res.params, res.server_opt_state, data, weights, rngs, lr)
    jax.block_until_ready(res.params)
    assert float(res.metrics["participating_clients"]) == N_CLIENTS


class TestStrictCoordinator:
    def _run(self, tmp_path, strict, rounds_per_block=2, **cfg_kwargs):
        model = get_model("linear", in_features=6, num_classes=3)
        ds = synthetic_classification(N_CLIENTS * SAMPLES, 3, (6,), seed=0)
        parts = [np.arange(i * SAMPLES, (i + 1) * SAMPLES) for i in range(N_CLIENTS)]
        data = pack_clients(ds, parts, batch_size=SAMPLES)
        coord = Coordinator(
            model, data,
            CoordinatorConfig(
                num_rounds=4, rounds_per_block=rounds_per_block, seed=7,
                base_dir=tmp_path, save_metrics=False, **cfg_kwargs,
            ),
            training=TrainingConfig(batch_size=SAMPLES, local_epochs=1),
            strict=strict,
        )
        return coord, coord.run()

    def test_strict_fused_run_completes_and_matches_default(self, tmp_path):
        strict_c, strict_hist = self._run(tmp_path / "strict", strict=True)
        plain_c, plain_hist = self._run(tmp_path / "plain", strict=False)
        assert [m.status for m in strict_hist] == [RoundStatus.COMPLETED] * 4
        for a, b in zip(jax.tree.leaves(strict_c.params),
                        jax.tree.leaves(plain_c.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert [m.agg_metrics.get("loss") for m in strict_hist] == [
            m.agg_metrics.get("loss") for m in plain_hist
        ]

    def test_strict_single_round_cohort_path(self, tmp_path):
        _, hist = self._run(
            tmp_path, strict=True, rounds_per_block=1, participation_rate=0.5,
        )
        assert [m.status for m in hist] == [RoundStatus.COMPLETED] * 4

    def test_strict_validates_contracts_at_construction(self, tmp_path):
        # The construction-time eval_shape check is active: it has already run
        # for the fused configuration above; here we assert it raises on a
        # round program that violates the contract.
        from nanofed_tpu.analysis import ContractViolation, check_round_step

        model = get_model("linear", in_features=6, num_classes=3)
        mesh = make_mesh()
        strategy = fedavg_strategy()
        data, padded = _client_data(mesh)
        step = build_round_step(
            model.apply, TrainingConfig(batch_size=SAMPLES, local_epochs=1),
            mesh, strategy,
        )
        params = model.init(jax.random.key(0))
        sos = init_server_state(strategy, params)

        def drifted(p, s, d, w, r, lr_scale=1.0):
            res = step(p, s, d, w, r, lr_scale)
            return res._replace(
                params=jax.tree.map(lambda x: x.astype(jnp.bfloat16), res.params)
            )

        with pytest.raises(ContractViolation, match="params"):
            check_round_step(
                drifted, params, sos, data,
                jax.ShapeDtypeStruct((padded,), jnp.float32),
                jax.eval_shape(lambda: stack_rngs(jax.random.key(0), padded)),
            )

    def test_experiment_summary_records_strict(self, tmp_path):
        from nanofed_tpu.experiments import run_experiment

        summary = run_experiment(
            model="mlp", num_clients=4, num_rounds=1, local_epochs=1,
            batch_size=32, train_size=256, out_dir=tmp_path, strict=True,
        )
        assert summary["strict"] is True
