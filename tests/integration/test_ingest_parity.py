"""Batched ingest ≡ per-submit aggregation, under chaos (ISSUE 7 satellite).

Two federations run the IDENTICAL client schedule — same deterministic
updates, same wire faults (drops, lost ACKs), same duplicates and corrupt
bodies — once over the per-submit path and once over the batched
device-resident ingest path, on the 8-device virtual CPU mesh the whole suite
runs on.  The trajectories must agree to float tolerance: round statuses,
cohort sizes, staleness stats, and the final global params.  This is the
proof that swapping the serving path cannot change the algorithm."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
    RetryPolicy,
)
from nanofed_tpu.faults import ChaosSchedule, FaultEvent, FaultPlan
from nanofed_tpu.ingest import IngestConfig
from nanofed_tpu.models import get_model
from nanofed_tpu.observability.registry import MetricsRegistry

PORT = 19100


def _params():
    return get_model("linear", in_features=6, num_classes=3).init(
        jax.random.key(0)
    )


def _flat(tree):
    return np.concatenate(
        [np.asarray(leaf, np.float32).ravel() for leaf in jax.tree.leaves(tree)]
    )


def _trained(global_params, i, r):
    """Deterministic 'local training': client i's round-r update."""
    return jax.tree.map(
        lambda p: np.asarray(p, np.float32) + (i + 1) * 0.01 + r * 0.003,
        global_params,
    )


def _chaos():
    """One seeded wire-fault schedule per run (both runs get an identical
    copy): a dropped connection c1 retries through, and a lost ACK whose
    retry must dedupe."""
    return ChaosSchedule(FaultPlan(seed=11, events=(
        FaultEvent(kind="drop", round=0, client="c1", count=1),
        FaultEvent(kind="ack_drop", round=1, client="c3", count=1),
    )))


async def _sync_client(i, port, params0, rounds):
    retry = RetryPolicy(max_attempts=5, base_backoff_s=0.02, seed=3)
    url = f"http://127.0.0.1:{port}"
    corrupt_once = {"left": 1 if i == 2 else 0}

    def flip(endpoint, body):
        if corrupt_once["left"]:
            corrupt_once["left"] -= 1
            return bytes(b ^ 0xFF for b in body)
        return body

    async with HTTPClient(url, f"c{i}", timeout_s=15, retry=retry,
                          wire_filter=flip) as c:
        for r in range(rounds):
            while True:
                p, rnd, active = await c.fetch_global_model(like=params0)
                if not active:
                    return
                if rnd == r:
                    break
                await asyncio.sleep(0.01)
            trained = _trained(p, i, r)
            metrics = {"num_samples": float(i + 1), "loss": 0.1 * (i + 1),
                       "accuracy": 0.5}
            ok = await c.submit_update(trained, metrics)
            if not ok:
                # The corrupt body was rejected (400 bad payload, FINAL) —
                # the client re-submits clean, same as a real re-encode.
                ok = await c.submit_update(trained, metrics)
            assert ok, f"c{i} round {r}"
            if i == 0:
                # Duplicate storm: the same bytes + idempotency key again.
                assert await c.resend_last_update()


async def _run_sync(port, ingest):
    params0 = _params()
    registry = MetricsRegistry()
    server = HTTPServer(
        port=port, registry=registry, chaos=_chaos(),
        ingest=IngestConfig(capacity=8) if ingest else None,
    )
    await server.start()
    try:
        coordinator = NetworkCoordinator(
            server, params0,
            NetworkRoundConfig(num_rounds=3, min_clients=5,
                               min_completion_rate=0.8, round_timeout_s=15),
            registry=registry,
        )
        # c4 is the dropper: it never submits; required = ceil(5*0.8) = 4,
        # so every round waits for ALL four live clients — including c1's
        # retry through its dropped connection — and completes without c4.
        tasks = [asyncio.create_task(_sync_client(i, port, params0, 3))
                 for i in range(4)]
        history = await coordinator.run()
        await asyncio.gather(*tasks)
        return history, coordinator.params, registry
    finally:
        await server.stop()


def test_sync_fedavg_batched_equals_per_submit_under_chaos():
    h_plain, p_plain, _ = asyncio.run(_run_sync(PORT, ingest=False))
    h_ingest, p_ingest, reg = asyncio.run(_run_sync(PORT + 1, ingest=True))
    assert [h["status"] for h in h_plain] == ["COMPLETED"] * 3
    assert [h["status"] for h in h_ingest] == ["COMPLETED"] * 3
    for a, b in zip(h_plain, h_ingest):
        assert a["num_clients"] == b["num_clients"]
        assert a["metrics"]["loss"] == pytest.approx(b["metrics"]["loss"],
                                                     abs=1e-5)
    np.testing.assert_allclose(_flat(p_plain), _flat(p_ingest),
                               rtol=1e-4, atol=1e-5)
    # The batched path really ran: drains + counters prove it.
    text = reg.render_prometheus()
    assert 'nanofed_ingest_drains_total{policy="fedavg"} 3' in text
    assert 'result="duplicate"' in text  # c0's storm deduped
    assert 'result="bad_payload"' in text  # c2's corrupt body rejected


async def _fedbuff_client(i, port, params0, plan):
    """``plan`` is a list of (wait_for_version, declared_round_lag, dup)
    tuples: fetch once per entry unless lagging (a stale client re-uses its
    old base and round), optionally re-send the same submit (duplicate)."""
    url = f"http://127.0.0.1:{port}"
    async with HTTPClient(url, f"c{i}", timeout_s=15,
                          retry=RetryPolicy(max_attempts=5,
                                            base_backoff_s=0.02, seed=4)) as c:
        last = None
        for step, (wait_version, lag, dup) in enumerate(plan):
            while True:
                status = await c.check_server_status()
                if not status.get("training_active", True):
                    return
                if status.get("round", -1) >= wait_version:
                    break
                await asyncio.sleep(0.01)
            if lag and last is not None:
                # Stale straggler: do NOT re-fetch; re-train from the old
                # base and submit for the old round.
                p = last
            else:
                p, rnd, active = await c.fetch_global_model(like=params0)
                if not active:
                    return
                last = p
            trained = _trained(p, i, step)
            assert await c.submit_update(
                trained, {"num_samples": float(i + 1), "loss": 0.2}
            )
            if dup:
                assert await c.resend_last_update()


async def _run_fedbuff(port, ingest):
    params0 = _params()
    registry = MetricsRegistry()
    server = HTTPServer(
        port=port, registry=registry,
        ingest=IngestConfig(capacity=16) if ingest else None,
    )
    await server.start()
    try:
        coordinator = NetworkCoordinator(
            server, params0,
            NetworkRoundConfig(num_rounds=3, async_buffer_k=3,
                               staleness_window=3, round_timeout_s=15,
                               poll_interval_s=0.01),
            registry=registry,
        )
        # Aggregation 0: everyone fresh at version 0.  Aggregation 1: c1
        # lags (submits for version 0 while the server is on 1 — staleness
        # weighting engages) and c0 duplicates.  Aggregation 2: all fresh.
        plans = {
            0: [(0, False, True), (1, False, False), (2, False, False)],
            1: [(0, False, False), (1, True, False), (2, False, False)],
            2: [(0, False, False), (1, False, False), (2, False, False)],
        }
        tasks = [
            asyncio.create_task(_fedbuff_client(i, port, params0, plan))
            for i, plan in plans.items()
        ]
        history = await coordinator.run()
        await asyncio.gather(*tasks)
        return history, coordinator.params
    finally:
        await server.stop()


def test_fedbuff_batched_equals_per_submit_with_staleness():
    h_plain, p_plain = asyncio.run(_run_fedbuff(PORT + 2, ingest=False))
    h_ingest, p_ingest = asyncio.run(_run_fedbuff(PORT + 3, ingest=True))
    assert [h["status"] for h in h_plain] == ["COMPLETED"] * 3
    assert [h["status"] for h in h_ingest] == ["COMPLETED"] * 3
    for a, b in zip(h_plain, h_ingest):
        assert a["num_clients"] == b["num_clients"]
        # Staleness weighting engaged identically on both paths (the per-
        # aggregation multisets match; buffer order within one drain is
        # arrival timing, not semantics).
        assert sorted(a["staleness"]) == sorted(b["staleness"])
        assert sorted(a["discounts"]) == sorted(b["discounts"])
    assert any(1 in h["staleness"] for h in h_ingest)  # the lag really happened
    np.testing.assert_allclose(_flat(p_plain), _flat(p_ingest),
                               rtol=1e-4, atol=1e-5)


def test_ingest_refuses_per_update_mechanisms():
    """validation/robust need individual update trees, which batched ingest
    folds away at submit time — the combination must refuse loudly."""
    from nanofed_tpu.security.validation import ValidationConfig

    params0 = _params()
    server = HTTPServer(port=PORT + 4, registry=MetricsRegistry(),
                        ingest=IngestConfig(capacity=4))
    with pytest.raises(ValueError, match="batched ingest"):
        NetworkCoordinator(
            server, params0, NetworkRoundConfig(num_rounds=1),
            validation=ValidationConfig(),
        )
