"""Batched ingest ≡ per-submit aggregation, under chaos (ISSUE 7 satellite).

Two federations run the IDENTICAL client schedule — same deterministic
updates, same wire faults (drops, lost ACKs), same duplicates and corrupt
bodies — once over the per-submit path and once over the batched
device-resident ingest path, on the 8-device virtual CPU mesh the whole suite
runs on.  The trajectories must agree to float tolerance: round statuses,
cohort sizes, staleness stats, and the final global params.  This is the
proof that swapping the serving path cannot change the algorithm."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
    RetryPolicy,
)
from nanofed_tpu.faults import ChaosSchedule, FaultEvent, FaultPlan
from nanofed_tpu.ingest import IngestConfig
from nanofed_tpu.models import get_model
from nanofed_tpu.observability.registry import MetricsRegistry

PORT = 19100


def _params():
    return get_model("linear", in_features=6, num_classes=3).init(
        jax.random.key(0)
    )


def _flat(tree):
    return np.concatenate(
        [np.asarray(leaf, np.float32).ravel() for leaf in jax.tree.leaves(tree)]
    )


def _trained(global_params, i, r):
    """Deterministic 'local training': client i's round-r update."""
    return jax.tree.map(
        lambda p: np.asarray(p, np.float32) + (i + 1) * 0.01 + r * 0.003,
        global_params,
    )


def _chaos():
    """One seeded wire-fault schedule per run (both runs get an identical
    copy): a dropped connection c1 retries through, and a lost ACK whose
    retry must dedupe."""
    return ChaosSchedule(FaultPlan(seed=11, events=(
        FaultEvent(kind="drop", round=0, client="c1", count=1),
        FaultEvent(kind="ack_drop", round=1, client="c3", count=1),
    )))


async def _sync_client(i, port, params0, rounds):
    retry = RetryPolicy(max_attempts=5, base_backoff_s=0.02, seed=3)
    url = f"http://127.0.0.1:{port}"
    corrupt_once = {"left": 1 if i == 2 else 0}

    def flip(endpoint, body):
        if corrupt_once["left"]:
            corrupt_once["left"] -= 1
            return bytes(b ^ 0xFF for b in body)
        return body

    async with HTTPClient(url, f"c{i}", timeout_s=15, retry=retry,
                          wire_filter=flip) as c:
        for r in range(rounds):
            while True:
                p, rnd, active = await c.fetch_global_model(like=params0)
                if not active:
                    return
                if rnd == r:
                    break
                await asyncio.sleep(0.01)
            trained = _trained(p, i, r)
            metrics = {"num_samples": float(i + 1), "loss": 0.1 * (i + 1),
                       "accuracy": 0.5}
            ok = await c.submit_update(trained, metrics)
            if not ok:
                # The corrupt body was rejected (400 bad payload, FINAL) —
                # the client re-submits clean, same as a real re-encode.
                ok = await c.submit_update(trained, metrics)
            assert ok, f"c{i} round {r}"
            if i == 0:
                # Duplicate storm: the same bytes + idempotency key again.
                assert await c.resend_last_update()


async def _run_sync(port, ingest):
    params0 = _params()
    registry = MetricsRegistry()
    server = HTTPServer(
        port=port, registry=registry, chaos=_chaos(),
        ingest=IngestConfig(capacity=8) if ingest else None,
    )
    await server.start()
    try:
        coordinator = NetworkCoordinator(
            server, params0,
            NetworkRoundConfig(num_rounds=3, min_clients=5,
                               min_completion_rate=0.8, round_timeout_s=15),
            registry=registry,
        )
        # c4 is the dropper: it never submits; required = ceil(5*0.8) = 4,
        # so every round waits for ALL four live clients — including c1's
        # retry through its dropped connection — and completes without c4.
        tasks = [asyncio.create_task(_sync_client(i, port, params0, 3))
                 for i in range(4)]
        history = await coordinator.run()
        await asyncio.gather(*tasks)
        return history, coordinator.params, registry
    finally:
        await server.stop()


def test_sync_fedavg_batched_equals_per_submit_under_chaos():
    h_plain, p_plain, _ = asyncio.run(_run_sync(PORT, ingest=False))
    h_ingest, p_ingest, reg = asyncio.run(_run_sync(PORT + 1, ingest=True))
    assert [h["status"] for h in h_plain] == ["COMPLETED"] * 3
    assert [h["status"] for h in h_ingest] == ["COMPLETED"] * 3
    for a, b in zip(h_plain, h_ingest):
        assert a["num_clients"] == b["num_clients"]
        assert a["metrics"]["loss"] == pytest.approx(b["metrics"]["loss"],
                                                     abs=1e-5)
    np.testing.assert_allclose(_flat(p_plain), _flat(p_ingest),
                               rtol=1e-4, atol=1e-5)
    # The batched path really ran: drains + counters prove it.
    text = reg.render_prometheus()
    assert 'nanofed_ingest_drains_total{policy="fedavg"} 3' in text
    assert 'result="duplicate"' in text  # c0's storm deduped
    assert 'result="bad_payload"' in text  # c2's corrupt body rejected


async def _fedbuff_client(i, port, params0, plan):
    """``plan`` is a list of (wait_for_version, declared_round_lag, dup)
    tuples: fetch once per entry unless lagging (a stale client re-uses its
    old base and round), optionally re-send the same submit (duplicate)."""
    url = f"http://127.0.0.1:{port}"
    async with HTTPClient(url, f"c{i}", timeout_s=15,
                          retry=RetryPolicy(max_attempts=5,
                                            base_backoff_s=0.02, seed=4)) as c:
        last = None
        for step, (wait_version, lag, dup) in enumerate(plan):
            while True:
                status = await c.check_server_status()
                if not status.get("training_active", True):
                    return
                if status.get("round", -1) >= wait_version:
                    break
                await asyncio.sleep(0.01)
            if lag and last is not None:
                # Stale straggler: do NOT re-fetch; re-train from the old
                # base and submit for the old round.
                p = last
            else:
                p, rnd, active = await c.fetch_global_model(like=params0)
                if not active:
                    return
                last = p
            trained = _trained(p, i, step)
            assert await c.submit_update(
                trained, {"num_samples": float(i + 1), "loss": 0.2}
            )
            if dup:
                assert await c.resend_last_update()


async def _run_fedbuff(port, ingest):
    params0 = _params()
    registry = MetricsRegistry()
    server = HTTPServer(
        port=port, registry=registry,
        ingest=IngestConfig(capacity=16) if ingest else None,
    )
    await server.start()
    try:
        coordinator = NetworkCoordinator(
            server, params0,
            NetworkRoundConfig(num_rounds=3, async_buffer_k=3,
                               staleness_window=3, round_timeout_s=15,
                               poll_interval_s=0.01),
            registry=registry,
        )
        # Aggregation 0: everyone fresh at version 0.  Aggregation 1: c1
        # lags (submits for version 0 while the server is on 1 — staleness
        # weighting engages) and c0 duplicates.  Aggregation 2: all fresh.
        plans = {
            0: [(0, False, True), (1, False, False), (2, False, False)],
            1: [(0, False, False), (1, True, False), (2, False, False)],
            2: [(0, False, False), (1, False, False), (2, False, False)],
        }
        tasks = [
            asyncio.create_task(_fedbuff_client(i, port, params0, plan))
            for i, plan in plans.items()
        ]
        history = await coordinator.run()
        await asyncio.gather(*tasks)
        return history, coordinator.params
    finally:
        await server.stop()


def test_fedbuff_batched_equals_per_submit_with_staleness():
    h_plain, p_plain = asyncio.run(_run_fedbuff(PORT + 2, ingest=False))
    h_ingest, p_ingest = asyncio.run(_run_fedbuff(PORT + 3, ingest=True))
    assert [h["status"] for h in h_plain] == ["COMPLETED"] * 3
    assert [h["status"] for h in h_ingest] == ["COMPLETED"] * 3
    for a, b in zip(h_plain, h_ingest):
        assert a["num_clients"] == b["num_clients"]
        # Staleness weighting engaged identically on both paths (the per-
        # aggregation multisets match; buffer order within one drain is
        # arrival timing, not semantics).
        assert sorted(a["staleness"]) == sorted(b["staleness"])
        assert sorted(a["discounts"]) == sorted(b["discounts"])
    assert any(1 in h["staleness"] for h in h_ingest)  # the lag really happened
    np.testing.assert_allclose(_flat(p_plain), _flat(p_ingest),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Ingest-on-hosts parity (ISSUE 19): host-local PARTIAL drains composed by the
# one cross-host psum ≡ a single host draining the union of the buffers.  This
# is the algebraic contract the wire→mesh bridge rests on — unnormalized
# Σ w δ / Σ w is the union's weighted mean under ANY client→host partition.
# ---------------------------------------------------------------------------

FLAT = 11


def _pipeline(capacity):
    from nanofed_tpu.ingest.pipeline import IngestPipeline

    return IngestPipeline(
        {"w": np.zeros(FLAT, np.float32)}, IngestConfig(capacity=capacity),
        registry=MetricsRegistry(),
    )


def _hier_mesh():
    from nanofed_tpu.parallel.mesh import make_mesh

    return make_mesh(shape=(2, 4, 1))  # 2 virtual hosts over the 8-dev suite


def test_hierarchical_fedavg_partials_equal_union_drain_strict():
    """Three FedAvg rounds through two host-local buffers + the ONE cross-host
    reduce track the single-host union drain to 1e-4 — with the cross-host
    dispatch under ``jax.transfer_guard("disallow")`` (strict mode: committed
    inputs, zero implicit transfers)."""
    from nanofed_tpu.communication.federation import (
        assemble_host_rows,
        build_cross_host_reduce,
        host_partial_row,
    )
    from nanofed_tpu.parallel.mesh import replicated_sharding

    mesh = _hier_mesh()
    repl = replicated_sharding(mesh)
    fn = build_cross_host_reduce(mesh, FLAT)
    hosts = [_pipeline(8), _pipeline(8)]
    union = _pipeline(16)
    rng = np.random.default_rng(5)
    hier = rng.normal(size=FLAT).astype(np.float32)
    flat_union = hier.copy()
    for r in range(3):
        union.note_version(r, {"w": flat_union}, window=0)
        for h, pipe in enumerate(hosts):
            for j in range(3 + h):  # uneven cohorts: 3 on host0, 4 on host1
                delta = (rng.normal(size=FLAT) * 0.1).astype(np.float32)
                cid, w = f"h{h}_c{j}", float(1 + j + 2 * h)
                for target in (pipe, union):
                    target.offer(delta, client_id=cid, round_number=r,
                                 metrics={"num_samples": w})
        rows = []
        for pipe in hosts:
            out, mass, metas = pipe.drain_fedavg_partial()
            assert metas, "host drained empty"
            rows.append(host_partial_row(np.asarray(out), mass, FLAT))
        rows_dev = assemble_host_rows(mesh, np.stack(rows))
        base_dev = jax.device_put(jnp.asarray(hier), repl)
        with jax.transfer_guard("disallow"):
            new_dev, tail_dev = fn(rows_dev, base_dev)
        hier = np.asarray(new_dev)
        u_out, u_metas = union.drain_fedavg(r)
        assert len(u_metas) == 7
        flat_union = np.asarray(u_out)
        np.testing.assert_allclose(hier, flat_union, rtol=1e-4, atol=1e-4)
        assert float(np.asarray(tail_dev)[0]) == pytest.approx(
            sum(m.weight for m in u_metas)
        )


def test_hierarchical_fedbuff_partials_match_union_staleness():
    """Per-host FedBuff partial drains + the cross-host reduce reproduce the
    union drain: IDENTICAL staleness/discount multisets (union of the hosts'
    stats ≡ the single-host stats) and the same applied params — server_lr
    and 1/K applied once, globally, after the psum."""
    from nanofed_tpu.communication.federation import (
        assemble_host_rows,
        build_cross_host_reduce,
        host_partial_row,
    )
    from nanofed_tpu.parallel.mesh import replicated_sharding

    mesh = _hier_mesh()
    fn = build_cross_host_reduce(mesh, FLAT)
    hosts = [_pipeline(8), _pipeline(8)]
    union = _pipeline(16)
    rng = np.random.default_rng(9)
    versions = {v: rng.normal(size=FLAT).astype(np.float32) for v in range(3)}
    for pipe in (*hosts, union):
        for v, flat in versions.items():
            pipe.note_version(v, {"w": flat}, window=2)
    # (host, base_version) offers: mixed staleness on both hosts, plus one
    # slot whose base version left the window (skipped identically).
    offers = [(0, 2), (0, 1), (0, 0), (1, 2), (1, 1), (1, 7)]
    for j, (h, v) in enumerate(offers):
        delta = (rng.normal(size=FLAT) * 0.1).astype(np.float32)
        cid = f"c{j}"
        hosts[h].offer(delta, client_id=cid, round_number=v,
                       metrics={"num_samples": 1.0})
        union.offer(delta, client_id=cid, round_number=v,
                    metrics={"num_samples": 1.0})
    rows, stats_union_of_hosts = [], {"staleness": [], "discounts": [],
                                      "skipped": 0}
    for pipe in hosts:
        out, metas, stats = pipe.drain_fedbuff_partial(
            k=pipe.fill, current_version=2
        )
        stats_union_of_hosts["staleness"] += stats["staleness"]
        stats_union_of_hosts["discounts"] += stats["discounts"]
        stats_union_of_hosts["skipped"] += stats["num_skipped_out_of_window"]
        rows.append(host_partial_row(
            np.asarray(out), float(stats["num_aggregated"]), FLAT
        ))
    u_out, u_live, u_stats = union.drain_fedbuff(
        k=6, current_version=2, server_lr=1.0
    )
    assert sorted(stats_union_of_hosts["staleness"]) == sorted(
        u_stats["staleness"]
    )
    assert sorted(stats_union_of_hosts["discounts"]) == sorted(
        u_stats["discounts"]
    )
    assert stats_union_of_hosts["skipped"] == u_stats[
        "num_skipped_out_of_window"
    ] == 1
    base_dev = jax.device_put(
        jnp.asarray(versions[2]), replicated_sharding(mesh)
    )
    new_dev, tail_dev = fn(assemble_host_rows(mesh, np.stack(rows)), base_dev)
    assert int(np.asarray(tail_dev)[0]) == u_stats["num_aggregated"] == 5
    np.testing.assert_allclose(
        np.asarray(new_dev), np.asarray(u_out), rtol=1e-5, atol=1e-6
    )


def test_fused_drained_ingest_program_matches_two_stage():
    """The single fused program (per-device ingest slabs → host-local reduce →
    one hosts psum → apply) and the two-stage runtime path (host partial rows
    → cross-host reduce) are the same function."""
    from nanofed_tpu.communication.federation import (
        assemble_host_rows,
        build_cross_host_reduce,
        build_drained_ingest_reduce,
        host_partial_row,
    )
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    from nanofed_tpu.parallel.mesh import CLIENT_AXIS, HOST_AXIS, replicated_sharding

    mesh = _hier_mesh()
    cap, shards = 4, 8  # 2 hosts x 4 client shards
    rng = np.random.default_rng(3)
    buf = rng.normal(size=(shards, cap, FLAT)).astype(np.float32)
    coefs = np.abs(rng.normal(size=(shards, cap))).astype(np.float32)
    coefs[0, 1] = 0.0  # an unoccupied slot: exact-zero coefficient
    base = rng.normal(size=FLAT).astype(np.float32)
    spec = NamedSharding(mesh, P((HOST_AXIS, CLIENT_AXIS)))
    fused = build_drained_ingest_reduce(mesh, cap, FLAT)
    out_fused = fused(
        jax.device_put(buf, spec), jax.device_put(coefs, spec),
        jax.device_put(jnp.asarray(base), replicated_sharding(mesh)),
    )
    rows = []
    for h in range(2):
        shard_slice = slice(h * 4, (h + 1) * 4)
        num = np.einsum("sc,scp->p", coefs[shard_slice], buf[shard_slice])
        rows.append(host_partial_row(
            num, float(coefs[shard_slice].sum()), FLAT
        ))
    two_stage = build_cross_host_reduce(mesh, FLAT)
    out_two, _ = two_stage(
        assemble_host_rows(mesh, np.stack(rows)),
        jax.device_put(jnp.asarray(base), replicated_sharding(mesh)),
    )
    np.testing.assert_allclose(
        np.asarray(out_fused), np.asarray(out_two), rtol=1e-5, atol=1e-6
    )


def test_ingest_refuses_per_update_mechanisms():
    """validation/robust need individual update trees, which batched ingest
    folds away at submit time — the combination must refuse loudly."""
    from nanofed_tpu.security.validation import ValidationConfig

    params0 = _params()
    server = HTTPServer(port=PORT + 4, registry=MetricsRegistry(),
                        ingest=IngestConfig(capacity=4))
    with pytest.raises(ValueError, match="batched ingest"):
        NetworkCoordinator(
            server, params0, NetworkRoundConfig(num_rounds=1),
            validation=ValidationConfig(),
        )
