"""Secure aggregation carried end-to-end over the real HTTP transport, and network-path
update validation.

The reference wires ``ThresholdSecureAggregation`` into its aggregator
(``nanofed/server/aggregator/privacy.py:311-319``) but its transport cannot carry a
masked round and its crypto is placeholder-grade; here a full Bonawitz masked round runs
over real aiohttp sockets: enroll -> roster -> mask -> POST -> modular sum -> unmask,
with the aggregate matching plain FedAvg to quantization tolerance while the server only
ever buffers uniform uint32 vectors.

The validation tests cover the gap the reference also has (``DefaultModelValidator``
exists but its coordinator never calls it): a NaN-injecting or oversized networked
client is dropped before aggregation.
"""

import pytest

pytest.importorskip(
    "cryptography", reason="secure-aggregation protocol tests need the optional crypto dependency"
)

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.communication import (
    HTTPClient,
    HTTPServer,
    NetworkCoordinator,
    NetworkRoundConfig,
)
from nanofed_tpu.communication.network_coordinator import stack_model_updates
from nanofed_tpu.aggregation.fedavg import fedavg_combine
from nanofed_tpu.core.types import ModelUpdate
from nanofed_tpu.models import get_model
from nanofed_tpu.security.secure_agg import (
    ClientKeyPair,
    SecureAggregationConfig,
    mask_update,
)
from nanofed_tpu.security.validation import ValidationConfig

PORT = 18473


def _client_params(model, seed):
    return model.init(jax.random.key(seed))


async def _fetch_model_retry(client, like, attempts=100, delay=0.05):
    """The coordinator publishes the round-0 model concurrently with client startup;
    retry briefly instead of failing on a 503 'no model published'."""
    from nanofed_tpu.core.exceptions import NanoFedError

    for _ in range(attempts):
        try:
            return await client.fetch_global_model(like=like)
        except NanoFedError:
            await asyncio.sleep(delay)
    raise TimeoutError("model never published")


def test_masked_round_end_to_end_matches_fedavg():
    """3 real aiohttp clients run one full masked round; the coordinator's aggregate
    equals the unmasked weighted FedAvg within quantization tolerance, and the server
    never observes any individual update (its masked buffer holds uniform uint32)."""
    model = get_model("linear", in_features=6, num_classes=2)
    init = _client_params(model, 0)
    cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
    num_samples = {"c1": 30.0, "c2": 10.0, "c3": 20.0}
    local_params = {cid: _client_params(model, s)
                    for s, cid in enumerate(num_samples, start=1)}
    observed_masked = {}

    async def run_client(cid: str):
        keypair = ClientKeyPair.generate()
        async with HTTPClient(f"http://127.0.0.1:{PORT}", cid, timeout_s=30) as client:
            assert await client.register_secagg(keypair.public_bytes(), num_samples[cid])
            roster = await client.fetch_secagg_roster()
            params, rnd, active = await _fetch_model_retry(client, init)
            assert active
            masked = mask_update(
                local_params[cid],
                roster.index_of(cid),
                keypair,
                roster.ordered_keys(),
                rnd,
                cfg,
                weight=roster.weights[cid],
            )
            observed_masked[cid] = masked
            assert await client.submit_masked_update(masked, {"num_samples": num_samples[cid]})

    async def main():
        server = HTTPServer(port=PORT)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=1, min_clients=3, round_timeout_s=30),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(), *(run_client(c) for c in num_samples)
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    assert [h["status"] for h in coordinator.history] == ["COMPLETED"]
    assert coordinator.history[0]["secure"] is True

    # Expected: plain weighted FedAvg over the same updates.
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=local_params[c],
                    metrics={"num_samples": num_samples[c]}, timestamp="")
        for c in num_samples
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)

    # The server-side payloads are masked: each wire vector must NOT equal the client's
    # bare quantized update (masks applied), and mask cancellation requires all three.
    from nanofed_tpu.security.secure_agg import quantize
    from nanofed_tpu.utils.trees import tree_ravel

    for cid, masked in observed_masked.items():
        flat, _ = tree_ravel(local_params[cid])
        bare = quantize(np.asarray(flat, np.float64) * 1.0, cfg.frac_bits)
        assert not np.array_equal(masked, bare)


def test_masked_round_fails_on_dropout():
    """No-dropout SecAgg semantics: if an enrolled client never submits, the round is
    FAILED (uncancelled masks must never be dequantized into params)."""
    model = get_model("linear", in_features=4, num_classes=2)
    init = _client_params(model, 0)
    cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)

    async def run_client(cid: str, submit: bool):
        keypair = ClientKeyPair.generate()
        async with HTTPClient(f"http://127.0.0.1:{PORT + 1}", cid, timeout_s=10) as client:
            assert await client.register_secagg(keypair.public_bytes(), 10.0)
            roster = await client.fetch_secagg_roster()
            params, rnd, active = await _fetch_model_retry(client, init)
            if submit:
                masked = mask_update(
                    _client_params(model, 3), roster.index_of(cid), keypair,
                    roster.ordered_keys(), rnd, cfg, weight=roster.weights[cid],
                )
                await client.submit_masked_update(masked, {})

    async def main():
        server = HTTPServer(port=PORT + 1)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=1, min_clients=3, round_timeout_s=1.5),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                run_client("c1", True),
                run_client("c2", True),
                run_client("c3", False),  # enrolled but silent
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    assert coordinator.history[0]["status"] == "FAILED"
    # Params untouched by the failed round.
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(init)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_nan_injecting_client_is_rejected():
    """Network-path validation: a malicious client POSTing NaN params is dropped with a
    logged reason; the aggregate is computed from the honest clients only."""
    model = get_model("linear", in_features=5, num_classes=2)
    init = _client_params(model, 0)
    honest = {f"h{i}": _client_params(model, i) for i in (1, 2, 3)}

    async def run_honest(cid):
        async with HTTPClient(f"http://127.0.0.1:{PORT + 2}", cid, timeout_s=10) as c:
            params, rnd, active = await _fetch_model_retry(c, init)
            assert await c.submit_update(honest[cid], {"num_samples": 10.0})

    async def run_malicious():
        async with HTTPClient(f"http://127.0.0.1:{PORT + 2}", "evil", timeout_s=10) as c:
            params, rnd, active = await _fetch_model_retry(c, init)
            poisoned = jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), init)
            assert await c.submit_update(poisoned, {"num_samples": 1e9})

    async def main():
        server = HTTPServer(port=PORT + 2)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=1, min_clients=4, round_timeout_s=10),
                validation=ValidationConfig(max_norm=100.0),
            )
            await asyncio.gather(
                coordinator.run(),
                *(run_honest(c) for c in honest),
                run_malicious(),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    # 4 received, 1 rejected -> below min_clients, round FAILED, but crucially the
    # NaN never reached the params.
    record = coordinator.history[0]
    assert record["num_rejected"] == 1
    assert all(np.all(np.isfinite(np.asarray(leaf)))
               for leaf in jax.tree.leaves(coordinator.params))


def test_nan_client_dropped_but_round_completes_with_completion_rate():
    """With min_completion_rate < 1 the round still completes from the honest cohort."""
    model = get_model("linear", in_features=5, num_classes=2)
    init = _client_params(model, 0)
    honest = {f"h{i}": _client_params(model, i) for i in (1, 2, 3)}

    async def run_honest(cid):
        async with HTTPClient(f"http://127.0.0.1:{PORT + 3}", cid, timeout_s=10) as c:
            await _fetch_model_retry(c, init)
            assert await c.submit_update(honest[cid], {"num_samples": 10.0})

    async def run_malicious():
        async with HTTPClient(f"http://127.0.0.1:{PORT + 3}", "evil", timeout_s=10) as c:
            await _fetch_model_retry(c, init)
            poisoned = jax.tree.map(lambda x: jnp.full_like(x, jnp.inf), init)
            assert await c.submit_update(poisoned, {"num_samples": 10.0})

    async def main():
        server = HTTPServer(port=PORT + 3)
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=1, min_clients=4,
                                   min_completion_rate=0.75, round_timeout_s=10),
                validation=ValidationConfig(max_norm=100.0),
            )
            await asyncio.gather(
                coordinator.run(),
                *(run_honest(c) for c in honest),
                run_malicious(),
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    record = coordinator.history[0]
    assert record["status"] == "COMPLETED"
    assert record["num_rejected"] == 1
    assert record["num_clients"] == 3
    expected = fedavg_combine(stack_model_updates([
        ModelUpdate(client_id=c, round_number=0, params=honest[c],
                    metrics={"num_samples": 10.0}, timestamp="")
        for c in sorted(honest)
    ]))
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_forged_masked_update_rejected_under_signatures():
    """require_signatures=True applies to MASKED payloads too: an attacker who knows an
    enrolled client id cannot inject an unsigned uint32 vector; the honest cohort's
    signed masked round completes."""
    from nanofed_tpu.security.signing import SecurityManager

    model = get_model("linear", in_features=4, num_classes=2)
    init = _client_params(model, 0)
    cfg = SecureAggregationConfig(min_clients=3, frac_bits=16)
    managers = {c: SecurityManager(key_size=1024) for c in ("c1", "c2", "c3")}
    rejected = {}

    async def run_client(cid: str, forge: bool):
        keypair = ClientKeyPair.generate()
        async with HTTPClient(
            f"http://127.0.0.1:{PORT + 4}", cid, timeout_s=10,
            security_manager=managers[cid],
        ) as client:
            assert await client.register_secagg(keypair.public_bytes(), 10.0)
            roster = await client.fetch_secagg_roster()
            params, rnd, active = await _fetch_model_retry(client, init)
            masked = mask_update(
                _client_params(model, 7), roster.index_of(cid), keypair,
                roster.ordered_keys(), rnd, cfg, weight=roster.weights[cid],
            )
            if forge:
                # Enrolled legitimately, then submits WITHOUT signing (e.g. a stolen
                # session replaying through a different stack).
                client.security_manager = None
            ok = await client.submit_masked_update(masked, {})
            rejected[cid] = not ok

    async def main():
        server = HTTPServer(
            port=PORT + 4,
            client_keys={c: m.get_public_key() for c, m in managers.items()},
            require_signatures=True,
        )
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, init,
                NetworkRoundConfig(num_rounds=1, min_clients=3, round_timeout_s=2.0),
                secure=cfg,
            )
            await asyncio.gather(
                coordinator.run(),
                run_client("c1", False),
                run_client("c2", False),
                run_client("c3", True),  # enrolled, but submits UNSIGNED
            )
            return coordinator
        finally:
            await server.stop()

    coordinator = asyncio.run(main())
    # The forged submission bounced (403) -> cohort incomplete -> round FAILED and the
    # forged vector never reached the aggregate.
    assert rejected == {"c1": False, "c2": False, "c3": True}
    assert coordinator.history[0]["status"] == "FAILED"
    for got, want in zip(jax.tree.leaves(coordinator.params), jax.tree.leaves(init)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_unsigned_enrollment_rejected_under_signatures():
    """require_signatures gates ENROLLMENT too: an attacker who knows a client id
    cannot claim its cohort slot (and mask identity) with an unsigned register."""
    import asyncio as aio

    from aiohttp.test_utils import TestClient, TestServer

    from nanofed_tpu.security.signing import SecurityManager

    manager = SecurityManager(key_size=1024)

    async def scenario():
        import base64

        server = HTTPServer(
            port=0, client_keys={"c1": manager.get_public_key()},
            require_signatures=True,
        )
        client = TestClient(TestServer(server._app))
        await client.start_server()
        try:
            await server.open_secagg(1)
            session = (await (await client.get("/secagg/roster")).json())["session"]
            pk = bytes(32)
            body = {"public_key": base64.b64encode(pk).decode(), "num_samples": 10.0}
            # Unsigned -> 403; unknown id -> 403; correctly signed -> 200.
            r = await client.post("/secagg/register", json=body,
                                  headers={"X-NanoFed-Client": "c1"})
            assert r.status == 403
            r = await client.post("/secagg/register", json=body,
                                  headers={"X-NanoFed-Client": "intruder"})
            assert r.status == 403
            sig = base64.b64encode(
                manager.sign_enrollment("c1", pk, 10.0, session)).decode()
            r = await client.post("/secagg/register", json=body,
                                  headers={"X-NanoFed-Client": "c1",
                                           "X-NanoFed-Signature": sig})
            assert r.status == 200
            # Idempotent retry: identical signed payload (int/float sample counts
            # sign identically — JSON round-trips both to float) -> 200.
            sig2 = base64.b64encode(
                manager.sign_enrollment("c1", pk, 10, session)).decode()
            r = await client.post("/secagg/register", json=body,
                                  headers={"X-NanoFed-Client": "c1",
                                           "X-NanoFed-Signature": sig2})
            assert r.status == 200
            # REPLAY into a fresh cohort: the old signature no longer verifies
            # (bound to the previous session nonce).
            await server.open_secagg(1)
            r = await client.post("/secagg/register", json=body,
                                  headers={"X-NanoFed-Client": "c1",
                                           "X-NanoFed-Signature": sig})
            assert r.status == 403
            # A DIFFERENT key for an enrolled id is refused even when validly signed
            # (mid-session key swap would break mask cancellation).
            await server.open_secagg(1)
            session3 = (await (await client.get("/secagg/roster")).json())["session"]
            sig3 = base64.b64encode(
                manager.sign_enrollment("c1", pk, 10.0, session3)).decode()
            assert (await client.post("/secagg/register", json=body,
                                      headers={"X-NanoFed-Client": "c1",
                                               "X-NanoFed-Signature": sig3})).status == 200
            pk2 = bytes(31) + b"x"
            body2 = {"public_key": base64.b64encode(pk2).decode(), "num_samples": 10.0}
            sig4 = base64.b64encode(
                manager.sign_enrollment("c1", pk2, 10.0, session3)).decode()
            r = await client.post("/secagg/register", json=body2,
                                  headers={"X-NanoFed-Client": "c1",
                                           "X-NanoFed-Signature": sig4})
            assert r.status == 409
        finally:
            await client.close()

    aio.new_event_loop().run_until_complete(scenario())
