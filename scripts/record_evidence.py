#!/usr/bin/env python
"""Record benchmark-evidence artifacts beyond the headline bench (VERDICT r2 items 6, 9).

Four modes, each writing a ``runs/*_r{N}.json`` artifact:

- ``dp``        — DP-FedAvg (central clip+noise at the reduce) on REAL digit images
                  upsampled to the flagship CNN's 28x28 input: per-round (ε, δ) spend
                  from the coordinator's accountant alongside the accuracy trajectory.
                  Capability parity: the reference computes DP aggregation
                  (``nanofed/server/aggregator/privacy.py:299-346``) but never records
                  a spend-vs-accuracy artifact.
- ``fedprox``   — FedProx vs FedAvg under severe Dirichlet non-IID skew (the thing
                  FedProx is FOR, Li et al. 2020): multi-seed trajectories at
                  μ ∈ {0, 0.05, 0.2} in a high-drift regime (16 local epochs, C=0.3).
                  The reference has no FedProx at all; BASELINE.json config #3 names it.
- ``labelskew`` — BASELINE.json config #2 end-to-end on REAL data: 100 clients,
                  2-class label-skew shards, C=0.1 participation, the flagship CNN on
                  the real digits images upsampled to its 28x28 input.
- ``byzantine`` — the trimmed-mean defense measured: poisoned clients (scaled inputs
                  + shifted labels) collapse plain FedAvg while
                  ``robust=RobustAggregationConfig`` holds the clean trajectory.
- ``scaffold``  — SCAFFOLD vs FedProx vs FedAvg in the fedprox mode's high-drift
                  regime (Karimireddy et al. 2020): the control-variate correction
                  measured against both the uncorrected and proximally-damped arms.
- ``personalization`` — global vs fine-tuned-per-client accuracy on each client's
                  own held-out split under label skew (the FedAvg-then-fine-tune
                  baseline of Wang et al. 2019).

Usage:
    python scripts/record_evidence.py dp [--round-tag r03]
    python scripts/record_evidence.py fedprox
    python scripts/record_evidence.py labelskew
    python scripts/record_evidence.py byzantine
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _trajectory(coord) -> list[dict]:
    """Drain a coordinator, collecting per-round eval/train metrics."""
    t0 = time.time()
    out = []
    for m in coord.start_training():
        row = {"round": m.round_id, "elapsed_s": round(time.time() - t0, 2),
               "duration_s": round(m.duration_s, 4)}
        for k in ("privacy_epsilon", "privacy_delta"):
            if k in m.agg_metrics:
                row[k] = round(float(m.agg_metrics[k]), 6)
        if m.eval_metrics.get("accuracy") is not None:
            row["test_accuracy"] = round(float(m.eval_metrics["accuracy"]), 4)
        out.append(row)
    return out


def _final_accuracy(traj: list[dict]) -> float | None:
    """Last EVALUATED accuracy — the final round may not be an eval round when
    num_rounds % eval_every != 0 (the commit-ac86b76 semantics, in ONE place)."""
    return next((r["test_accuracy"] for r in reversed(traj)
                 if "test_accuracy" in r), None)


def _write(name: str, artifact: dict) -> Path:
    out = REPO / "runs" / f"{name}.json"
    out.parent.mkdir(exist_ok=True)
    out.write_text(json.dumps(artifact, indent=2))
    print(f"\nartifact written to {out}")
    return out


def run_dp(tag: str, model_name: str = "linear", num_rounds: int = 40,
           eval_every: int = 1) -> int:
    """DP-FedAvg privacy-utility curve on REAL digits.

    Central DP only pays off in the many-clients regime: per-round SNR of the noised
    mean is K/(σ·√d) (signal ≤ C after clipping; noise ℓ2 ≈ σ·C·√d/K), so the honest
    demonstration — the one the DP-FedAvg literature (McMahan et al. 2018) actually
    runs — uses many clients, a small model, and client-subsampling amplification.
    Arms: no-DP control + ε ∈ {1, 4, 8}, each σ calibrated for the full run via RDP
    with q = participation_rate.

    ``model_name="cnn"`` runs the same arms with the FLAGSHIP MNIST CNN on the real
    digits upsampled to 28x28 (VERDICT r3 item 7): DP noise hurts a 1.2M-parameter
    model differently than logistic regression — noise ℓ2 grows with √d — so the
    utility half of "privacy-utility" is measured on the model the framework
    headlines, not a stand-in.
    """
    import jax

    from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.privacy import PrivacyConfig
    from nanofed_tpu.privacy.accounting import noise_multiplier_for_budget
    from nanofed_tpu.trainer import TrainingConfig

    from nanofed_tpu.orchestration import cohort_size

    budget_delta = 1e-5
    num_clients, participation = 240, 0.1  # cohort K=24, q=0.1 (amplification regime)
    cohort = cohort_size(num_clients, participation)
    # Realized per-client inclusion probability (= what the coordinator accounts at).
    q = cohort / num_clients
    clip = 0.5
    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    if model_name == "cnn":
        from nanofed_tpu.data.datasets import resize_images

        train = resize_images(train, 28, 28)
        test = resize_images(test, 28, 28)
        model = get_model("mnist_cnn")
        model_desc = "mnist_cnn (flagship ~1.2M params) on digits@28x28"
        training = TrainingConfig(batch_size=8, local_epochs=4, learning_rate=0.1)
    else:
        model = get_model("linear", in_features=64, num_classes=10)
        model_desc = "linear(64->10)"
        training = TrainingConfig(batch_size=6, local_epochs=4, learning_rate=0.3)

    def make_coord(central_privacy, seed=0):
        return Coordinator(
            model=model,
            train_data=federate(train, num_clients=num_clients, scheme="iid",
                                batch_size=training.batch_size, seed=seed),
            config=CoordinatorConfig(num_rounds=num_rounds, seed=seed,
                                     participation_rate=participation,
                                     base_dir="runs/dp_run", eval_every=eval_every,
                                     save_metrics=False),
            training=training,
            eval_data=pack_eval(test, batch_size=256),
            central_privacy=central_privacy,
        )

    final_acc_of = _final_accuracy

    name = f"dp_fedavg_{tag}" if model_name != "cnn" else f"dp_fedavg_cnn_{tag}"

    def write_artifact(partial: bool) -> None:
        """One write per completed arm: a truncated run still leaves evidence."""
        _write(name, {
            "artifact": name,
            "partial": partial,
            "benchmark": "dp_fedavg_mnist (BASELINE.json config #4): "
                         "privacy-utility curve",
            "dataset": train.name,
            "real_data": True,
            "data_note": "REAL sklearn digits (MNIST unfetchable here — see "
                         "runs/mnist_fetch_attempt_*.log)"
                         + ("; upsampled 8x8 -> 28x28 for the flagship CNN input"
                            if model_name == "cnn" else ""),
            "model": model_desc,
            "regime": {"num_clients": num_clients,
                       "participation_rate": participation,
                       "cohort_size": cohort,
                       "num_rounds": num_rounds, "eval_every": eval_every,
                       "clip_norm": clip,
                       "batch_size": training.batch_size,
                       "local_epochs": training.local_epochs,
                       "learning_rate": training.learning_rate},
            "mechanism": "central DP-FedAvg (McMahan et al. 2018): per-update clip "
                         "to C, uniform-weight mean over the sampled cohort, one "
                         "Gaussian draw sigma*C/K at the replicated aggregate; "
                         "client-subsampling amplification accounted at "
                         "q=participation_rate",
            "accounting": "RDPAccountant (exact sampled-Gaussian RDP, "
                          "Mironov-Talwar-Zhang 2019; integer orders); fixed-size "
                          "uniform cohort accounted as Poisson subsampling at "
                          "q=cohort/N — the standard approximation (McMahan et al. "
                          "2018), not a strict without-replacement upper bound; "
                          "sigma per arm from noise_multiplier_for_budget",
            "arms": arms,
            "summary": {k: v.get("final_test_accuracy") for k, v in arms.items()},
            "platform": str(jax.devices()[0].platform),
        })

    arms = {}
    control = _trajectory(make_coord(None))
    arms["no_dp"] = {
        "trajectory": control,
        "final_test_accuracy": final_acc_of(control),
    }
    print(f"control (no DP): final acc={final_acc_of(control)}", flush=True)
    write_artifact(partial=True)

    for budget_eps in (8.0, 4.0, 1.0):
        sigma = noise_multiplier_for_budget(
            budget_eps, budget_delta, sampling_rate=q, num_events=num_rounds,
        )
        privacy = PrivacyConfig(epsilon=budget_eps, delta=budget_delta,
                                max_gradient_norm=clip, noise_multiplier=sigma)
        coord = make_coord(PrivacyAwareAggregationConfig(privacy=privacy))
        traj = _trajectory(coord)
        spent = coord.privacy_spent
        final_acc = final_acc_of(traj)
        arms[f"eps={budget_eps:g}"] = {
            "noise_multiplier": round(sigma, 4),
            "epsilon_spent_total": round(spent.epsilon_spent, 4),
            "delta_spent_total": spent.delta_spent,
            "within_budget": bool(spent.epsilon_spent <= budget_eps),
            "final_test_accuracy": final_acc,
            "trajectory": traj,
        }
        print(f"eps={budget_eps:g}: sigma={sigma:.3f} final acc={final_acc} "
              f"(spent {spent.epsilon_spent:.3f})", flush=True)
        write_artifact(partial=True)

    write_artifact(partial=False)
    return 0


def run_fedprox(tag: str) -> int:
    import jax
    import numpy as np

    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig

    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    model = get_model("digits_mlp", hidden=96)
    # High-drift regime: severe skew (Dirichlet alpha=0.05 — most clients see 1-2
    # classes), 16 local epochs at lr=0.5, 30% participation.  This is where client
    # updates diverge and the proximal term earns its keep.
    regime = dict(alpha=0.05, local_epochs=16, learning_rate=0.5, clients=30,
                  participation=0.3, rounds=25, batch_size=16)
    arms = {}
    for mu in (0.0, 0.05, 0.2):
        per_seed = []
        for seed in (0, 1, 2):
            cd = federate(train, num_clients=regime["clients"], scheme="dirichlet",
                          batch_size=regime["batch_size"], seed=seed,
                          alpha=regime["alpha"])
            coord = Coordinator(
                model=model, train_data=cd,
                config=CoordinatorConfig(num_rounds=regime["rounds"], seed=seed,
                                         participation_rate=regime["participation"],
                                         base_dir="runs/fedprox_run", eval_every=1,
                                         save_metrics=False),
                training=TrainingConfig(batch_size=regime["batch_size"],
                                        local_epochs=regime["local_epochs"],
                                        learning_rate=regime["learning_rate"],
                                        prox_mu=mu),
                eval_data=pack_eval(test, batch_size=128),
            )
            accs = [r["test_accuracy"] for r in _trajectory(coord)
                    if "test_accuracy" in r]
            per_seed.append(accs)
            print(f"  mu={mu} seed={seed}: final={accs[-1]:.4f}", flush=True)
        arr = np.asarray(per_seed)
        arms[f"mu={mu}"] = {
            "per_seed_trajectories": arr.round(4).tolist(),
            "mean_trajectory": arr.mean(axis=0).round(4).tolist(),
            "final_accuracy_mean": round(float(arr[:, -1].mean()), 4),
            "last5_accuracy_mean": round(float(arr[:, -5:].mean()), 4),
        }
    fedavg = arms["mu=0.0"]["last5_accuracy_mean"]
    best_prox = max(v["last5_accuracy_mean"] for k, v in arms.items() if k != "mu=0.0")
    _write(f"noniid_fedprox_{tag}", {
        "artifact": f"noniid_fedprox_{tag}",
        "benchmark": "fedprox vs fedavg under Dirichlet non-IID "
                     "(BASELINE.json config #3 capability)",
        "dataset": "digits", "real_data": True, "model": "digits_mlp",
        "regime": regime, "seeds": [0, 1, 2],
        "arms": arms,
        "fedprox_beats_fedavg": bool(best_prox > fedavg),
        "summary": f"last-5-round mean accuracy: FedAvg {fedavg:.4f} vs best FedProx "
                   f"{best_prox:.4f} (3 seeds)",
        "platform": str(jax.devices()[0].platform),
    })
    print(f"FedAvg {fedavg:.4f} vs best FedProx {best_prox:.4f}")
    return 0


def run_scaffold(tag: str) -> int:
    """SCAFFOLD vs FedProx vs FedAvg in the ``fedprox`` mode's high-drift regime
    (Dirichlet alpha=0.05, 16 local epochs, 30% participation): partial
    participation is exactly where the stored controls earn their keep — each
    round's cohort is a biased sample, and the controls carry the absent clients'
    gradient directions into the round.

    Honest per-arm tuning: FedAvg/FedProx run at the regime's lr=0.5 (their tuned
    value from ``noniid_fedprox``); SCAFFOLD runs at lr=0.2, inside its stability
    bound (eta_l = O(1/K) — the one-round-stale correction amplifies at aggressive
    local lrs).  The lr=0.5 SCAFFOLD arm is RECORDED TOO, diverged: an evidence
    artifact should show the stability bound, not hide it."""
    import jax
    import numpy as np

    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig

    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    model = get_model("digits_mlp", hidden=96)
    regime = dict(alpha=0.05, local_epochs=16, clients=30,
                  participation=0.3, rounds=25, batch_size=16)
    arms = {}
    for arm_name, lr, arm_kw in (
        ("fedavg", 0.5, {}),
        ("fedprox_mu=0.2", 0.5, {"prox_mu": 0.2}),
        ("scaffold", 0.2, {"scaffold": True}),
        ("scaffold_lr=0.5_unstable", 0.5, {"scaffold": True}),
    ):
        per_seed = []
        for seed in (0, 1, 2):
            cd = federate(train, num_clients=regime["clients"], scheme="dirichlet",
                          batch_size=regime["batch_size"], seed=seed,
                          alpha=regime["alpha"])
            coord = Coordinator(
                model=model, train_data=cd,
                config=CoordinatorConfig(num_rounds=regime["rounds"], seed=seed,
                                         participation_rate=regime["participation"],
                                         base_dir="runs/scaffold_run", eval_every=1,
                                         save_metrics=False),
                training=TrainingConfig(batch_size=regime["batch_size"],
                                        local_epochs=regime["local_epochs"],
                                        learning_rate=lr,
                                        prox_mu=arm_kw.get("prox_mu", 0.0)),
                eval_data=pack_eval(test, batch_size=128),
                scaffold=arm_kw.get("scaffold", False),
            )
            accs = [r["test_accuracy"] for r in _trajectory(coord)
                    if "test_accuracy" in r]
            per_seed.append(accs)
            print(f"  {arm_name} seed={seed}: final={accs[-1]:.4f}", flush=True)
        arr = np.asarray(per_seed)
        arms[arm_name] = {
            "learning_rate": lr,
            "per_seed_trajectories": arr.round(4).tolist(),
            "mean_trajectory": arr.mean(axis=0).round(4).tolist(),
            "final_accuracy_mean": round(float(arr[:, -1].mean()), 4),
            "last5_accuracy_mean": round(float(arr[:, -5:].mean()), 4),
        }
    fedavg = arms["fedavg"]["last5_accuracy_mean"]
    scaffold = arms["scaffold"]["last5_accuracy_mean"]
    fedprox = arms["fedprox_mu=0.2"]["last5_accuracy_mean"]
    _write(f"scaffold_{tag}", {
        "artifact": f"scaffold_{tag}",
        "benchmark": "SCAFFOLD vs FedProx vs FedAvg under Dirichlet non-IID with "
                     "30% participation (Karimireddy et al. 2020)",
        "dataset": "digits", "real_data": True, "model": "digits_mlp",
        "regime": regime, "seeds": [0, 1, 2],
        "per_arm_lr_note": "FedAvg/FedProx at their tuned lr=0.5; SCAFFOLD at "
                           "lr=0.2 (inside its eta_l stability bound); the lr=0.5 "
                           "SCAFFOLD arm is recorded to SHOW the bound",
        "arms": arms,
        "scaffold_beats_fedavg": bool(scaffold > fedavg),
        "scaffold_beats_fedprox": bool(scaffold > fedprox),
        "summary": f"last-5-round mean accuracy: FedAvg {fedavg:.4f}, "
                   f"FedProx(mu=0.2) {fedprox:.4f}, SCAFFOLD {scaffold:.4f} (3 seeds)",
        "platform": str(jax.devices()[0].platform),
    })
    print(f"FedAvg {fedavg:.4f}, FedProx {fedprox:.4f}, SCAFFOLD {scaffold:.4f}")
    return 0


def run_labelskew(tag: str, num_rounds: int = 8) -> int:
    """BASELINE.json config #2 on REAL data (VERDICT r4 ask #9): 100 clients, 2-class
    label-skew shards, C=0.1 participation, the flagship CNN — on the real digits
    images upsampled to the CNN's 28x28 input.  Supersedes the r03 synthetic-data
    artifact (``real_data: false``); the cohort-gathering path makes the CNN config
    CPU-feasible (each round trains the 10-client cohort, not all 100)."""
    import jax

    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.data.datasets import resize_images
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig

    train = resize_images(load_digits_dataset("train"), 28, 28)
    test = resize_images(load_digits_dataset("test"), 28, 28)
    training = TrainingConfig(batch_size=8, local_epochs=2, learning_rate=0.1)
    coord = Coordinator(
        model=get_model("mnist_cnn"),
        train_data=federate(train, num_clients=100, scheme="label_skew",
                            shards_per_client=2, batch_size=training.batch_size,
                            seed=0),
        config=CoordinatorConfig(num_rounds=num_rounds, seed=0,
                                 participation_rate=0.1,
                                 base_dir="runs/labelskew_run", eval_every=1,
                                 save_metrics=False),
        training=training,
        eval_data=pack_eval(test, batch_size=256),
    )
    trajectory = _trajectory(coord)
    _write(f"labelskew_{tag}", {
        "artifact": f"labelskew_{tag}",
        "benchmark": "mnist_labelskew (BASELINE.json config #2)",
        "dataset": train.name,
        "real_data": True,
        "data_note": "REAL sklearn digits (1,797 handwritten-digit images) "
                     "upsampled 8x8 -> 28x28 for the flagship CNN input — MNIST "
                     "unfetchable here (runs/mnist_fetch_attempt_*.log); every "
                     "config-#2 mechanic is exact: 100 clients, 2-class label-skew "
                     f"shards, C=0.1 cohort sampling, mnist_cnn, {num_rounds} "
                     "rounds",
        "model": "mnist_cnn",
        "regime": {"num_clients": 100, "scheme": "label_skew",
                   "shards_per_client": 2, "participation_rate": 0.1,
                   "num_rounds": num_rounds,
                   "batch_size": training.batch_size,
                   "local_epochs": training.local_epochs,
                   "learning_rate": training.learning_rate},
        "final_test_accuracy": _final_accuracy(trajectory),
        "total_wall_clock_s": trajectory[-1]["elapsed_s"] if trajectory else None,
        "trajectory": trajectory,
        "platform": str(jax.devices()[0].platform),
        "supersedes": "labelskew_r03 (synthetic MNIST-shaped data, real_data: false)",
    })
    print(json.dumps(trajectory[-1]))
    return 0


def run_personalization(tag: str) -> int:
    """Personalized evaluation measured (Wang et al. 2019's fine-tune baseline —
    the reference has no personalization notion at all): train a global model
    federally under 2-class label skew, then compare the GLOBAL model's accuracy on
    each client's own held-out split against a few-epoch LOCAL fine-tune from the
    global initialization."""
    import jax
    import numpy as np

    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import (
        TrainingConfig,
        make_personalized_evaluator,
        split_client_data,
    )

    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    model = get_model("digits_mlp", hidden=96)
    num_clients, rounds = 20, 15
    cd = federate(train, num_clients=num_clients, scheme="label_skew",
                  batch_size=16, seed=0, shards_per_client=2)
    fit_cd, heldout_cd = split_client_data(cd, test_fraction=0.25, seed=0)

    # Federate on the TRAIN splits only — the held-out quarter is what makes the
    # personalized numbers honest.
    coord = Coordinator(
        model=model, train_data=fit_cd,
        config=CoordinatorConfig(num_rounds=rounds, seed=0,
                                 base_dir="runs/personalization_run",
                                 save_metrics=False),
        training=TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.5),
        eval_data=pack_eval(test, batch_size=128),
    )
    coord.run()
    iid_acc = coord.evaluate()["accuracy"]

    evaluate = make_personalized_evaluator(
        model.apply,
        TrainingConfig(batch_size=16, local_epochs=3, learning_rate=0.1),
    )
    out = evaluate(coord.params, fit_cd, heldout_cd, jax.random.key(7))
    g = float(out["global_accuracy"])
    p = float(out["personal_accuracy"])
    _write(f"personalization_{tag}", {
        "artifact": f"personalization_{tag}",
        "benchmark": "global vs fine-tuned-per-client accuracy on each client's "
                     "own held-out split (FedAvg-then-fine-tune baseline)",
        "dataset": "digits", "real_data": True, "model": "digits_mlp(96)",
        "regime": {"num_clients": num_clients, "scheme": "label_skew",
                   "shards_per_client": 2, "federated_rounds": rounds,
                   "finetune": {"local_epochs": 3, "learning_rate": 0.1},
                   "heldout_fraction": 0.25},
        "global_model_iid_test_accuracy": round(iid_acc, 4),
        "global_accuracy_on_own_heldout": round(g, 4),
        "personalized_accuracy_on_own_heldout": round(p, 4),
        "personalization_gain": round(p - g, 4),
        "per_client_global": np.asarray(
            out["global_accuracy_per_client"]).round(4).tolist(),
        "per_client_personal": np.asarray(
            out["personal_accuracy_per_client"]).round(4).tolist(),
        "summary": f"on own held-out data: global {g:.4f} -> personalized {p:.4f} "
                   f"(gain {p - g:+.4f}); global model's IID test accuracy "
                   f"{iid_acc:.4f}",
        "platform": str(jax.devices()[0].platform),
    })
    print(f"global {g:.4f} -> personalized {p:.4f}")
    return 0


def run_asyncfed(tag: str) -> int:
    """FedBuff vs the synchronous barrier, measured where async matters: a
    federation with one hardware-slow straggler.  Both arms consume roughly the
    same number of CLIENT updates; the sync arm must wait for the straggler every
    round, the async arm aggregates whenever K fresh-or-stale updates arrive.
    Reported: wall-clock, model versions produced, final held-out accuracy."""
    import asyncio
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.communication import (
        HTTPClient,
        HTTPServer,
        NetworkCoordinator,
        NetworkRoundConfig,
    )
    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.trainer.local import make_evaluator, make_local_fit

    model = get_model("digits_mlp", hidden=32)
    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    num_clients = 6
    cd = federate(train, num_clients=num_clients, scheme="iid", batch_size=16, seed=0)
    # JITTED, warmed local fit: on this 1-core host every client's compute
    # SERIALIZES on the event loop, which a real federation never does (clients own
    # their devices) — and the eager per-op dispatch path costs ~1 s where the
    # compiled program costs ~2 ms.  Keeping the fit negligible makes the measured
    # wall time reflect the COORDINATION structure — the straggler's delay and who
    # waits for it — which is the thing this benchmark isolates.
    fit = jax.jit(make_local_fit(
        model.apply, TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.3)
    ))
    _warm = fit(model.init(jax.random.key(0)),
                jax.tree.map(lambda a: jnp.asarray(a[0]), cd), jax.random.key(0))
    jax.block_until_ready(_warm.params)
    evaluator = make_evaluator(model.apply, batch_size=128)
    eval_data = jax.tree.map(jnp.asarray, pack_eval(test, batch_size=128))
    init = model.init(jax.random.key(0))
    straggler_delay = 0.5  # the slow client's per-update wall cost (device speed)
    fast_delay = 0.05  # everyone else's

    def make_client(port, cid, idx, delay):
        async def client():
            data = jax.tree.map(lambda a: jnp.asarray(a[idx]), cd)
            async with HTTPClient(f"http://127.0.0.1:{port}", cid,
                                  timeout_s=120) as c:
                last_round = -1
                while True:
                    fetched, rnd, active = await c.fetch_global_model(like=init)
                    if not active:
                        return
                    if rnd == last_round:
                        # Sync arm: the round hasn't advanced — wait rather than
                        # re-submit into a closed round.  (Async publishes a new
                        # version after every aggregation, so this rarely binds.)
                        await asyncio.sleep(0.01)
                        continue
                    last_round = rnd
                    result = fit(jax.tree.map(jnp.asarray, fetched), data,
                                 jax.random.key(idx * 1000 + rnd))
                    await asyncio.sleep(delay)
                    await c.submit_update(
                        result.params,
                        {"loss": float(result.metrics.loss),
                         "num_samples": float(result.metrics.samples)},
                    )

        return client

    def run_arm(port, cfg) -> dict:
        async def main():
            server = HTTPServer(port=port)
            coord = NetworkCoordinator(server, init, cfg)
            await server.start()
            t0 = _time.perf_counter()
            try:
                tasks = [
                    asyncio.create_task(
                        make_client(port, f"c{i}", i,
                                    straggler_delay if i == 0 else fast_delay)()
                    )
                    for i in range(num_clients)
                ]
                history = await coord.run()
                await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
            finally:
                await server.stop()
            wall = _time.perf_counter() - t0
            acc = float(evaluator(jax.tree.map(jnp.asarray, coord.params),
                                  eval_data)["accuracy"])
            completed = [h for h in history if h["status"] == "COMPLETED"]
            stale = [s for h in completed for s in h.get("staleness", [])]
            return {
                "wall_s": round(wall, 2),
                "versions": len(completed),
                "updates_consumed": int(sum(h["num_clients"] for h in completed)),
                "final_test_accuracy": round(acc, 4),
                **({"stale_update_fraction":
                    round(float(np.mean([s > 0 for s in stale])), 3)}
                   if stale else {}),
            }

        return asyncio.run(main())

    # Three arms.  Sync: 12 all-client barrier rounds = 72 updates, every round
    # gated on the straggler.  Async same-UPDATES: K=3 x 24 aggregations = the same
    # 72-update budget with no barrier — this shows the wall win AND the per-update
    # staleness cost honestly.  Async same-WALL: as many aggregations as fit the
    # sync arm's wall clock — the FedBuff claim is TIME-to-accuracy, and this is
    # the apples-to-apples version of it.
    sync = run_arm(18910, NetworkRoundConfig(
        num_rounds=12, min_clients=num_clients, min_completion_rate=1.0,
        round_timeout_s=60.0, poll_interval_s=0.01))
    async_same_updates = run_arm(18911, NetworkRoundConfig(
        num_rounds=24, async_buffer_k=3, staleness_window=8,
        round_timeout_s=60.0, poll_interval_s=0.01))
    per_agg = async_same_updates["wall_s"] / max(async_same_updates["versions"], 1)
    samewall_aggs = max(int(sync["wall_s"] / per_agg), 1)
    async_same_wall = run_arm(18912, NetworkRoundConfig(
        num_rounds=samewall_aggs, async_buffer_k=3, staleness_window=8,
        round_timeout_s=60.0, poll_interval_s=0.01))
    if async_same_wall["wall_s"] < 0.9 * sync["wall_s"]:
        # The first arm's per-aggregation estimate includes its warmup; recalibrate
        # once from the measured steady rate so the arm actually spends the budget.
        rate = async_same_wall["wall_s"] / max(async_same_wall["versions"], 1)
        samewall_aggs = max(int(sync["wall_s"] / rate), samewall_aggs + 1)
        async_same_wall = run_arm(18913, NetworkRoundConfig(
            num_rounds=samewall_aggs, async_buffer_k=3, staleness_window=8,
            round_timeout_s=60.0, poll_interval_s=0.01))

    _write(f"asyncfed_{tag}", {
        "artifact": f"asyncfed_{tag}",
        "benchmark": "FedBuff async buffered aggregation vs the synchronous "
                     "barrier with one slow straggler (Nguyen et al. 2022)",
        "dataset": "digits", "real_data": True, "model": "digits_mlp(32)",
        "regime": {"num_clients": num_clients, "straggler_delay_s": straggler_delay,
                   "fast_delay_s": fast_delay,
                   "sync": "12 rounds x 6-client barrier",
                   "async": "K=3 buffer, staleness_window=8, alpha=0.5",
                   "note": "jitted negligible local fit by design: on a 1-core "
                           "host client compute serializes (real clients own "
                           "their devices), so wall time must isolate the "
                           "coordination structure"},
        "sync": sync,
        "async_same_update_budget": async_same_updates,
        "async_same_wall_budget": async_same_wall,
        "speedup_wall_same_updates": round(
            sync["wall_s"] / async_same_updates["wall_s"], 2),
        "staleness_cost_note": (
            "at the same 72-update budget async finishes "
            f"{round(sync['wall_s'] / async_same_updates['wall_s'], 1)}x faster "
            "but stale deltas make less per-update progress — the honest FedBuff "
            "comparison is TIME-to-accuracy (same-wall arm)"),
        "summary": (
            f"sync: {sync['wall_s']}s -> {sync['final_test_accuracy']}; "
            f"async at the same wall budget: {async_same_wall['wall_s']}s -> "
            f"{async_same_wall['final_test_accuracy']} "
            f"({async_same_wall['versions']} versions, "
            f"{async_same_wall['updates_consumed']} updates the barrier would "
            "have blocked)"),
        "platform": str(jax.devices()[0].platform),
    })
    print(f"sync {sync['wall_s']}s acc {sync['final_test_accuracy']} | "
          f"async same-wall {async_same_wall['wall_s']}s acc "
          f"{async_same_wall['final_test_accuracy']}")
    return 0


def run_byzantine(tag: str) -> int:
    """Measure the Byzantine-robust trimmed mean doing its job (new capability —
    the reference has no robust aggregation at all): 16 clients on real digits,
    2 of them poisoned (inputs scaled x50, labels shifted +1 mod 10 — their local
    SGD produces large, systematically wrong updates), 3 arms:

      clean_fedavg     no attackers (the ceiling)
      attacked_fedavg  2 attackers, plain weighted FedAvg
      attacked_robust  2 attackers, trimmed mean with trim_k=2
      attacked_median  2 attackers, knob-free coordinate-wise median
      attacked_krum    2 attackers, Multi-Krum whole-update selection (f=2)
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.aggregation import RobustAggregationConfig
    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig

    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    model = get_model("digits_mlp", hidden=96)
    training = TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.5)
    num_clients, n_attackers, rounds = 16, 2, 20

    def make_data(poison: bool):
        cd = federate(train, num_clients=num_clients, scheme="iid",
                      batch_size=training.batch_size, seed=0)
        if not poison:
            return cd
        x = np.array(cd.x)
        y = np.array(cd.y)
        x[:n_attackers] *= 50.0          # huge gradients
        y[:n_attackers] = (y[:n_attackers] + 1) % 10  # systematically wrong
        return cd._replace(x=jnp.asarray(x), y=jnp.asarray(y))

    arms = {}
    for name, poison, robust in (
        ("clean_fedavg", False, None),
        ("attacked_fedavg", True, None),
        ("attacked_robust", True, RobustAggregationConfig(trim_k=n_attackers)),
        ("attacked_median", True, RobustAggregationConfig(method="median")),
        ("attacked_krum", True,
         RobustAggregationConfig(method="multi_krum", trim_k=n_attackers)),
    ):
        coord = Coordinator(
            model=model, train_data=make_data(poison),
            config=CoordinatorConfig(num_rounds=rounds, seed=0,
                                     base_dir="runs/byzantine_run", eval_every=2,
                                     save_metrics=False),
            training=training,
            eval_data=pack_eval(test, batch_size=128),
            robust=robust,
        )
        traj = _trajectory(coord)
        final = _final_accuracy(traj)
        arms[name] = {"final_test_accuracy": final, "trajectory": traj}
        print(f"  {name}: final {final}", flush=True)

    clean = arms["clean_fedavg"]["final_test_accuracy"]
    attacked = arms["attacked_fedavg"]["final_test_accuracy"]
    robustf = arms["attacked_robust"]["final_test_accuracy"]
    medianf = arms["attacked_median"]["final_test_accuracy"]
    krumf = arms["attacked_krum"]["final_test_accuracy"]
    _write(f"byzantine_{tag}", {
        "artifact": f"byzantine_{tag}",
        "claim": "coordinate-wise trimmed mean (aggregation.robust, Yin et al. "
                 "2018) bounds Byzantine clients the plain weighted mean cannot",
        "dataset": "digits", "real_data": True, "model": "digits_mlp(96)",
        "regime": {"num_clients": num_clients, "attackers": n_attackers,
                   "attack": "inputs x50 + labels shifted +1 mod 10",
                   "trim_k": n_attackers, "num_rounds": rounds,
                   "batch_size": training.batch_size,
                   "local_epochs": training.local_epochs,
                   "learning_rate": training.learning_rate},
        "arms": arms,
        "summary": (f"final held-out accuracy: clean FedAvg {clean}; under attack "
                    f"FedAvg {attacked} vs trimmed mean {robustf} vs median "
                    f"{medianf} vs multi-krum {krumf}"),
        # "Holds" means the defense PRESERVES clean accuracy (within 2 points),
        # not merely that it beats the collapsed arm — a regressed estimator landing
        # at 15% would beat 7.8% yet be a broken defense.  Every defense arm is
        # gated; the aggregate flag is their conjunction.
        "defense_holds_per_arm": {
            name: bool(acc is not None and clean is not None
                       and acc >= clean - 0.02)
            for name, acc in (("attacked_robust", robustf),
                              ("attacked_median", medianf),
                              ("attacked_krum", krumf))
        },
        "defense_holds": bool(
            clean is not None
            and all(acc is not None and acc >= clean - 0.02
                    for acc in (robustf, medianf, krumf))
        ),
        "platform": str(jax.devices()[0].platform),
    })
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mode",
                    choices=["dp", "fedprox", "labelskew", "byzantine", "scaffold",
                             "personalization", "asyncfed"])
    ap.add_argument("--round-tag", default="r03")
    ap.add_argument(
        "--platform", choices=["auto", "cpu"], default="auto",
        help="cpu forces the virtual 8-device CPU mesh (for wedged/absent accelerators; "
        "the artifact records the platform either way)",
    )
    ap.add_argument("--n-devices", type=int, default=8)
    ap.add_argument(
        "--model", choices=["linear", "cnn"], default="linear",
        help="dp mode only: 'cnn' runs the arms with the flagship MNIST CNN on "
        "digits@28x28 (VERDICT r3 item 7)",
    )
    ap.add_argument("--rounds", type=int, default=40,
                    help="dp mode only: rounds per arm (sigma is calibrated for "
                    "exactly this count, so it stays a valid budget experiment)")
    ap.add_argument("--eval-every", type=int, default=1,
                    help="dp mode only: eval cadence (sparser = cheaper on CPU)")
    args = ap.parse_args()
    if args.platform == "cpu":
        from nanofed_tpu.utils.platform import force_cpu_mesh

        force_cpu_mesh(args.n_devices)
    if args.mode == "dp":
        return run_dp(args.round_tag, model_name=args.model,
                      num_rounds=args.rounds, eval_every=args.eval_every)
    # labelskew stays at config #2's 8 rounds (the num_rounds parameter exists for
    # programmatic callers; --rounds is dp-mode-only and defaults to 40, which
    # would silently quintuple the labelskew budget if wired through).
    return {"fedprox": run_fedprox, "labelskew": run_labelskew,
            "byzantine": run_byzantine, "scaffold": run_scaffold,
            "personalization": run_personalization,
            "asyncfed": run_asyncfed}[args.mode](args.round_tag)


if __name__ == "__main__":
    sys.exit(main())
