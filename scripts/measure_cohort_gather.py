#!/usr/bin/env python
"""Measure the cohort-gathering optimization's claimed win (VERDICT r4 ask #7).

``orchestration/coordinator.py`` claims gathering the sampled cohort (K_pad rows)
instead of zero-weighting all N clients avoids burning (1-q) of every round's FLOPs —
"at the DP benchmark's q=0.1 that is a 10x waste".  Bit-exactness is pinned by
``tests/integration/test_end_to_end.py::test_cohort_gather_equals_full_mask_round``;
this script pins the TIMING: the same coordinator config run both ways (the test
suite's own forcing mechanism flips the second one onto the legacy full-N path),
median of ``--reps`` steady-state rounds each, written to
``runs/cohort_gather_<tag>.json`` with both times and the ratio.

Usage:
    python scripts/measure_cohort_gather.py [--round-tag r05] [--clients 240]
        [--participation 0.1] [--reps 5] [--platform cpu|accel]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _time_rounds(coord, reps: int) -> list[float]:
    """Advance ``reps`` steady-state rounds (round 0 = compile+warm-up, excluded),
    returning per-round wall-clock seconds."""
    import jax

    gen = coord.start_training()
    next(gen)  # warm-up round: XLA compile lands here
    times = []
    for _ in range(reps):
        t = time.perf_counter()
        next(gen)
        jax.block_until_ready(coord.params)
        times.append(time.perf_counter() - t)
    gen.close()
    return times


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--round-tag", default="r05")
    ap.add_argument("--clients", type=int, default=240)
    ap.add_argument("--participation", type=float, default=0.1)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--platform", default="cpu", choices=["cpu", "accel"])
    ap.add_argument("--n-devices", type=int, default=8)
    ap.add_argument("--samples-per-client", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=512,
                    help="MLP width — sized so rounds are compute-bound (at ~45 ms "
                    "rounds, fixed per-round overhead dilutes the ratio and the "
                    "measurement answers the wrong question)")
    args = ap.parse_args()

    if args.platform == "cpu":
        from nanofed_tpu.utils.platform import force_cpu_mesh

        force_cpu_mesh(args.n_devices)

    import jax
    import numpy as np

    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig

    model = get_model("mlp", in_features=64, hidden=args.hidden, num_classes=10)
    data = federate(
        synthetic_classification(args.clients * args.samples_per_client, 10, (64,),
                                 seed=0),
        num_clients=args.clients, scheme="iid", batch_size=16, seed=0,
    )

    def make():
        return Coordinator(
            model=model,
            train_data=data,
            config=CoordinatorConfig(
                num_rounds=args.reps + 1, participation_rate=args.participation,
                seed=7, base_dir="/tmp/cohort_gather_bench", save_metrics=False,
            ),
            training=TrainingConfig(batch_size=16, local_epochs=2),
        )

    results = {}
    for name in ("gathered", "full"):
        coord = make()
        if name == "full":
            # The test suite's forcing mechanism (test_end_to_end.py:226-227):
            # legacy path = round step over all N padded, non-cohort rows weight 0.
            coord._cohort_mode = False
            coord._step_clients = coord._padded_clients
        else:
            assert coord._cohort_mode, (
                "config unexpectedly fell back to the full-N path; the comparison "
                "would be vacuous"
            )
        print(f"[{name}] step_clients={coord._step_clients} "
              f"(padded N={coord._padded_clients})", flush=True)
        times = _time_rounds(coord, args.reps)
        results[name] = {
            "step_clients": int(coord._step_clients),
            "round_times_s": [round(t, 4) for t in times],
            "median_s": round(float(np.median(times)), 4),
        }
        print(f"[{name}] median {results[name]['median_s']}s over {args.reps} "
              f"steady-state rounds", flush=True)

    ratio = results["full"]["median_s"] / results["gathered"]["median_s"]
    artifact = {
        "artifact": f"cohort_gather_{args.round_tag}",
        "claim": (
            "orchestration/coordinator.py cohort gathering: partial-participation "
            "rounds run over the gathered K_pad cohort instead of all N "
            "zero-weighted clients"
        ),
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "config": {
            "clients": args.clients,
            "participation": args.participation,
            "cohort_step_clients": results["gathered"]["step_clients"],
            "model": f"mlp(64->{args.hidden}->10)",
            "samples_per_client": args.samples_per_client,
            "batch_size": 16,
            "local_epochs": 2,
            "reps": args.reps,
            "aggregation": "median of steady-state rounds (warm-up excluded)",
        },
        "gathered": results["gathered"],
        "full_n_forced": results["full"],
        "speedup": round(ratio, 2),
        "note": (
            "bit-exactness of the two paths is pinned separately by "
            "tests/integration/test_end_to_end.py::"
            "test_cohort_gather_equals_full_mask_round; the FLOP ratio at "
            f"q={args.participation} is ~{1 / args.participation:.1f}x — fixed "
            "per-round overhead dilutes the measured speedup below it on small "
            "workloads, while working-set effects can push it above (the full-N "
            "arm streams 10x the client rows through the cache hierarchy)"
        ),
    }
    out = REPO / "runs" / f"cohort_gather_{args.round_tag}.json"
    out.write_text(json.dumps(artifact, indent=2))
    print(f"\nspeedup {ratio:.2f}x; artifact written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
