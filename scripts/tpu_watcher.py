#!/usr/bin/env python
"""Session-long accelerator-tunnel watcher (VERDICT r4 ask #1).

Round 4 built the on-chip evidence campaign (``scripts/tpu_campaign.py``) but probed
the tunnel exactly once, hours before the session ended — a chip that recovered
mid-session would have gone unnoticed. This watcher closes that gap: it re-probes the
backend every ``--interval`` seconds for the whole session, appends every attempt to
``runs/tpu_campaign_<tag>.log`` (so the round leaves a record even if the tunnel never
answers), and on the FIRST successful probe fires the full campaign, then exits.

Usage:
    python scripts/tpu_watcher.py --tag r05 [--interval 600] [--max-hours 12]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PY = sys.executable

MEASUREMENT_SCRIPTS = (
    "bench.py", "record_evidence.py", "record_accuracy.py",
    "measure_cohort_gather.py", "measure_pallas.py", "profile_flagship.py",
)


def measurement_running() -> bool:
    """True when a benchmark/evidence measurement owns the (single) core — a 150 s
    backend-init probe mid-measurement distorts its round times by up to ~2x
    (observed: 67 s vs 97 s for identical rounds), exactly the noise that fails
    the linearity audit.

    Parses /proc argv properly instead of pgrep -f substring matching: the session
    harness's own wrapper process carries the literal text "bench.py" inside a huge
    prompt argument and LIVES ALL SESSION — a substring guard deferred every probe
    forever (observed r05).  A measurement is a python process whose argv contains
    a TOKEN that is one of the known script paths."""
    me = os.getpid()
    for pid_dir in Path("/proc").iterdir():
        if not pid_dir.name.isdigit() or int(pid_dir.name) == me:
            continue
        try:
            argv = (pid_dir / "cmdline").read_bytes().split(b"\0")
        except OSError:
            continue
        if not argv or b"python" not in argv[0]:
            continue
        for tok in argv[1:]:
            name = tok.decode(errors="replace").rsplit("/", 1)[-1]
            if name in MEASUREMENT_SCRIPTS:
                return True
    return False


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tag", default="r05")
    ap.add_argument("--interval", type=float, default=600.0,
                    help="seconds between probes (default 600 = 10 min)")
    ap.add_argument("--max-hours", type=float, default=12.0,
                    help="give up after this many hours of failed probes")
    ap.add_argument("--stages", default=None,
                    help="comma list forwarded to tpu_campaign.py --stages — re-arm "
                    "the watcher for just the stages a flaky tunnel killed, without "
                    "re-burning budget on artifacts already captured")
    args = ap.parse_args()

    log_path = REPO / "runs" / f"tpu_campaign_{args.tag}.log"
    log_path.parent.mkdir(exist_ok=True)

    def log(msg: str) -> None:
        line = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] watcher: {msg}"
        print(line, flush=True)
        with open(log_path, "a") as f:
            f.write(line + "\n")

    deadline = time.time() + args.max_hours * 3600.0
    attempt = 0
    deferred = 0
    log(f"armed — probing every {args.interval:.0f}s for up to "
        f"{args.max_hours:.1f}h; on first success: tpu_campaign.py --tag {args.tag}")
    # At least one CYCLE always runs, however small the window (the arming log line
    # above can outlast a sub-second window on a loaded core, which made zero-cycle
    # exits a real flake).  A cycle that finds a measurement on the core still
    # defers — probing mid-measurement is the greater evil — and the zero-probe
    # exit path below says so honestly.
    first_cycle = True
    while first_cycle or time.time() < deadline:
        first_cycle = False
        if measurement_running():
            deferred += 1
            log("measurement in progress on this core — deferring the probe")
            time.sleep(args.interval)
            continue
        attempt += 1
        t0 = time.time()
        try:
            proc = subprocess.run(
                [PY, str(REPO / "bench.py"), "--probe", "accel", "probe"],
                capture_output=True, text=True, timeout=240,
            )
            ok = any('"probe": "ok"' in line for line in proc.stdout.splitlines())
            tail = (proc.stdout.strip().splitlines() or ["<no stdout>"])[-1]
        except subprocess.TimeoutExpired:
            ok, tail = False, "probe subprocess timed out after 240s (hard-wedged)"
        log(f"probe #{attempt}: {'OK' if ok else 'failed'} in "
            f"{time.time() - t0:.0f}s — {tail[:200]}")
        if ok:
            log("chip answered — firing the campaign (probe already passed, skipping "
                "its probe stage)")
            argv = [PY, str(REPO / "scripts" / "tpu_campaign.py"),
                    "--tag", args.tag, "--skip-probe"]
            if args.stages:
                argv += ["--stages", args.stages]
            rc = subprocess.call(argv)
            log(f"campaign finished rc={rc}")
            return rc
        time.sleep(max(0.0, args.interval - (time.time() - t0)))
    if attempt == 0:
        # Every cycle found a measurement on the core — the tunnel was never even
        # TESTED; don't let the exit line misattribute that to the chip.
        log(f"window closed after {deferred} deferred cycle(s) and ZERO probes — "
            "the core was busy with measurements all session; tunnel state unknown")
    else:
        log(f"gave up after {attempt} failed probes ({deferred} deferred cycle(s)) "
            f"over {args.max_hours:.1f}h — tunnel never answered this session")
    return 2


if __name__ == "__main__":
    sys.exit(main())
