#!/usr/bin/env python
"""Profile the 1000-client flagship round on the real chip and attack the MFU
(VERDICT r3 item 2: the round beats the target 17x yet leaves ~94% of the chip idle —
5.84% MFU at client_chunk=125, batch 64, bf16).

Three instruments, one artifact (``runs/profile_flagship_<tag>.json``):

1. **Config sweep** — the knobs round time actually depends on:
   ``client_chunk`` x {125, 250, 500, 1000} (scan trip count vs per-chunk width: fewer,
   wider chunks amortize scan overhead and feed the MXU bigger batched convs, at the
   cost of activation memory) crossed with per-client ``batch_size`` {60, 64} (each
   client holds exactly 60 samples, so batch 64 pads every client's single batch with
   4 dead rows — ~6.7% wasted compute — while batch 60 fits exactly).
2. **Fixed-vs-compute decomposition** — rounds at local_epochs {2, 4} for the best
   config: t(E) = fixed + E*per_epoch separates the per-epoch training compute from
   per-round overhead (weight broadcast/donation, the psum-mean reduce, server-optax
   step, metrics transfers).
3. **Static MXU shape analysis** — the ceiling the model's own shapes impose: per-layer
   FLOP shares x systolic-array utilization bounds from contraction/output-channel
   padding to the 128-lane MXU (conv1 contracts 3x3x1=9 of 128 lanes; conv2 288/384
   with 64/128 output channels; fc1 is near-ideal).  The measured MFU is judged
   against THIS ceiling, not against 100%.

Optionally captures a ``jax.profiler`` trace of one steady-state round of the best
config (``--trace``; the trace dir is large and stays untracked — the JSON artifact
records its path and the top-level timing split).

Run on the real chip (default env).  CPU runs are refused unless ``--allow-cpu``
(plumbing checks only — CPU timings say nothing about MXU behavior).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Analytic per-sample training FLOPs (fwd 2*MACs, bwd ~2x fwd => 3x fwd), batch-60
# basis; the padded batch-64 configs do 64/60 of this per sample-slot.
_LAYERS = [
    # (name, fwd MACs/sample, contraction K, output channels N)
    ("conv1 3x3x1->32", 26 * 26 * 32 * 9 * 1, 9, 32),
    ("conv2 3x3x32->64", 24 * 24 * 64 * 9 * 32, 9 * 32, 64),
    ("fc1 9216->128", 9216 * 128, 9216, 128),
    ("fc2 128->10", 128 * 10, 128, 10),
]
CNN_FWD_FLOPS = 2 * sum(m for _, m, _, _ in _LAYERS)
CNN_TRAIN_FLOPS = 3 * CNN_FWD_FLOPS
V5E_BF16_PEAK = 197e12
MXU_LANES = 128


def mxu_shape_analysis() -> dict:
    """Static per-layer MXU utilization bound from shape padding (both matmul
    operand dims pad to 128 lanes on the systolic array)."""
    import math

    total = sum(m for _, m, _, _ in _LAYERS)
    layers, weighted = [], 0.0
    for name, macs, k, n in _LAYERS:
        util_k = k / (MXU_LANES * math.ceil(k / MXU_LANES))
        util_n = n / (MXU_LANES * math.ceil(n / MXU_LANES))
        util = util_k * util_n
        share = macs / total
        weighted += share * util
        layers.append({
            "layer": name, "flop_share": round(share, 4),
            "contraction": k, "out_channels": n,
            "mxu_utilization_bound": round(util, 4),
        })
    return {
        "per_layer": layers,
        "flop_weighted_mxu_ceiling": round(weighted, 4),
        "note": (
            "upper bound on achievable MFU from the model's own shapes: the MXU "
            "contracts 128 lanes x 128 lanes, so a conv with 1 input channel "
            "(contraction 9) can never use more than 9/128 of the array regardless "
            "of scheduling; measured MFU should be read against this ceiling"
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--round-tag", default="r04")
    ap.add_argument("--chunks", default="125,250,500,1000")
    ap.add_argument("--batches", default="60,64")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--trace", action="store_true",
                    help="capture a jax.profiler trace of the best config")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="plumbing check only — CPU timings are meaningless here")
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--samples", type=int, default=60,
                    help="samples per client (reduce for CPU plumbing checks)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.data import pack_clients, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        build_round_step,
        init_server_state,
        make_mesh,
        pad_client_count,
        pad_clients,
        replicated_sharding,
        shard_client_data,
    )
    from nanofed_tpu.trainer import TrainingConfig, stack_rngs
    from nanofed_tpu.utils.platform import enable_compilation_cache, log_stage

    if jax.default_backend() != "tpu" and not args.allow_cpu:
        print("refusing: not a TPU backend (pass --allow-cpu for a plumbing check)")
        return 2
    enable_compilation_cache()

    n_clients, n_samples = args.clients, args.samples
    mesh = make_mesh()
    n_dev = len(mesh.devices.flat)
    repl = replicated_sharding(mesh)
    model = get_model("mnist_cnn")
    strategy = fedavg_strategy()
    t_start = time.time()

    def run_config(chunk: int, batch: int, epochs: int, reps: int):
        """Build + warm + time one (client_chunk, batch_size, local_epochs) config;
        returns per-round times and the compile wall-clock."""
        ds = synthetic_classification(n_samples * n_clients, 10, (28, 28, 1), seed=0)
        data = pack_clients(
            ds, [np.arange(i * n_samples, (i + 1) * n_samples) for i in range(n_clients)],
            batch_size=batch,
        )
        padded = pad_client_count(n_clients, n_dev)
        data = shard_client_data(pad_clients(data, padded), mesh)
        num_samples = jnp.asarray(np.asarray(data.mask).sum(axis=1))
        weights = compute_weights(num_samples) * (num_samples > 0)
        training = TrainingConfig(batch_size=batch, local_epochs=epochs,
                                  learning_rate=0.1, compute_dtype="bfloat16")
        step = build_round_step(model.apply, training, mesh, strategy,
                                client_chunk=chunk, donate=True)
        params = jax.device_put(model.init(jax.random.key(0)), repl)
        sos = jax.device_put(init_server_state(strategy, params), repl)
        tc = time.perf_counter()
        res = step(params, sos, data, weights, stack_rngs(jax.random.key(0), padded))
        params, sos = res.params, res.server_opt_state
        jax.block_until_ready(params)
        compile_s = time.perf_counter() - tc
        times = []
        for r in range(1, reps + 1):
            t = time.perf_counter()
            res = step(params, sos, data, weights,
                       stack_rngs(jax.random.key(r), padded))
            params, sos = res.params, res.server_opt_state
            jax.block_until_ready(params)
            times.append(time.perf_counter() - t)
        return times, compile_s, (step, params, sos, data, weights, padded)

    def mfu(value_s: float, epochs: int, batch: int) -> float:
        # Useful FLOPs (60 real samples/client); padded batch rows burn extra chip
        # time but do no useful work, so they lower MFU rather than inflating FLOPs.
        flops = CNN_TRAIN_FLOPS * epochs * n_samples * n_clients
        return flops / value_s / (V5E_BF16_PEAK * n_dev)

    sweep = []
    best = None
    for chunk in (int(c) for c in args.chunks.split(",")):
        if n_clients % chunk and chunk < n_clients:
            continue
        for batch in (int(b) for b in args.batches.split(",")):
            label = f"chunk={chunk} batch={batch}"
            log_stage(f"sweep {label}: compiling + timing {args.reps} rounds",
                      t0=t_start)
            try:
                times, compile_s, handles = run_config(chunk, batch, 2, args.reps)
            except Exception as e:  # OOM at wide chunks is a finding, not a crash
                log_stage(f"sweep {label}: FAILED ({type(e).__name__}: {e})",
                          t0=t_start)
                sweep.append({"client_chunk": chunk, "batch_size": batch,
                              "error": f"{type(e).__name__}: {e}"})
                continue
            value = float(np.median(times))
            row = {
                "client_chunk": chunk, "batch_size": batch,
                "round_s": round(value, 4),
                "round_times_s": [round(t, 4) for t in times],
                "compile_s": round(compile_s, 1),
                "est_mfu_pct": round(100 * mfu(value, 2, batch), 2),
            }
            sweep.append(row)
            log_stage(f"sweep {label}: {value:.4f}s/round "
                      f"(MFU {row['est_mfu_pct']}%)", t0=t_start)
            if best is None or value < best[0]:
                best = (value, chunk, batch, handles)

    if best is None:
        print("no config completed")
        return 1
    best_value, best_chunk, best_batch, handles = best

    # Fixed-vs-compute decomposition at the best config: t(E) = fixed + E*per_epoch.
    log_stage(f"decomposition: best config chunk={best_chunk} batch={best_batch}; "
              "timing local_epochs=4", t0=t_start)
    times4, _, _ = run_config(best_chunk, best_batch, 4, args.reps)
    t4 = float(np.median(times4))
    per_epoch = max((t4 - best_value) / 2.0, 0.0)
    fixed = max(best_value - 2 * per_epoch, 0.0)
    decomposition = {
        "round_s_at_2_epochs": round(best_value, 4),
        "round_s_at_4_epochs": round(t4, 4),
        "per_epoch_compute_s": round(per_epoch, 4),
        "fixed_overhead_s": round(fixed, 4),
        "fixed_share_pct": round(100 * fixed / best_value, 1),
        "note": (
            "fixed = per-round cost independent of training epochs (broadcast, "
            "reduce+psum, server step, metric transfers, scan setup); per_epoch = "
            "the MXU-bound local-SGD compute"
        ),
    }

    trace_dir = None
    if args.trace:
        step, params, sos, data, weights, padded = handles
        trace_dir = str(REPO / "runs" / f"profile_trace_{args.round_tag}")
        log_stage(f"capturing jax.profiler trace to {trace_dir}", t0=t_start)
        with jax.profiler.trace(trace_dir):
            res = step(params, sos, data, weights,
                       stack_rngs(jax.random.key(99), padded))
            jax.block_until_ready(res.params)

    ok = [r for r in sweep if "round_s" in r]
    baseline = next((r for r in ok
                     if r["client_chunk"] == 125 and r["batch_size"] == 64), None)
    shape = mxu_shape_analysis()
    artifact = {
        "artifact": f"profile_flagship_{args.round_tag}",
        "purpose": "VERDICT r3 item 2: where does the flagship round's time go, and "
                   "how far is the measured MFU from the shape-imposed ceiling",
        "workload": {"num_clients": n_clients, "samples_per_client": n_samples,
                     "local_epochs": 2, "compute_dtype": "bfloat16",
                     "model": "mnist_cnn"},
        "device": str(jax.devices()[0]),
        "platform": str(jax.devices()[0].platform),
        "sweep": sweep,
        "best": {"client_chunk": best_chunk, "batch_size": best_batch,
                 "round_s": round(best_value, 4),
                 "est_mfu_pct": round(100 * mfu(best_value, 2, best_batch), 2)},
        "round3_baseline": {"client_chunk": 125, "batch_size": 64,
                            "round_s_r03": 0.7502, "est_mfu_pct_r03": 5.84,
                            "swept_here": baseline},
        "decomposition": decomposition,
        "mxu_shape_analysis": shape,
        "trace_dir": trace_dir,
        "mfu_basis": f"useful FLOPs only ({n_samples} samples/client x {n_clients} clients x "
                     f"2 epochs x {CNN_TRAIN_FLOPS / 1e6:.1f} MFLOP/sample-pass) at "
                     f"{V5E_BF16_PEAK / 1e12:.0f} TFLOP/s bf16 peak per chip",
    }
    out = REPO / "runs" / f"profile_flagship_{args.round_tag}.json"
    out.parent.mkdir(exist_ok=True)
    out.write_text(json.dumps(artifact, indent=2))
    print(json.dumps({k: artifact[k] for k in
                      ("best", "decomposition", "round3_baseline")}, indent=2))
    print(f"shape ceiling: {shape['flop_weighted_mxu_ceiling']:.1%} "
          f"(measured best MFU {artifact['best']['est_mfu_pct']}%)")
    log_stage(f"artifact written to {out}", t0=t_start)
    return 0


if __name__ == "__main__":
    sys.exit(main())
