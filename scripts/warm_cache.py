#!/usr/bin/env python
"""Warm (or verify) the shippable persistent compilation cache.

The warm-ship workflow (tuning.compile_cache): on the BUILD host, pre-compile
the full candidate program set into a cache directory off the critical path
and stamp a toolchain manifest::

    python scripts/warm_cache.py --model digits_mlp --cache-dir .jax_cache

then ``tar`` the directory, move it to the accel host, and on the RECEIVING
host check the manifest before trusting a single entry::

    python scripts/warm_cache.py --verify-only --cache-dir .jax_cache

``--verify-only`` exits 1 on an incompatible cache (foreign jax/jaxlib/
platform — XLA would silently key-miss and recompile everything; the manifest
says so up front).  Both modes print one JSON document to stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="digits_mlp")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--capacity", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--cache-dir", default=None,
                    help="cache directory (default: $NANOFED_CACHE_DIR or "
                    "./.jax_cache)")
    ap.add_argument("--compile-budget", type=float, default=None,
                    help="cap the sweep's total compile seconds (remaining "
                    "candidates are skipped, stated in the table)")
    ap.add_argument("--candidate-deadline", type=float, default=None,
                    help="per-candidate compile deadline in seconds (a wedged "
                    "compile is recorded, not waited out)")
    ap.add_argument("--force", action="store_true",
                    help="re-sweep over a warm autotune table (XLA entries "
                    "still hit, so a forced re-warm is cheap)")
    ap.add_argument("--verify-only", action="store_true",
                    help="verify an existing cache's manifest against THIS "
                    "host's toolchain instead of warming; exit 1 on mismatch")
    args = ap.parse_args(argv)

    from nanofed_tpu.tuning import verify_manifest

    if args.verify_only:
        import os

        # Same default resolution as utils.platform.enable_compilation_cache.
        cache_dir = (
            args.cache_dir
            or os.environ.get("NANOFED_CACHE_DIR")
            or os.path.join(os.getcwd(), ".jax_cache")
        )
        verdict = verify_manifest(cache_dir)
        print(json.dumps(verdict, indent=2, default=str))
        return 0 if verdict["compatible"] else 1

    from nanofed_tpu.models import get_model
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.tuning import PopulationSpec, TuningSpace, warm

    model = get_model(args.model)
    sample_shape = tuple(model.input_shape)
    result = warm(
        model,
        PopulationSpec(num_clients=args.clients, capacity=args.capacity,
                       sample_shape=sample_shape),
        TrainingConfig(batch_size=args.batch_size, local_epochs=1,
                       learning_rate=0.1),
        num_rounds=args.rounds,
        space=TuningSpace(
            client_chunks=(None,), rounds_per_blocks=(1, args.rounds),
            model_shards=(1,), batch_sizes=(args.batch_size,),
        ),
        cache_dir=args.cache_dir,
        force=args.force,
        compile_budget_s=args.compile_budget,
        candidate_deadline_s=args.candidate_deadline,
    )
    out = result.to_dict()
    out["verify"] = verify_manifest(result.cache_dir)
    print(json.dumps(out, indent=2, default=str))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
