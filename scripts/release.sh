#!/usr/bin/env bash
# Tag-and-release helper (parity with the reference's scripts/release.sh): bumps the
# version in pyproject.toml + package __init__, regenerates the changelog section, commits
# and tags. Push is left to the operator.
set -euo pipefail

VERSION="${1:-}"
if [[ -z "$VERSION" ]]; then
    echo "usage: scripts/release.sh <version>   (e.g. 0.2.0)" >&2
    exit 1
fi

if [[ -n "$(git status --porcelain)" ]]; then
    echo "working tree not clean; commit or stash first" >&2
    exit 1
fi

sed -i "s/^version = \".*\"/version = \"$VERSION\"/" pyproject.toml
sed -i "s/^__version__ = \".*\"/__version__ = \"$VERSION\"/" nanofed_tpu/__init__.py

python scripts/changelog.py "v$VERSION" > /tmp/changelog_section.md
if [[ -f CHANGELOG.md ]]; then
    cat /tmp/changelog_section.md CHANGELOG.md > /tmp/changelog_full.md
    mv /tmp/changelog_full.md CHANGELOG.md
else
    mv /tmp/changelog_section.md CHANGELOG.md
fi

git add pyproject.toml nanofed_tpu/__init__.py CHANGELOG.md
git commit -m "chore: release v$VERSION"
git tag -a "v$VERSION" -m "v$VERSION"
echo "tagged v$VERSION — push with: git push && git push --tags"
