#!/usr/bin/env python
"""Record the scan-over-layers compile-wall evidence artifact.

Measures the compile walltime of the REAL dispatched transformer round
program (``build_round_step`` via the autotuner's lowering path — not a bare
forward pass) at several depths, unrolled vs ``scan_layers=True``, and writes
``runs/compile_r17_<stamp>.json``.  The claim under test: unrolled compile
cost grows ~linearly in depth because XLA optimizes ``depth`` structurally
identical block bodies independently, while the scanned layout hands XLA ONE
block body regardless of depth, so its compile time is near-constant.

The XLA persistent compilation cache is NOT enabled for these measurements
(``jax_compilation_cache_dir`` stays unset and the autotune result cache is
off), so every number is a real from-scratch compile.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DEPTHS = (2, 4, 8)
VOCAB, SEQ_LEN, WIDTH, HEADS = 64, 16, 32, 4


def main() -> int:
    import jax

    from nanofed_tpu.models.transformer import transformer_lm
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.tuning import PopulationSpec, TuningSpace
    from nanofed_tpu.tuning.autotuner import autotune

    assert jax.config.jax_compilation_cache_dir is None, (
        "persistent compilation cache must be OFF while measuring compiles"
    )

    space = TuningSpace(client_chunks=(None,), rounds_per_blocks=(1,),
                        model_shards=(1,), batch_sizes=(16,))
    pop = PopulationSpec(num_clients=8, capacity=32, sample_shape=(SEQ_LEN,),
                         x_dtype="int32")
    training = TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1)

    rows = []
    for depth in DEPTHS:
        row = {"depth": depth}
        for scan in (False, True):
            model = transformer_lm(vocab=VOCAB, seq_len=SEQ_LEN, width=WIDTH,
                                   depth=depth, heads=HEADS, scan_layers=scan)
            result = autotune(model, pop, training, num_rounds=4, space=space,
                              cache_dir=None, out_dir=None,
                              include_epilogues=False)
            outcome = result.outcomes[0]
            assert outcome.feasible, outcome.reject_reason
            key = "scan" if scan else "unrolled"
            row[f"{key}_compile_s"] = outcome.cost["compile_seconds"]
        row["scan_over_unrolled"] = round(
            row["scan_compile_s"] / row["unrolled_compile_s"], 4
        )
        rows.append(row)
        print(f"depth={depth}: unrolled={row['unrolled_compile_s']}s "
              f"scan={row['scan_compile_s']}s", file=sys.stderr)

    first, last = rows[0], rows[-1]
    dev = jax.devices()[0]
    artifact = {
        "what": (
            "compile walltime of the dispatched transformer ROUND PROGRAM "
            "(build_round_step lowered+compiled through the autotuner path) "
            "at increasing depth, unrolled blocks vs scan-over-layers"
        ),
        "basis": (
            f"measured wall-clock of XLA compilation on platform="
            f"{dev.platform!r} device_kind={dev.device_kind!r} "
            f"(jax {jax.__version__}); the persistent compilation cache and "
            "the autotune result cache were both disabled, so every compile "
            "is from scratch.  CPU compile walltimes — absolute seconds will "
            "differ on TPU toolchains, the GROWTH SHAPE in depth is the claim."
        ),
        "model": {"vocab": VOCAB, "seq_len": SEQ_LEN, "width": WIDTH,
                  "heads": HEADS, "depths": list(DEPTHS)},
        "depths": rows,
        "growth": {
            "depth_ratio": last["depth"] / first["depth"],
            "unrolled_compile_ratio": round(
                last["unrolled_compile_s"] / first["unrolled_compile_s"], 4
            ),
            "scan_compile_ratio": round(
                last["scan_compile_s"] / first["scan_compile_s"], 4
            ),
            "claim": (
                "unrolled compile grows with depth; scan compile is "
                "near-constant (ratio ~1) because XLA sees one block body"
            ),
        },
        "parity": (
            "scan == unrolled layer math (identical logits, identical init "
            "values, identical RNG splits) is pinned by "
            "tests/unit/models/test_transformer.py"
        ),
    }

    out_dir = Path(__file__).resolve().parent.parent / "runs"
    out_dir.mkdir(exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    out = out_dir / f"compile_r17_{stamp}.json"
    out.write_text(json.dumps(artifact, indent=2, sort_keys=True) + "\n")
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
