#!/usr/bin/env python
"""One-shot on-chip evidence campaign for a round (VERDICT r3 items 1-3, 5-7).

The accelerator tunnel has been intermittent; when it IS up, this script captures
every on-chip artifact the round needs in one pass — most-critical first, so a tunnel
that dies mid-campaign still leaves the headline evidence — with per-stage isolation
(a failing stage is logged and skipped, never fatal) and a persistent campaign log
(``runs/tpu_campaign_<tag>.log``).

Stages, in priority order (artifacts land in ``runs/``):

  probe        short watchdogged backend probe; the campaign aborts early (rc 2) if
               the chip does not answer — no stage should burn its budget on a
               wedged tunnel
  bench        ``python bench.py`` — the driver-format headline numbers; stdout JSON
               is also recorded to ``runs/bench_tpu_<tag>.json`` (builder-side copy
               in case the round-end driver capture hits a dead tunnel again)
  pallas       ``scripts/measure_pallas.py`` — settles the fused dp_reduce kernel
               with numbers (VERDICT item 3)
  profile      ``scripts/profile_flagship.py`` — client_chunk x batch sweep, MFU vs
               the shape ceiling, fixed-vs-compute split (VERDICT item 2)
  accuracy100  ``scripts/record_accuracy.py --clients 100`` — north-star client
               count on real digits (VERDICT item 5)
  labelskew    ``scripts/record_evidence.py labelskew`` — config #2 (100 clients,
               2-class shards, C=0.1, CNN) on real digits, on-chip
  dp_cnn       ``scripts/record_evidence.py dp --model cnn`` — privacy-utility on
               the flagship CNN (VERDICT item 7)
  accuracy1000 ``scripts/record_accuracy.py --clients 1000`` — clearly-labeled
               degenerate-shard regime (~1.8 images/client on digits)

Usage:
    python scripts/tpu_campaign.py [--tag r04] [--stages bench,profile,...]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PY = sys.executable


def stages_for(tag: str) -> list[tuple[str, list[str], float]]:
    """(name, argv, budget_s) per stage."""
    s = str(REPO / "scripts")
    return [
        ("bench", [PY, str(REPO / "bench.py")], 2400.0),
        ("pallas", [PY, f"{s}/measure_pallas.py", "--round-tag", tag], 1200.0),
        ("profile", [PY, f"{s}/profile_flagship.py", "--round-tag", tag, "--trace"],
         2400.0),
        ("accuracy100", [PY, f"{s}/record_accuracy.py", "--clients", "100",
                         "--round-tag", tag], 1500.0),
        ("labelskew", [PY, f"{s}/record_evidence.py", "labelskew",
                       "--round-tag", tag], 1800.0),
        ("dp_cnn", [PY, f"{s}/record_evidence.py", "dp", "--model", "cnn",
                    "--round-tag", tag], 3600.0),
        ("accuracy1000", [PY, f"{s}/record_accuracy.py", "--clients", "1000",
                          "--round-tag", tag], 1500.0),
    ]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tag", default="r04")
    ap.add_argument("--stages", default=None,
                    help="comma list to run a subset (default: all, in order)")
    ap.add_argument("--skip-probe", action="store_true")
    args = ap.parse_args()

    log_path = REPO / "runs" / f"tpu_campaign_{args.tag}.log"
    log_path.parent.mkdir(exist_ok=True)

    def log(msg: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        print(line, flush=True)
        with open(log_path, "a") as f:
            f.write(line + "\n")

    if not args.skip_probe:
        log("probe: checking the accelerator answers before spending any budget")
        try:
            proc = subprocess.run(
                [PY, str(REPO / "bench.py"), "--probe", "accel", "probe"],
                capture_output=True, text=True, timeout=240,
            )
        except subprocess.TimeoutExpired:
            # A probe that cannot even exit its own watchdog = tunnel hard-wedged.
            log("probe: TIMED OUT after 240s — chip does not answer; aborting")
            return 2
        ok = any('"probe": "ok"' in line for line in proc.stdout.splitlines())
        log(f"probe: {'OK — ' + proc.stdout.strip().splitlines()[-1] if ok else 'FAILED'}")
        if not ok:
            log(f"probe stderr tail: {proc.stderr.splitlines()[-3:]}")
            return 2

    all_stages = stages_for(args.tag)
    selected = args.stages.split(",") if args.stages else None
    if selected is not None:
        unknown = [s for s in selected if s not in {n for n, _, _ in all_stages}]
        if unknown:
            # A typo must not exit 0 having "successfully" run nothing.
            log(f"unknown stage(s) {unknown}; valid: {[n for n, _, _ in all_stages]}")
            return 2
    summary = {}
    for name, argv, budget in all_stages:
        if selected is not None and name not in selected:
            continue
        log(f"stage {name}: {' '.join(argv[1:])} (budget {budget:.0f}s)")
        t0 = time.time()
        try:
            proc = subprocess.run(argv, capture_output=True, text=True, timeout=budget)
            rc, out, err = proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            rc = -1
            out = e.stdout.decode(errors="replace") if isinstance(e.stdout, bytes) else (e.stdout or "")
            err = e.stderr.decode(errors="replace") if isinstance(e.stderr, bytes) else (e.stderr or "")
        dt = time.time() - t0
        with open(log_path, "a") as f:
            f.write(f"----- {name} stdout -----\n{out}\n")
            f.write(f"----- {name} stderr (tail) -----\n"
                    + "\n".join(err.splitlines()[-30:]) + "\n")
        summary[name] = {"rc": rc, "seconds": round(dt, 1)}
        log(f"stage {name}: rc={rc} in {dt:.0f}s")
        if name == "bench":
            # Builder-side copy of the headline numbers, in the r03 artifact format.
            # Parsed REGARDLESS of rc: bench.py streams each workload's JSON as it
            # completes, so a flagship timeout must not lose a parity line already
            # sitting in stdout (the rc is recorded next to whatever was salvaged).
            results = []
            for line in out.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        results.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
            if results:
                bench_art = REPO / "runs" / f"bench_tpu_{args.tag}.json"
                bench_art.write_text(json.dumps({
                    "artifact": f"bench_tpu_{args.tag}",
                    "bench_rc": rc,
                    "note": (
                        "bench.py output captured by scripts/tpu_campaign.py on the "
                        "live chip; the driver's BENCH_*.json at round end is the "
                        "authoritative capture — this copy exists so the on-chip "
                        "evidence survives a tunnel that wedges before round end"
                        + ("" if rc == 0 else
                           f"; bench.py exited rc={rc} — partial results salvaged")
                    ),
                    "results": results,
                }, indent=2))
                log(f"stage bench: recorded {bench_art} ({len(results)} result(s))")

    log(f"campaign done: {json.dumps(summary)}")
    failed = [k for k, v in summary.items() if v["rc"] != 0]
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
