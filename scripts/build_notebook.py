#!/usr/bin/env python
"""Author + execute the tutorial notebook (parity: ``examples/mnist/tutorial.ipynb`` in
the reference, a 20-cell executed walkthrough whose cell outputs are the source of the
published baseline numbers).

Builds ``examples/mnist/tutorial.ipynb`` from the cell specs below with nbformat, then
executes it with nbconvert so the committed notebook carries REAL outputs.
"""

from __future__ import annotations

import sys
from pathlib import Path

import nbformat as nbf

REPO = Path(__file__).resolve().parent.parent

MD = [
    # 0
    """# NanoFed-TPU tutorial: federated learning as one SPMD program

This is the TPU-native re-telling of the reference tutorial
(`examples/mnist/tutorial.ipynb` in camille-004/nanofed). The reference runs an aiohttp
server plus client coroutines that exchange weights as JSON over localhost; every round
is a distributed-systems dance of polling, serialization and Python loops. Here the same
federated round is **one jitted XLA program over a device mesh**:

```
round = jit( shard_map( vmap(local_fit) ; psum-weighted-mean ) )
```

- every **client** is a slot on a named `clients` mesh axis (vmapped within a device,
  sharded across devices),
- **local training** is a `lax.scan` over batches inside `vmap` — no Python per-batch loop,
- **aggregation** (FedAvg) is a `psum` weighted mean across the mesh — the "network"
  is the TPU interconnect (ICI),
- the coordinator's wait-barrier disappears: SPMD lockstep *is* the barrier.
""",
    # 1
    """## 1. Platform setup

On a TPU host this cell is unnecessary — JAX finds the chips. For a portable tutorial we
force the **virtual 8-device CPU mesh** (the same trick `tests/conftest.py` uses), so
every `shard_map`/collective path below runs exactly as it would across 8 real chips.

> Skip this cell on a real TPU slice.""",
    # 2
    """## 2. Data: real images, federated

We use a real dataset that ships offline (scikit-learn's 1,797 handwritten 8×8 digit
images; swap in MNIST IDX files via `load_mnist(data_dir=...)` after running
`scripts/fetch_mnist.py`). `federate` partitions it into per-client shards and packs
them into ONE `ClientData` batch — a pytree of `[clients, samples, ...]` arrays with a
padding mask, because SPMD wants equal shapes, not ragged Python lists.""",
    # 3
    """## 3. Model: a pure `(init, apply)` pair

No `nn.Module`s: a model is a named pair of pure functions over an explicit parameter
pytree — the property that lets a whole federated round jit into one program.""",
    # 4
    """## 4. Train: the coordinator drives jitted SPMD rounds

`Coordinator` is the round engine (the reference's `Coordinator.train_round` polls an
HTTP buffer at 1 Hz; ours calls the compiled round step). Round 0 pays the XLA compile;
every later round is sub-millisecond-to-milliseconds at this scale.""",
    # 5
    """### Inspect the metrics artifacts

Per-round metrics land in `metrics/metrics_round_N.json` with per-client detail —
format parity with the reference's artifacts (its `coordinator.py:247-280`).""",
    # 6
    """## 5. Evaluation trajectory

`eval_every` evaluates the global model on held-out data inside the round loop; the
history lets us plot accuracy over rounds.""",
    # 7
    """## 6. Differential privacy in one argument

`central_privacy` turns the reduce into DP-FedAvg: per-client update clipping + Gaussian
noise INSIDE the jitted aggregation, and the coordinator accounts the (ε, δ) spend per
round (`privacy_epsilon` in the metrics).""",
    # 8
    """## 7. Checkpoint & resume

`FileStateStore` checkpoints round state; a new `Coordinator` with the same store picks
up at the next round — resume is integrated into the engine (the reference ships a
recovery module but never wires it in).""",
    # 9
    """## 8. Privacy calibration: pick σ for your budget, not by hand

The reference makes users choose a noise multiplier and hope; here
`noise_multiplier_for_budget` inverts the tight RDP accountant — give it (ε, δ) and the
round count, get the smallest σ that stays within budget.""",
    # 10
    """## 9. Secure aggregation over a REAL network

The masked round end-to-end on localhost aiohttp: clients enroll X25519 keys, fetch the
roster (canonical order + server-computed normalized weights), pre-scale + quantize +
pairwise-mask their update, and POST the masked uint32 vector. The server modular-sums —
the pairwise masks cancel *exactly* — and dequantizes the cohort's weighted mean. It
never sees an individual update. (This is the single-round no-dropout Bonawitz variant;
a missing client fails the round closed.)""",
    # 11
    """## 10. Dropout-tolerant secure aggregation (double masking)

In a real federation, dropout is the common case — one flaky phone must not kill the
cohort's round. `dropout_tolerant=True` runs the Bonawitz §4 double-masking variant:

1. each round, every client draws a **fresh ephemeral mask key + self-mask seed** and
   Shamir-shares both across the cohort (sealed blobs routed through — but unreadable
   by — the server; per-round freshness means a reveal burns only that round);
2. clients mask with pairwise streams **plus a self mask** and submit;
3. whoever misses the timeout is *dropped*: survivors answer the server's **unmask
   request** with shares of the dropped clients' mask keys and the survivors' self
   seeds — never both secrets of one client;
4. the coordinator reconstructs the orphaned masks, completes the round as the
   **weighted FedAvg of the survivors**, and evicts the dropped client.

Below, `c3` vanishes mid-round (after the share barrier — its masks are already baked
into everyone's vectors) and the round still completes from 3 survivors.

> **Serving this over the wire** (`nanofed-tpu serve --secure --dropout-tolerant`):
> `--min-clients` is a true *minimum* — enrollment stays open for stragglers (cap it
> with `--max-clients`) until the roster quiesces, and the Shamir threshold is derived
> from the cohort that **actually enrolled** (`max(configured, n//2+1)`, the
> split-view floor), announced to clients in the roster and re-derived per round as
> evictions shrink the active cohort. The static `threshold=3` below is the
> library-level equivalent for this fixed 4-client demo cohort.""",
    # 12
    """## 11. Per-round learning-rate schedules

Round-wise client-lr decay is standard FL practice the reference lacks. The TPU
constraint shapes the design: re-baking `TrainingConfig.learning_rate` per round is a
*static* jit-argument change — every round would re-trace and re-compile (~20-40 s on
a chip). Instead the schedule's scale streams through the compiled round step as a
**traced scalar** (`round_step(..., lr_scale)`): one program, zero recompiles, and a
resumed run continues the schedule exactly (it is a pure function of the round index).

The *server* optimizer needs no machinery at all — its optax state persists across
rounds, so `fedadam_strategy(learning_rate=optax.cosine_decay_schedule(...))` steps
per round natively.""",
    # 13
    """## 12. SCAFFOLD: correct the drift instead of damping it

Under non-IID data, FedAvg's local steps follow each client's own gradient field and
drift toward local optima; FedProx pulls iterates back with a proximal term.
**SCAFFOLD** (Karimireddy et al. 2020) removes the drift at its source: every local
step is corrected by (server control − client control), so in expectation each client
walks the *global* descent direction even on a one-class shard. The population's
client controls live as ONE stacked pytree sharded over the `clients` mesh axis —
under partial participation the cohort's control rows are gathered alongside its data
rows and the round's deltas scatter-added back.

Partial participation is exactly where it shines (each round's cohort is a biased
sample; the stored controls carry the absent clients' directions into the round), and
the correction is one round stale — it wants a *smaller* local lr than FedAvg's tuned
value (the paper's η_l = O(1/K) bound; `runs/scaffold_r05.json` records a diverged
lr=0.5 arm alongside the win).""",
    # 14
    """## 13. q8-delta wire compression

In a real cross-device federation the client→server update is the bandwidth bill.
`HTTPClient(update_encoding="q8-delta")` ships each round's **delta** stochastically
rounded to int8 with per-leaf absmax scales: unbiased (FedAvg's mean averages the
rounding noise away), **5.25×** fewer bytes than the already-binary npz format — 32×
fewer than the reference's JSON float lists — and signatures still verify, because
the client signs the server's exact float32 reconstruction. Measured end-to-end:
identical final accuracy after 15 fully-quantized rounds
(`runs/wire_compression_r05.json`). Below, the codec itself on a real trained
delta.""",
    # 15
    """## 14. Personalized evaluation

Global accuracy understates what federation gives each participant under non-IID
data: a client holding two classes doesn't need the 10-class decision boundary — it
needs a model that is excellent on ITS distribution after a few local steps.
`split_client_data` carves an honest per-client held-out split, and
`make_personalized_evaluator` fine-tunes the global model on each client's train
split and tests on its held-out split — one `jit(vmap(...))` over the whole
population, reusing the rounds' exact local-fit program. Measured at scale:
global 91.6% → personalized **99.4%** (`runs/personalization_r05.json`).""",
    # 16
    """## 15. Asynchronous federation (FedBuff)

The synchronous protocol is a barrier: every round waits for its slowest client.
`NetworkRoundConfig(async_buffer_k=K)` (CLI: `serve --async-buffer K`) removes it —
the server accepts updates based on any of the last `staleness_window` published
versions and aggregates exactly K whenever they arrive, each delta computed against
the version its client actually fetched and discounted by `(1+s)^-α` (Nguyen et al.
2022). Below, three clients at different speeds feed a live aiohttp server: no
aggregation waits for a cohort, and stale updates contribute at a discount instead
of gating anyone. Measured at scale (`runs/asyncfed_r05.json`): 5.4× faster to the
same update budget than the barrier, at higher accuracy.""",
    # 17
    """## Where to go next

- **Scale**: `client_chunk` trains 1000 clients on 8 chips in sequential chunks
  (`nanofed-tpu bench mnist_1000`); `compute_dtype="bfloat16"` engages the MXU.
  Measured on ONE real v5e chip: **0.74 s** for a 1000-client round of the current
  code — 271× the reference-extrapolated CPU baseline (`runs/bench_tpu_r05.json`).
- **Real networks**: `nanofed_tpu.communication` has a binary-payload HTTP server/client
  with RSA-PSS-signed updates and optional q8-delta compression;
  `examples/secure_federation/run_secure.py` is the full secure-aggregation protocol as
  a runnable script (`--dropout-tolerant --drop-client 2` demos multi-round recovery +
  eviction), and `nanofed-tpu serve --secure --dropout-tolerant` hosts it from the CLI.
- **Robustness**: `--robust-trim K` (or `method="median"`) bounds Byzantine clients
  structurally — measured holding 97.5% while plain FedAvg collapses to 7.8% under
  2 poisoned clients (`runs/byzantine_r05.json`).
- **Profiling**: `nanofed_tpu.utils.profiling.trace` captures TensorBoard/Perfetto
  device traces of a round.
- **Benchmarks**: `nanofed-tpu bench --list`; accuracy evidence in
  `runs/accuracy_digits_100c_r05.json` (the 97% bar met at 100 clients) and
  `runs/accuracy_digits_cnn28_r03.json` (the flagship CNN at 97.2% on real images).""",
]

CODE = [
    # A (after MD 1)
    """import os
from nanofed_tpu.utils.platform import force_cpu_mesh
force_cpu_mesh(8)   # portable tutorial: 8 virtual devices; skip on a real TPU slice

import jax
print(f"{len(jax.devices())} devices:", jax.devices()[:2], "...")""",
    # B (after MD 2)
    """from nanofed_tpu.data import federate, load_digits_dataset, pack_eval

train, test = load_digits_dataset("train"), load_digits_dataset("test")
print(f"train {train.x.shape}, test {test.x.shape}  (real 8x8 digit images)")

client_data = federate(train, num_clients=8, scheme="iid", batch_size=16, seed=0)
print("federated:", jax.tree.map(lambda a: a.shape, client_data))""",
    # C (after MD 3)
    """from nanofed_tpu.models import get_model, list_models
from nanofed_tpu.trainer import TrainingConfig

print("model zoo:", list_models())
model = get_model("digits_mlp", hidden=96)
training = TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.5)
params = model.init(jax.random.key(0))
print("params:", jax.tree.map(lambda a: a.shape, params))""",
    # D (after MD 4)
    """import time
from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig

coord = Coordinator(
    model=model,
    train_data=client_data,
    config=CoordinatorConfig(num_rounds=10, seed=0, base_dir="runs/tutorial",
                             eval_every=2),
    training=training,
    eval_data=pack_eval(test, batch_size=128),
)
t0 = time.time()
history = coord.run()
print(f"{len(history)} rounds in {time.time()-t0:.2f}s "
      f"(round 0 includes the XLA compile)")
for m in history[-3:]:
    print(f"  round {m.round_id}: loss={m.agg_metrics['loss']:.4f} "
          f"acc={m.agg_metrics['accuracy']:.4f} ({m.duration_s*1e3:.1f} ms)")""",
    # E (after MD 5)
    """import json, pathlib
artifact = json.loads(pathlib.Path("runs/tutorial/metrics/metrics_round_9.json").read_text())
print(json.dumps({k: v for k, v in artifact.items() if k != "clients"}, indent=2))
print("per-client weights:", [round(w, 3) for w in artifact["clients"]["weights"]])""",
    # F (after MD 6)
    """final = coord.evaluate()
print("final held-out:", final)
accs = [(m.round_id, m.eval_metrics["accuracy"]) for m in history if m.eval_metrics]
for r, a in accs:
    print(f"  round {r}: test acc {a:.4f} " + "#" * int(a * 40))""",
    # G (after MD 7)
    """from nanofed_tpu.aggregation import PrivacyAwareAggregationConfig
from nanofed_tpu.privacy import PrivacyConfig

dp_coord = Coordinator(
    model=model,
    train_data=client_data,
    config=CoordinatorConfig(num_rounds=3, seed=0, base_dir="runs/tutorial_dp"),
    training=training,
    central_privacy=PrivacyAwareAggregationConfig(
        privacy=PrivacyConfig(epsilon=8.0, delta=1e-5,
                              max_gradient_norm=1.0, noise_multiplier=0.7),
    ),
)
dp_history = dp_coord.run()
for m in dp_history:
    print(f"round {m.round_id}: acc={m.agg_metrics['accuracy']:.4f} "
          f"ε spent={m.agg_metrics['privacy_epsilon']:.3f} "
          f"(δ={m.agg_metrics['privacy_delta']:.0e})")""",
    # H (after MD 8)
    """import shutil

from nanofed_tpu.persistence import FileStateStore

# Fresh store: a leftover checkpoint from an earlier run would make BOTH
# coordinators resume instead of demonstrating train -> crash -> resume.
shutil.rmtree("runs/tutorial_ckpt", ignore_errors=True)
store = FileStateStore("runs/tutorial_ckpt")
c1 = Coordinator(model=model, train_data=client_data,
                 config=CoordinatorConfig(num_rounds=2, seed=0,
                                          base_dir="runs/tutorial_ckpt"),
                 training=training, state_store=store)
c1.run()
print("trained rounds 0-1; store has round", store.restore_latest().round_number)

c2 = Coordinator(model=model, train_data=client_data,
                 config=CoordinatorConfig(num_rounds=4, seed=0,
                                          base_dir="runs/tutorial_ckpt"),
                 training=training, state_store=FileStateStore("runs/tutorial_ckpt"))
resumed = c2.run()
print("resumed coordinator ran rounds:", [m.round_id for m in resumed])""",
    # I (after MD 9)
    """from nanofed_tpu.privacy.accounting import RDPAccountant, noise_multiplier_for_budget

rounds = 10
sigma = noise_multiplier_for_budget(epsilon=8.0, delta=1e-5,
                                    sampling_rate=1.0, num_events=rounds)
print(f"calibrated sigma for (eps=8, delta=1e-5) over {rounds} rounds: {sigma:.4f}")

acc = RDPAccountant()
acc.add_noise_event(sigma, 1.0, count=rounds)
print(f"spend check: eps={acc.get_privacy_spent(1e-5).epsilon_spent:.4f} <= 8.0")""",
    # J (after MD 10)
    """import asyncio, socket, numpy as np
from nanofed_tpu.communication import (HTTPClient, HTTPServer,
                                       NetworkCoordinator, NetworkRoundConfig)
from nanofed_tpu.security.secure_agg import (ClientKeyPair, SecureAggregationConfig,
                                             mask_update)

with socket.socket() as s:      # pick a free port (portable notebook)
    s.bind(("127.0.0.1", 0))
    PORT = s.getsockname()[1]

cfg = SecureAggregationConfig(min_clients=3)
init = model.init(jax.random.key(0))
local = {f"c{i}": model.init(jax.random.key(10 + i)) for i in range(3)}

async def secure_client(cid, n_samples):
    kp = ClientKeyPair.generate()
    async with HTTPClient(f"http://127.0.0.1:{PORT}", cid, timeout_s=30) as c:
        assert await c.register_secagg(kp.public_bytes(), n_samples)
        roster = await c.fetch_secagg_roster()
        for _ in range(200):                      # bounded: a failed round must error,
            try:                                  # not hang the notebook
                params, rnd, active = await c.fetch_global_model(like=init)
                break
            except Exception:
                await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never published")
        masked = mask_update(local[cid], roster.index_of(cid), kp,
                             roster.ordered_keys(), rnd, cfg,
                             weight=roster.weights[cid])
        await c.submit_masked_update(masked, {"num_samples": n_samples})

async def secure_round():
    server = HTTPServer(port=PORT)
    await server.start()
    try:
        nc = NetworkCoordinator(server, init,
                                NetworkRoundConfig(num_rounds=1, min_clients=3,
                                                   round_timeout_s=30),
                                secure=cfg)
        await asyncio.gather(nc.run(), secure_client("c0", 30.0),
                             secure_client("c1", 10.0), secure_client("c2", 20.0))
        return nc
    finally:
        await server.stop()

nc = await secure_round()
print("history:", nc.history)
delta = jax.tree.map(lambda a, b: float(np.abs(np.asarray(a - b)).max()),
                     nc.params, init)
print("aggregate moved (max |leaf delta|):", delta)""",
    # K (after MD 11) — dropout-tolerant double masking with a mid-round crash
    """import hashlib
from nanofed_tpu.security.secure_agg import (build_unmask_reveals,
                                             make_dropout_shares, open_share_inbox)

with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    PORT2 = s.getsockname()[1]

# threshold > n/2 (split-view defense); min_clients=3 is the privacy floor the
# 3 survivors still satisfy.
cfg_t = SecureAggregationConfig(min_clients=3, threshold=3, dropout_tolerant=True)
order4 = [f"c{i}" for i in range(4)]
local4 = {c: model.init(jax.random.key(20 + i)) for i, c in enumerate(order4)}

async def tolerant_client(cid, n_samples, drops=False):
    identity = ClientKeyPair.generate()
    async with HTTPClient(f"http://127.0.0.1:{PORT2}", cid, timeout_s=30) as c:
        assert await c.register_secagg(identity.public_bytes(), n_samples)
        roster = await c.fetch_secagg_roster()
        for _ in range(200):
            try:
                params, rnd, active = await c.fetch_global_model(like=init)
                break
            except Exception:
                await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never published")
        # Round start: fresh ephemeral secrets, Shamir-shared across the cohort.
        participants = await c.fetch_secagg_participants()
        mask_key = ClientKeyPair.generate()
        ctx = f"{c.secagg_session}:{rnd}"
        self_seed, sealed = make_dropout_shares(
            identity, mask_key, participants,
            {p: roster.public_keys[p] for p in participants}, cfg_t.threshold,
            my_id=cid, context=ctx)
        assert await c.deposit_secagg_shares(
            rnd, mask_key.public_bytes(), sealed,
            self_seed_commitment=hashlib.sha256(self_seed).digest())
        epks, inbox = await c.fetch_secagg_inbox(rnd)
        held = open_share_inbox(identity, cid, roster.public_keys, inbox, epks, ctx)
        if drops:
            print(f"  {cid}: crashing mid-round (after the share barrier)")
            return
        masked = mask_update(local4[cid], participants.index(cid), mask_key,
                             [epks[p] for p in participants], rnd, cfg_t,
                             weight=roster.weights[cid], self_seed=self_seed)
        await c.submit_masked_update(masked, {"num_samples": n_samples})
        for _ in range(600):                       # answer the unmask round
            request = await c.poll_unmask_request()
            if request is not None and cid in request["survivors"]:
                await c.submit_unmask_reveals(
                    request["round"], build_unmask_reveals(request, cid, held))
                return
            status = await c.check_server_status()
            if not status.get("training_active", True):
                return
            await asyncio.sleep(0.05)

async def tolerant_round():
    server = HTTPServer(port=PORT2)
    await server.start()
    try:
        nc = NetworkCoordinator(server, init,
                                NetworkRoundConfig(num_rounds=1, min_clients=4,
                                                   min_completion_rate=0.5,
                                                   round_timeout_s=2.5),
                                secure=cfg_t)
        await asyncio.gather(nc.run(),
                             tolerant_client("c0", 30.0), tolerant_client("c1", 10.0),
                             tolerant_client("c2", 20.0),
                             tolerant_client("c3", 40.0, drops=True))
        return nc
    finally:
        await server.stop()

nc2 = await tolerant_round()
print("history:", nc2.history)
assert nc2.history[0]["status"] == "COMPLETED" and nc2.history[0]["num_dropped"] == 1""",
    # L (after MD 12) — per-round lr schedule: decaying scale, zero recompiles
    """sched_coord = Coordinator(
    model=model,
    train_data=client_data,
    config=CoordinatorConfig(num_rounds=6, seed=0, base_dir="runs/tutorial_sched",
                             save_metrics=False, eval_every=2,
                             lr_schedule="cosine", lr_min_factor=0.2),
    training=TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.5),
    eval_data=pack_eval(test, batch_size=128),
)
scales = []
for m in sched_coord.start_training():
    scales.append(m.agg_metrics["lr_scale"])
    acc = m.eval_metrics.get("accuracy")
    print(f"round {m.round_id}: lr_scale={scales[-1]:.3f}"
          + (f"  test acc {acc:.4f}" if acc is not None else ""))
assert scales[0] == 1.0 and all(a >= b for a, b in zip(scales, scales[1:]))
assert scales[-1] > 0.2  # decayed toward — but never ONTO — the floor""",
    # M (after MD 13): SCAFFOLD vs FedAvg under drift + partial participation
    """drift_data = federate(train, num_clients=16, scheme="dirichlet",
                      batch_size=16, seed=1, alpha=0.05)  # ~1-2 classes per client

finals = {}
for name, scaffold in (("fedavg", False), ("scaffold", True)):
    c = Coordinator(
        model=model, train_data=drift_data,
        config=CoordinatorConfig(num_rounds=12, seed=0, participation_rate=0.5,
                                 base_dir="runs/nb_scaffold", save_metrics=False),
        training=TrainingConfig(batch_size=16, local_epochs=16, learning_rate=0.2),
        eval_data=pack_eval(test, batch_size=128),
        scaffold=scaffold,
    )
    c.run()
    finals[name] = c.evaluate()["accuracy"]
    print(f"{name:9s} final held-out accuracy: {finals[name]:.4f}")
print(f"drift correction buys {finals['scaffold'] - finals['fedavg']:+.4f}")""",
    # N (after MD 14): q8-delta codec on a real trained delta
    """import numpy as np
from nanofed_tpu.communication import (decode_delta_q8, encode_delta_q8,
                                       encode_params)
from nanofed_tpu.trainer import make_local_fit

fit = make_local_fit(model.apply, TrainingConfig(batch_size=16, local_epochs=2,
                                                 learning_rate=0.2))
one = jax.tree.map(lambda a: jax.numpy.asarray(a[0]), client_data)
res = fit(params, one, jax.random.key(3))
delta = jax.tree.map(lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
                     res.params, params)

wire_q8 = encode_delta_q8(delta, seed=0)
wire_npz = encode_params(res.params)
dq = decode_delta_q8(wire_q8, like=delta)
err = max(float(np.abs(a - b).max())
          for a, b in zip(jax.tree.leaves(dq), jax.tree.leaves(delta)))
print(f"npz full params: {len(wire_npz):7d} bytes")
print(f"q8 delta:        {len(wire_q8):7d} bytes  ({len(wire_npz)/len(wire_q8):.2f}x smaller)")
print(f"max dequantization error: {err:.2e} (bounded by absmax/127 per leaf)")""",
    # O (after MD 15): personalized evaluation on the drift federation
    """from nanofed_tpu.trainer import make_personalized_evaluator, split_client_data

fit_cd, heldout_cd = split_client_data(drift_data, test_fraction=0.25, seed=0)
pers_coord = Coordinator(
    model=model, train_data=fit_cd,
    config=CoordinatorConfig(num_rounds=8, seed=0, base_dir="runs/nb_pers",
                             save_metrics=False),
    training=TrainingConfig(batch_size=16, local_epochs=4, learning_rate=0.5),
)
pers_coord.run()
evaluate = make_personalized_evaluator(
    model.apply, TrainingConfig(batch_size=16, local_epochs=3, learning_rate=0.1))
out = evaluate(pers_coord.params, fit_cd, heldout_cd, jax.random.key(7))
print(f"on clients' OWN held-out data:")
print(f"  global model:       {float(out['global_accuracy']):.4f}")
print(f"  after 3 fine-tune epochs: {float(out['personal_accuracy']):.4f}"
      f"  (gain {float(out['personalization_gain']):+.4f})")""",
    # P (after MD 16): FedBuff async federation over live aiohttp (top-level await)
    """import asyncio
from nanofed_tpu.communication import (HTTPClient, HTTPServer,
                                       NetworkCoordinator, NetworkRoundConfig)
from nanofed_tpu.trainer.local import make_local_fit as _mlf

async_fit = jax.jit(_mlf(model.apply, TrainingConfig(batch_size=16, local_epochs=1,
                                                     learning_rate=0.3)))
async_init = model.init(jax.random.key(0))
_ = async_fit(async_init, jax.tree.map(lambda a: jax.numpy.asarray(a[0]), client_data),
              jax.random.key(0))  # warm the compile outside the timed federation

async def nb_client(cid, idx, delay, port):
    data = jax.tree.map(lambda a: jax.numpy.asarray(a[idx]), client_data)
    async with HTTPClient(f"http://127.0.0.1:{port}", cid, timeout_s=30) as c:
        while True:
            try:
                fetched, rnd, active = await c.fetch_global_model(like=async_init)
                if not active:
                    return
                r = async_fit(jax.tree.map(jax.numpy.asarray, fetched), data,
                              jax.random.key(idx * 100 + rnd))
                await asyncio.sleep(delay)   # heterogeneous device speed
                await c.submit_update(r.params, {"loss": float(r.metrics.loss),
                                                 "num_samples": 100.0})
            except Exception:
                return

import socket
with socket.socket() as _s:      # pick a free port (portable notebook)
    _s.bind(("127.0.0.1", 0))
    PORT = _s.getsockname()[1]
server = HTTPServer(port=PORT)
coord = NetworkCoordinator(server, async_init, NetworkRoundConfig(
    num_rounds=6, async_buffer_k=2, staleness_window=6,
    round_timeout_s=20.0, poll_interval_s=0.01))
await server.start()
tasks = [asyncio.ensure_future(nb_client(f"c{i}", i, 0.08 if i == 0 else 0.02, PORT))
         for i in range(3)]
history = await coord.run()
await asyncio.gather(*tasks)
await server.stop()
all_staleness = []
for h in history:
    s = h.get("staleness", [])   # FAILED records carry no staleness
    all_staleness += s
    print(f"aggregation {h['aggregation']} [{h['status']}]: "
          f"{h['num_clients']} updates, staleness {s}")
stale = sum(v > 0 for v in all_staleness)
print(f"{stale}/{len(all_staleness)} aggregated updates were stale — "
      "discounted by (1+s)^-0.5, and no aggregation waited for a cohort")
assert stale > 0  # the demo only teaches what its own run shows""",
]


def build() -> nbf.NotebookNode:
    nb = nbf.v4.new_notebook()
    nb.metadata["kernelspec"] = {"name": "python3", "display_name": "Python 3",
                                 "language": "python"}
    cells = [nbf.v4.new_markdown_cell(MD[0])]
    # MD[i] pairs with CODE[i-1]; the last MD entry is the unpaired closing section —
    # derived, so adding a section is one MD + one CODE append, not three edits.
    for md_i in range(1, len(CODE) + 1):
        cells.append(nbf.v4.new_markdown_cell(MD[md_i]))
        cells.append(nbf.v4.new_code_cell(CODE[md_i - 1]))
    cells.append(nbf.v4.new_markdown_cell(MD[-1]))
    nb.cells = cells
    return nb


def main() -> int:
    out = REPO / "examples" / "mnist" / "tutorial.ipynb"
    nb = build()
    nbf.write(nb, out)
    print(f"wrote {out} ({len(nb.cells)} cells); executing...")

    from nbclient import NotebookClient

    client = NotebookClient(nb, timeout=600, kernel_name="python3",
                            resources={"metadata": {"path": str(REPO)}})
    client.execute()
    nbf.write(nb, out)
    print("executed + saved with outputs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
