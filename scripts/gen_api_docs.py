#!/usr/bin/env python
"""Generate the API reference (``docs/api/*.md``) from the package's docstrings.

The reference publishes a Sphinx API site via readthedocs; this repo keeps docs in
markdown, so the reference pages are generated straight from ``inspect`` — every public
module, class, function and dataclass with its signature and docstring.  Regenerate with
``make api-docs`` (or ``python scripts/gen_api_docs.py``) after API changes; CI treats a
dirty regeneration as a failure the same way formatters are treated.
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

MODULES = [
    ("core", ["nanofed_tpu.core.types", "nanofed_tpu.core.interfaces",
              "nanofed_tpu.core.exceptions"]),
    ("data", ["nanofed_tpu.data.datasets", "nanofed_tpu.data.partition",
              "nanofed_tpu.data.batching"]),
    ("models", ["nanofed_tpu.models.base", "nanofed_tpu.models.linear",
                "nanofed_tpu.models.mnist", "nanofed_tpu.models.resnet",
                "nanofed_tpu.models.transformer", "nanofed_tpu.nn"]),
    ("adapters", ["nanofed_tpu.adapters.lora",
                  "nanofed_tpu.adapters.evidence"]),
    ("fleet", ["nanofed_tpu.fleet.profile", "nanofed_tpu.fleet.aggregate",
               "nanofed_tpu.fleet.wire", "nanofed_tpu.fleet.gateway",
               "nanofed_tpu.fleet.swarm", "nanofed_tpu.fleet.tuning",
               "nanofed_tpu.fleet.evidence"]),
    ("trainer", ["nanofed_tpu.trainer.config", "nanofed_tpu.trainer.local",
                 "nanofed_tpu.trainer.private", "nanofed_tpu.trainer.scaffold",
                 "nanofed_tpu.trainer.schedules",
                 "nanofed_tpu.trainer.personalization",
                 "nanofed_tpu.trainer.callbacks", "nanofed_tpu.trainer.api"]),
    ("aggregation", ["nanofed_tpu.aggregation.base", "nanofed_tpu.aggregation.fedavg",
                     "nanofed_tpu.aggregation.privacy",
                     "nanofed_tpu.aggregation.robust"]),
    ("parallel", ["nanofed_tpu.parallel.mesh", "nanofed_tpu.parallel.round_step",
                  "nanofed_tpu.parallel.multi_round",
                  "nanofed_tpu.parallel.scaffold_step",
                  "nanofed_tpu.parallel.resilience"]),
    ("privacy", ["nanofed_tpu.privacy.config", "nanofed_tpu.privacy.noise",
                 "nanofed_tpu.privacy.accounting", "nanofed_tpu.privacy.mechanisms"]),
    ("security", ["nanofed_tpu.security.validation", "nanofed_tpu.security.signing",
                  "nanofed_tpu.security.secure_agg"]),
    ("persistence", ["nanofed_tpu.persistence.serialization",
                     "nanofed_tpu.persistence.model_manager",
                     "nanofed_tpu.persistence.state_store",
                     "nanofed_tpu.persistence.generation_store"]),
    ("orchestration", ["nanofed_tpu.orchestration.types",
                       "nanofed_tpu.orchestration.coordinator"]),
    ("communication", ["nanofed_tpu.communication.codec",
                       "nanofed_tpu.communication.transport",
                       "nanofed_tpu.communication.http_server",
                       "nanofed_tpu.communication.http_client",
                       "nanofed_tpu.communication.retry",
                       "nanofed_tpu.communication.network_coordinator"]),
    ("faults", ["nanofed_tpu.faults.plan",
                "nanofed_tpu.faults.injector",
                "nanofed_tpu.faults.host_injector"]),
    ("ingest", ["nanofed_tpu.ingest.buffer",
                "nanofed_tpu.ingest.pipeline"]),
    ("loadgen", ["nanofed_tpu.loadgen.swarm",
                 "nanofed_tpu.loadgen.harness"]),
    ("service", ["nanofed_tpu.service.scheduler",
                 "nanofed_tpu.service.tenant",
                 "nanofed_tpu.service.service",
                 "nanofed_tpu.service.harness"]),
    ("observability", ["nanofed_tpu.observability.registry",
                       "nanofed_tpu.observability.spans",
                       "nanofed_tpu.observability.telemetry",
                       "nanofed_tpu.observability.profiling",
                       "nanofed_tpu.observability.tracing",
                       "nanofed_tpu.observability.critical_path"]),
    ("tuning", ["nanofed_tpu.tuning.autotuner",
                "nanofed_tpu.tuning.epilogues"]),
    ("analysis", ["nanofed_tpu.analysis.fedlint",
                  "nanofed_tpu.analysis.program_audit",
                  "nanofed_tpu.analysis.contracts"]),
    ("ops", ["nanofed_tpu.ops.reduce", "nanofed_tpu.ops.dp_reduce",
             "nanofed_tpu.ops.quantize"]),
    ("utils", ["nanofed_tpu.utils.logger", "nanofed_tpu.utils.profiling",
               "nanofed_tpu.utils.trees", "nanofed_tpu.utils.platform",
               "nanofed_tpu.utils.clock", "nanofed_tpu.utils.aio",
               "nanofed_tpu.utils.dates"]),
    ("top-level", ["nanofed_tpu.experiments", "nanofed_tpu.benchmarks",
                   "nanofed_tpu.cli"]),
]


def _sig(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # Function-object defaults repr with a memory address ("<function sum at 0x...>"),
    # which would churn the generated files on every run; keep just the name.
    return re.sub(r"<function (\S+) at 0x[0-9a-f]+>", r"<function \1>", sig)


def _doc(obj) -> str:
    d = inspect.getdoc(obj)
    return d.strip() if d else "*(undocumented)*"


def _summary(obj) -> str:
    """First PARAGRAPH of the docstring as one line (a first physical line can end
    mid-sentence when the source wraps)."""
    return " ".join(_doc(obj).split("\n\n")[0].split())


def _is_public(name: str) -> bool:
    return not name.startswith("_")


def document_module(modname: str) -> str:
    try:
        mod = importlib.import_module(modname)
    except ImportError as e:
        # An optional dependency (e.g. `cryptography` for the security modules) may
        # be absent in this environment; keep the page generable rather than dying
        # halfway with some files regenerated and others stale.
        print(f"  SKIPPED {modname}: {e}", file=sys.stderr)
        return "\n".join([
            f"## `{modname}`", "",
            f"*(not regenerated here — import failed: `{e}`; rerun `make api-docs` "
            "in an environment with the module's optional dependencies)*", "",
        ])
    lines = [f"## `{modname}`", "", _doc(mod), ""]
    members = []
    for name, obj in vars(mod).items():
        if not _is_public(name):
            continue
        # Plain classes/functions, plus functools.wraps'd wrapper objects —
        # notably jax.jit callables (the Pallas ops are module-level jits):
        # they carry the wrapped function's __module__/__doc__/signature, and
        # skipping them silently dropped every kernel from the ops page.
        wrapped_fn = inspect.isfunction(getattr(obj, "__wrapped__", None))
        if inspect.isclass(obj) or inspect.isfunction(obj) or (
            callable(obj) and wrapped_fn
        ):
            if getattr(obj, "__module__", None) != modname:
                continue  # re-exports documented at their home module
            members.append((name, obj))
    for name, obj in members:
        if inspect.isclass(obj):
            kind = "dataclass" if dataclasses.is_dataclass(obj) else "class"
            lines += [f"### {kind} `{name}{_sig(obj)}`", "", _doc(obj), ""]
            if dataclasses.is_dataclass(obj):
                rows = [
                    f"| `{f.name}` | `{getattr(f.type, '__name__', f.type)}` | "
                    f"`{f.default if f.default is not dataclasses.MISSING else '—'}` |"
                    for f in dataclasses.fields(obj)
                ]
                lines += ["| field | type | default |", "|---|---|---|", *rows, ""]
            for mname, meth in vars(obj).items():
                if not _is_public(mname):
                    continue
                func = meth.__func__ if isinstance(meth, (classmethod, staticmethod)) else meth
                if inspect.isfunction(func) and inspect.getdoc(func):
                    lines += [f"- **`{mname}{_sig(func)}`** — {_summary(func)}"]
            lines += [""]
        else:
            lines += [f"### `{name}{_sig(obj)}`", "", _doc(obj), ""]
    return "\n".join(lines)


def main() -> int:
    outdir = REPO / "docs" / "api"
    outdir.mkdir(parents=True, exist_ok=True)
    index = ["# API reference", "",
             "Generated from docstrings by `scripts/gen_api_docs.py` — do not edit by",
             "hand; run `make api-docs` after API changes.", ""]
    for group, mods in MODULES:
        fname = f"{group.replace('-', '_')}.md"
        parts = [f"# `{group}` API", ""]
        for m in mods:
            parts.append(document_module(m))
        (outdir / fname).write_text("\n".join(parts) + "\n")
        index.append(f"- [{group}]({fname}): " + ", ".join(f"`{m}`" for m in mods))
        print(f"  wrote docs/api/{fname}")
    (outdir / "index.md").write_text("\n".join(index) + "\n")
    print("wrote docs/api/index.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
