#!/usr/bin/env python
"""Measure the Pallas reduce kernels vs XLA at the flagship stacked shape (VERDICT r2
item 5: 'finish the Pallas story or retire it with numbers').

Workload: C=1000 clients x P=1.2M params (the MNIST-CNN flagship shape), f32.

- plain weighted mean: ``ops.reduce.weighted_mean_flat``  vs  XLA tensordot/sum
- central-DP clip+mean: ``ops.dp_reduce.dp_clipped_mean_flat``  vs  XLA
  clip-then-mean (the materializing round-step form: vmap global-norm clip, then
  uniform weighted mean — three [C,P] HBM passes vs the kernel pipeline's two)

Writes ``runs/pallas_reduce_<tag>.json`` with median-of-N timings; the verdict in the
artifact decides which implementation the stacked DP paths use.

Run on the real chip (default env). CPU runs are refused — interpret-mode timings say
nothing about the HBM-traffic tradeoff being measured.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def time_fn(fn, *args, reps: int = 7) -> float:
    """Median wall-clock seconds of ``fn(*args)`` (blocked), after one warm-up —
    thin wrapper over the shared ``utils.profiling.device_time`` discipline."""
    from nanofed_tpu.utils.profiling import device_time

    return device_time(lambda: fn(*args), reps=reps)["median_s"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--params", type=int, default=1_199_882)  # MNIST-CNN param count
    ap.add_argument("--round-tag", default="r03")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.ops import dp_clipped_mean_flat, weighted_mean_flat
    from nanofed_tpu.utils.platform import enable_compilation_cache

    if jax.default_backend() != "tpu":
        print("refusing: not a TPU backend (interpret-mode timings are meaningless)")
        return 2
    enable_compilation_cache()

    c, p = args.clients, args.params
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(c, p)).astype(np.float32))
    w = jnp.asarray(np.ones(c, np.float32))
    clip = 0.5

    @jax.jit
    def xla_weighted_mean(x, w):
        return jnp.tensordot(w, x, axes=1) / jnp.maximum(w.sum(), 1e-12)

    @jax.jit
    def xla_clip_then_mean(x, w):
        norms = jnp.sqrt(jnp.sum(x * x, axis=1))
        coef = jnp.minimum(1.0, clip / jnp.maximum(norms, 1e-12))
        clipped = x * coef[:, None]  # the [C, P] materialization the kernel avoids
        return jnp.tensordot(w, clipped, axes=1) / jnp.maximum(w.sum(), 1e-12)

    results = {}
    for name, fn, fargs in [
        ("xla_weighted_mean", xla_weighted_mean, (x, w)),
        ("pallas_weighted_mean", lambda x, w: weighted_mean_flat(x, w), (x, w)),
        ("xla_clip_then_mean", xla_clip_then_mean, (x, w)),
        ("pallas_dp_clipped_mean", lambda x, w: dp_clipped_mean_flat(x, w, clip), (x, w)),
    ]:
        results[name] = time_fn(fn, *fargs)
        print(f"{name}: {results[name]*1e3:.2f} ms", flush=True)

    # Numerical agreement at the measured shape.
    ref = np.asarray(xla_clip_then_mean(x, w))
    got = np.asarray(dp_clipped_mean_flat(x, w, clip))
    max_err = float(np.max(np.abs(ref - got)))

    # SecAgg masking throughput: host Philox path vs on-device kernels, one client
    # masking a 10M-param update against a 9-peer cohort.
    from nanofed_tpu.security.secure_agg import (
        ClientKeyPair, SecureAggregationConfig, mask_update,
    )

    big_p = 10_000_000
    big = {"w": jnp.asarray(rng.normal(size=(big_p,)).astype(np.float32))}
    cfg = SecureAggregationConfig(min_clients=3)
    keys = [ClientKeyPair.generate() for _ in range(10)]
    pks = [k.public_bytes() for k in keys]
    for backend in ("host", "device"):
        results[f"secagg_mask_10M_{backend}"] = time_fn(
            lambda b=backend: mask_update(big, 0, keys[0], pks, 0, cfg, backend=b),
            reps=3,
        )
        print(f"secagg_mask_10M_{backend}: "
              f"{results[f'secagg_mask_10M_{backend}']*1e3:.2f} ms", flush=True)
    mask_speedup = results["secagg_mask_10M_host"] / results["secagg_mask_10M_device"]

    wm_speedup = results["xla_weighted_mean"] / results["pallas_weighted_mean"]
    dp_speedup = results["xla_clip_then_mean"] / results["pallas_dp_clipped_mean"]
    artifact = {
        "artifact": f"pallas_reduce_{args.round_tag}",
        "shape": {"clients": c, "params": p, "dtype": "float32"},
        "device": str(jax.devices()[0]),
        "timings_s": {k: round(v, 6) for k, v in results.items()},
        "plain_mean_speedup_vs_xla": round(wm_speedup, 3),
        "dp_fused_speedup_vs_xla": round(dp_speedup, 3),
        "secagg_mask_device_speedup_vs_host": round(mask_speedup, 3),
        "max_abs_err_vs_xla": max_err,
        "verdict": (
            "kernel wins — wire dp_reduce into the stacked central-DP paths"
            if dp_speedup > 1.05
            else "XLA wins or ties — keep XLA in production, kernel stays as the "
                 "measured baseline"
        ),
        "aggregation": "median after warm-up: 7 reps (reduce timings), "
                       "3 reps (secagg masking timings)",
    }
    out = REPO / "runs" / f"pallas_reduce_{args.round_tag}.json"
    out.parent.mkdir(exist_ok=True)
    out.write_text(json.dumps(artifact, indent=2))
    print(json.dumps(artifact, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
