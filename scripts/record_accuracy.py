#!/usr/bin/env python
"""Record the real-data accuracy evidence artifact (VERDICT r1 item 4).

Runs federated FedAvg on REAL handwritten-digit images to >= 97% held-out test accuracy
and writes ``runs/accuracy_<dataset>_r{N}.json`` with the config, per-eval trajectory,
and wall-clock-to-97.

Dataset choice: with MNIST IDX files present (``--data-dir``, see
``scripts/fetch_mnist.py``), runs the MNIST CNN at reference parity
(``docs/source/getting_started/tutorial.rst:325-334`` records 93.75% round-1 aggregated
accuracy; BASELINE.md's north star is wall-clock to 97% test accuracy).  In zero-egress
environments it falls back to the bundled sklearn digits dataset (1,797 real 8x8 digit
images) — smaller, but real pixels, real generalization, same 97% bar.

Usage:
    python scripts/record_accuracy.py [--data-dir data/mnist] [--round-tag r02]
    python scripts/record_accuracy.py --platform cpu   # force the virtual CPU mesh
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

TARGET_ACC = 0.97


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=None, help="MNIST IDX dir (else bundled digits)")
    ap.add_argument("--round-tag", default="r03")
    ap.add_argument("--platform", choices=["auto", "cpu"], default="auto")
    ap.add_argument("--max-rounds", type=int, default=60)
    ap.add_argument("--n-devices", type=int, default=8)
    ap.add_argument(
        "--model", choices=["mlp", "cnn"], default="cnn",
        help="evidence model when MNIST is unavailable: digits_mlp on native 8x8, or "
        "the flagship MNIST CNN on the real digits bilinearly upsampled to 28x28",
    )
    ap.add_argument(
        "--clients", type=int, default=None,
        help="override the client count (north-star configs: 100/1000; with the "
        "1,797-image digits set, 100 clients is a realistic ~18-images-per-client "
        "cross-device regime — the artifact name and body record the count)",
    )
    # Optimizer overrides (round-5 sweep: at 100 clients the MLP plateaus at 96.1%
    # with the defaults but crosses 97.5% by round ~21 with momentum 0.9 + 4 local
    # epochs — the fragmented-shard regime needs more local progress per round).
    ap.add_argument("--momentum", type=float, default=None)
    ap.add_argument("--local-epochs", type=int, default=None)
    ap.add_argument("--lr", type=float, default=None)
    ap.add_argument("--hidden", type=int, default=None,
                    help="digits_mlp width override (mlp evidence model only)")
    ap.add_argument("--lr-schedule", default="constant",
                    choices=["constant", "cosine", "linear", "step"])
    ap.add_argument("--lr-min-factor", type=float, default=0.0)
    args = ap.parse_args()

    from nanofed_tpu.utils.platform import (
        force_cpu_mesh,
        init_devices_or_die,
        log_stage,
    )

    if args.platform == "cpu":
        force_cpu_mesh(args.n_devices)

    import jax

    from nanofed_tpu.data import federate, load_digits_dataset, load_mnist, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig

    devices = init_devices_or_die(150.0)
    log_stage(f"devices: {len(devices)}x {devices[0].platform}")

    mnist_available = False
    if args.data_dir is not None:
        try:
            load_mnist("train", args.data_dir, synthetic_fallback=False)
            mnist_available = True
        except FileNotFoundError:
            log_stage(f"no MNIST under {args.data_dir}; using bundled digits")

    if mnist_available:
        dataset, model_name = "mnist", "mnist_cnn"
        model = get_model(model_name)
        train = load_mnist("train", args.data_dir, synthetic_fallback=False)
        test = load_mnist("test", args.data_dir, synthetic_fallback=False)
        training = TrainingConfig(batch_size=64, local_epochs=2, learning_rate=0.1)
        num_clients, batch_eval = 10, 256
    elif args.model == "cnn":
        # Flagship-model evidence without MNIST: the REAL digits images upsampled to
        # 28x28 so the parity CNN architecture itself (not a stand-in MLP) is what
        # crosses the 97% bar on real data.
        from nanofed_tpu.data.datasets import resize_images

        dataset, model_name = "digits_cnn28", "mnist_cnn"
        model = get_model(model_name)
        train = resize_images(load_digits_dataset("train"), 28, 28)
        test = resize_images(load_digits_dataset("test"), 28, 28)
        training = TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.1)
        num_clients, batch_eval = 8, 128
    else:
        dataset, model_name = "digits", "digits_mlp"
        model = get_model(model_name, hidden=args.hidden or 96)
        train = load_digits_dataset("train")
        test = load_digits_dataset("test")
        training = TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.5)
        num_clients, batch_eval = 8, 128

    import dataclasses

    overrides = {
        k: v for k, v in (
            ("momentum", args.momentum),
            ("local_epochs", args.local_epochs),
            ("learning_rate", args.lr),
        ) if v is not None
    }
    if overrides:
        training = dataclasses.replace(training, **overrides)

    if args.clients is not None:
        num_clients = args.clients
        dataset = f"{dataset}_{num_clients}c"
        if num_clients * 2 > len(train):
            # Degenerate shards (< 2 images/client) — keep batches meaningful.
            training = dataclasses.replace(training, batch_size=2)
    log_stage(f"dataset={train.name}: {len(train)} train / {len(test)} test (REAL data)")
    cd = federate(train, num_clients=num_clients, scheme="iid",
                  batch_size=training.batch_size, seed=0)
    coord = Coordinator(
        model=model,
        train_data=cd,
        config=CoordinatorConfig(num_rounds=args.max_rounds, seed=0,
                                 base_dir="runs/accuracy_run", eval_every=1,
                                 lr_schedule=args.lr_schedule,
                                 lr_min_factor=args.lr_min_factor),
        training=training,
        eval_data=pack_eval(test, batch_size=batch_eval),
    )

    t0 = time.time()
    trajectory = []
    reached_at = None
    for m in coord.start_training():
        acc = m.eval_metrics.get("accuracy")
        if acc is None:
            continue
        trajectory.append({"round": m.round_id, "test_accuracy": round(float(acc), 4),
                           "elapsed_s": round(time.time() - t0, 2)})
        log_stage(f"round {m.round_id}: test acc {acc:.4f}")
        if acc >= TARGET_ACC and reached_at is None:
            reached_at = trajectory[-1]
            break

    artifact = {
        "artifact": f"accuracy_{dataset}_{args.round_tag}",
        "dataset": train.name,
        "real_data": True,
        "data_note": (
            "sklearn digits: 1,797 REAL handwritten-digit images (UCI optdigits), "
            "bilinearly upsampled 8x8 -> 28x28 so the flagship MNIST-CNN architecture "
            "is the model under test; MNIST itself is unfetchable here (see "
            "runs/mnist_fetch_attempt_*.log for the documented zero-egress attempt)"
            if dataset == "digits_cnn28"
            else "sklearn digits: 1,797 REAL handwritten-digit images (UCI optdigits)"
        ) if dataset != "mnist" else "MNIST IDX files",
        "model": (f"{model_name}(hidden={args.hidden or 96})"
                  if model_name == "digits_mlp" else model_name),
        "num_clients": num_clients,
        "scheme": "iid",
        "training": {"batch_size": training.batch_size,
                     "local_epochs": training.local_epochs,
                     "learning_rate": training.learning_rate,
                     "momentum": training.momentum,
                     "lr_schedule": args.lr_schedule},
        "target_accuracy": TARGET_ACC,
        "reached": reached_at is not None,
        "reached_at_round": reached_at["round"] if reached_at else None,
        "wall_clock_to_target_s": reached_at["elapsed_s"] if reached_at else None,
        "final_test_accuracy": trajectory[-1]["test_accuracy"] if trajectory else None,
        "trajectory": trajectory,
        "platform": str(devices[0].platform),
        "devices": len(devices),
        "reference_parity_note": (
            "reference records 93.75% round-1 aggregated accuracy on MNIST "
            "(docs/source/getting_started/tutorial.rst:325-334); target here is the "
            "BASELINE.md 97% test-accuracy bar on real data"
        ),
    }
    out = REPO / "runs" / f"accuracy_{dataset}_{args.round_tag}.json"
    out.parent.mkdir(exist_ok=True)
    out.write_text(json.dumps(artifact, indent=2))
    print(json.dumps({k: v for k, v in artifact.items() if k != "trajectory"}, indent=2))
    log_stage(f"artifact written to {out}")
    return 0 if reached_at else 1


if __name__ == "__main__":
    sys.exit(main())
