"""Multi-process pod-scale federation harness (ROADMAP item 1).

One file, two jobs, both driven by REAL ``jax.distributed`` processes on the
CPU backend (gloo collectives — see ``parallel.mesh.initialize_distributed``),
so the whole hosts-axis path is testable without a pod:

* ``smoke`` (``make multihost-smoke``, the non-blocking CI job): a 2-process
  run of the HIERARCHICAL 3-axis round program — per-host data sharding via
  :func:`~nanofed_tpu.parallel.shard_host_local_data` (no process ever holds
  the full population), host-local ``psum`` over ``clients`` then ONE
  cross-host ``psum`` over ``hosts`` — asserted for trajectory parity
  (per-round losses AND final params, float tolerance) against a
  single-process 1-D mesh over the same virtual device count running the
  byte-identical workload.

* ``bench``: the scale jump — ``--clients 100000`` (default) streamed through
  ``client_chunk`` chunking x multi-process, producing a
  ``runs/multihost_*.json`` artifact with rounds/sec and clients/sec plus the
  topology block (``process_count``/``hosts``/``mesh_shape``) the BENCH
  conventions require.  The basis is stated honestly: virtual CPU devices and
  gloo-over-loopback measure the PROGRAM (hierarchical collectives, chunked
  streaming, multi-controller dispatch) at population scale, not TPU silicon.

* ``hostchaos`` (``make hostchaos-smoke``): the host fault-tolerance drill.
  A SUPERVISOR spawns the worker mesh under a seeded fault plan
  (``host_crash``/``host_stall``/``dcn_degrade`` — ``nanofed_tpu.faults``),
  the workers heartbeat (``parallel.resilience.Heartbeat``), bracket every
  cross-host dispatch with a ``CollectiveWatchdog`` deadline, and checkpoint
  at block boundaries under generation numbers with commit markers
  (``persistence.GenerationStore``).  When the plan kills or stalls a host,
  the supervisor detects it (process exit / frozen heartbeat), kills and
  REAPS every survivor, re-forms the mesh over the surviving host set (the
  shrunk hosts axis, cohort quotas, and data sharding all re-derive through
  ``MeshLayout``), resumes from the newest generation committed by ALL
  participants (at most one block of rounds re-run), and optionally lets the
  failed host REJOIN at the next generation boundary.  The run ends with a
  ``runs/hostchaos_*.json`` artifact: MTTR, rounds lost, post-recovery loss
  parity vs an unfailed run on the same shrunk mesh from the same recovery
  point, and a zero-orphans check over every pid ever spawned.

Launcher (default entry) spawns the worker processes of itself; workers rendez-
vous through ``jax.distributed`` on a loopback coordinator.  Every knob rides
argv so the launcher and workers cannot drift.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # the hostchaos supervisor imports nanofed_tpu
    sys.path.insert(0, str(REPO))

SMOKE_TOL = 5e-5  # hierarchical vs flat psum: re-association only (~1e-7 seen)

#: Worker exit code when the collective watchdog (or a gloo/distributed error)
#: surfaced a PEER's failure — distinct from the planned victim's own death
#: (HOST_CRASH_RC, imported so the supervisor's rc match can never drift from
#: what the injector actually exits with; host_injector is pure stdlib).
PEER_FAILURE_RC = 32
from nanofed_tpu.faults.host_injector import (  # noqa: E402
    HOST_CRASH_EXIT_CODE as HOST_CRASH_RC,
)


def _worker_env(args: argparse.Namespace, process_id: int) -> dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices_per_process}"
    )
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    env["NANOFED_MH_PROCESS_ID"] = str(process_id)
    return env


def client_rows(client_ids, capacity: int, feat: tuple[int, ...], seed: int):
    """Deterministic synthetic data for a RANGE of global client ids — the same
    rows regardless of which process (or how many) materializes them, which is
    what makes the multi-process run byte-comparable to the single-process
    reference.  Linearly-separable-ish classes so a few rounds visibly learn."""
    import numpy as np

    xs, ys = [], []
    for cid in client_ids:
        rng = np.random.default_rng(seed * 1_000_003 + int(cid))
        y = rng.integers(0, 10, size=capacity)
        x = rng.normal(0, 1, size=(capacity, *feat)).astype(np.float32)
        x[..., 0, 0, 0] += y  # class signal in one coordinate
        xs.append(x.astype(np.float32))
        ys.append(y.astype(np.int32))
    mask = np.ones((len(xs), capacity), np.float32)
    return np.stack(xs), np.stack(ys), mask


def run_worker(args: argparse.Namespace) -> int:
    """One jax.distributed process: build the hosts-axis mesh, shard THIS
    host's client rows, run the round program, report through files."""
    t0 = time.time()
    import jax

    from nanofed_tpu.parallel import initialize_distributed

    if args.num_processes > 1:
        info = initialize_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    else:
        info = {"process_index": 0, "process_count": 1}

    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.core.types import ClientData
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        build_round_step,
        client_shard_count,
        host_client_slice,
        init_server_state,
        make_mesh,
        mesh_shape,
        pad_client_count,
        param_sharding,
        shard_host_local_data,
    )
    from nanofed_tpu.trainer import TrainingConfig, stack_rngs

    devices = jax.devices()
    pid = info["process_index"]

    def log(msg: str) -> None:
        print(f"[{time.time() - t0:6.1f}s p{pid}] {msg}", file=sys.stderr,
              flush=True)

    log(f"up: {len(devices)} global devices across "
        f"{info['process_count']} process(es)")

    if args.hosts > 1:
        shape = (args.hosts, len(devices) // args.hosts, 1)
    else:
        shape = None  # the 1-D reference mesh
    mesh = make_mesh(shape=shape)
    n_shards = client_shard_count(mesh)

    model = get_model(args.model)
    feat = tuple(model.input_shape)
    padded = pad_client_count(args.clients, n_shards)
    start, stop = host_client_slice(padded, mesh)
    log(f"mesh {mesh_shape(mesh)}: padded {padded} clients, "
        f"this process holds rows [{start}, {stop})")

    # Per-host data sharding: ONLY this process's rows ever materialize here.
    ids = np.arange(start, stop)
    x, y, mask = client_rows(ids, args.capacity, feat, args.seed)
    mask[ids >= args.clients] = 0.0  # padding rows carry zero weight
    local = ClientData(x=x, y=y, mask=mask)
    num_samples_local = mask.sum(axis=1)
    data = shard_host_local_data(local, mesh, padded)
    log(f"data resident: {x.nbytes / 1e6:.1f} MB/process on device")

    training = TrainingConfig(
        batch_size=args.batch_size, local_epochs=1, learning_rate=0.1
    )
    strategy = fedavg_strategy()
    params_host = model.init(jax.random.key(args.seed))
    sos_host = init_server_state(strategy, params_host)
    start_round = 0
    if args.job == "hostchaos" and args.resume:
        from nanofed_tpu.persistence import GenerationStore

        rec = GenerationStore(args.ckpt_dir).latest_complete()
        if rec is not None:
            # Newest generation committed by ALL its participants: the only
            # legal multi-host recovery point (at-most-one-block loss).
            params_host, sos_host = rec.params, rec.server_state
            start_round = rec.round_number
            log(f"resumed generation {rec.generation} at round {start_round} "
                f"(committed by hosts {list(rec.hosts)})")
        else:
            log("resume requested but no complete generation yet — fresh start")
    params = jax.device_put(params_host, param_sharding(mesh, params_host))
    sos = jax.device_put(sos_host, param_sharding(mesh, sos_host))
    step = build_round_step(
        model.apply, training, mesh, strategy,
        client_chunk=args.client_chunk, params_like=params,
        donate=True,
    )

    # Replicated round inputs (weights, per-round key stacks) are pure
    # functions of (client id, seed, round), so every process COMPUTES them as
    # a tiny jitted program with replicated out_shardings instead of shipping
    # host arrays — a committed process-local array cannot be device_put onto
    # a multi-process sharding, and nothing needs to move anyway.
    del num_samples_local  # identical info rides the computed weights below
    from functools import partial

    from nanofed_tpu.parallel import replicated_sharding

    repl = replicated_sharding(mesh)
    weights = jax.jit(
        lambda: compute_weights(jnp.where(
            jnp.arange(padded) < args.clients, float(args.capacity), 0.0
        )),
        out_shardings=repl,
    )()

    # r rides as a TRACED scalar (fold_in accepts one): one compile serves
    # every round — static_argnums here would recompile the key stack per r,
    # polluting the timed round walltimes.
    @partial(jax.jit, out_shardings=repl)
    def round_rngs(r):
        return stack_rngs(
            jax.random.fold_in(jax.random.key(args.seed), r), padded
        )

    if args.job == "hostchaos":
        return _hostchaos_rounds(
            args, info, log, mesh, step, params, sos, data, weights,
            round_rngs, start_round,
        )

    losses: list[float] = []
    round_times: list[float] = []
    for r in range(args.rounds + 1):  # +1: round 0 pays the compile (warm-up)
        rngs = round_rngs(r)
        t = time.perf_counter()
        res = step(params, sos, data, weights, rngs)
        params, sos = res.params, res.server_opt_state
        jax.block_until_ready(params)
        dt = time.perf_counter() - t
        loss = float(res.metrics["loss"])
        losses.append(loss)
        if r > 0:
            round_times.append(dt)
        log(f"round {r}: loss={loss:.5f} ({dt:.2f}s"
            + (", incl. compile)" if r == 0 else ")"))

    result = {
        "mode": args.job,
        "losses": losses,
        "round_times_s": [round(x, 4) for x in round_times],
        "topology": {
            "process_count": info["process_count"],
            "hosts": args.hosts,
            "devices": len(devices),
            "mesh_shape": list(mesh_shape(mesh)),
        },
    }
    if pid == 0 and args.out is not None:
        flat = np.concatenate([
            np.asarray(jax.device_get(leaf)).ravel()
            for leaf in jax.tree.leaves(params)
        ])
        np.save(args.out + ".params.npy", flat)
        Path(args.out).write_text(json.dumps(result, indent=2))
        log(f"wrote {args.out}")
    return 0


def _hostchaos_rounds(
    args: argparse.Namespace,
    info: dict,
    log,
    mesh,
    step,
    params,
    sos,
    data,
    weights,
    round_rngs,
    start_round: int,
) -> int:
    """The fault-tolerant worker round loop: chaos injection at the host
    boundary, heartbeats, a watchdog deadline around every dispatch, and
    generation checkpoints at block boundaries.  The jitted round program is
    byte-identical to the smoke/bench jobs — chaos and resilience live
    entirely on the host side of the dispatch."""
    import jax
    import numpy as np

    from nanofed_tpu.faults import ChaosSchedule, FaultPlan, HostChaosInjector
    from nanofed_tpu.parallel import (
        CollectiveWatchdog,
        Heartbeat,
        HostFailure,
        mesh_shape,
    )
    from nanofed_tpu.persistence import GenerationStore

    host = args.host_id
    hosts_list = [int(h) for h in args.hosts_list.split(",")]
    injector = None
    if args.fault_plan:
        injector = HostChaosInjector(
            ChaosSchedule(FaultPlan.load(args.fault_plan)), host=host
        )
    hb = Heartbeat(args.hb_dir, host)
    store = GenerationStore(args.ckpt_dir, host=host)
    watchdog = CollectiveWatchdog(args.watchdog_deadline)
    progress = Path(args.progress) if args.progress else None
    pid = info["process_index"]

    def dispatch(params, sos, rngs):
        res = step(params, sos, data, weights, rngs)
        # Block INSIDE the watchdog bracket: the hang a dead peer causes
        # lives in the collective the result depends on.
        jax.block_until_ready((res.params, res.server_opt_state, res.metrics))
        return res

    def commit(rounds_done: int, params, sos) -> None:
        gen = rounds_done // args.block_size
        p_host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
        s_host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), sos)
        store.commit(gen, rounds_done, p_host, s_host, hosts=hosts_list)
        hb.beat(round_number=rounds_done, generation=gen, status="committed")
        log(f"committed generation {gen} at round {rounds_done}")

    losses: list[float] = []
    executed: list[int] = []
    first_dispatch = True
    for r in range(start_round, args.rounds):
        if injector is not None:
            injector.maybe_fail(r)  # may os._exit (crash) or park (stall)
            delay = injector.dcn_delay_s(r)
            if delay:
                log(f"chaos: dcn_degrade {delay:.3f}s before round {r}")
                time.sleep(delay)
        else:
            delay = 0.0
        hb.beat(round_number=r, generation=r // args.block_size,
                status="dispatch")
        rngs = round_rngs(r)
        # The first dispatched round pays trace+compile; the deadline must
        # not misread a slow compile (or a planned-degraded DCN link) as a
        # dead peer.
        grace = delay + (args.compile_grace if first_dispatch else 0.0)
        try:
            res = watchdog.run(
                dispatch, params, sos, rngs,
                round_number=r, dcn_grace_s=grace,
                # Keep beating while blocked on the collective: a waiting
                # peer is alive — only the genuinely stalled host freezes.
                tick=lambda: hb.beat(
                    round_number=r, generation=r // args.block_size,
                    status="dispatch",
                ),
            )
        except HostFailure as exc:
            log(f"watchdog: {exc}")
            hb.beat(round_number=r, status="peer_failure")
            # os._exit, not sys.exit: the interpreter's atexit runs JAX's
            # distributed teardown, which BARRIERS on the very peer that just
            # failed — the clean exit would hang as hard as the collective.
            os._exit(PEER_FAILURE_RC)
        except Exception as exc:  # gloo/coordination error: a peer is gone
            log(f"dispatch failed (peer loss?): {type(exc).__name__}: {exc}")
            hb.beat(round_number=r, status="peer_failure")
            os._exit(PEER_FAILURE_RC)
        first_dispatch = False
        params, sos = res.params, res.server_opt_state
        loss = float(res.metrics["loss"])
        losses.append(loss)
        executed.append(r)
        hb.beat(round_number=r + 1, generation=(r + 1) // args.block_size,
                status="running")
        if progress is not None and pid == 0:
            with progress.open("a") as f:
                f.write(json.dumps(
                    {"round": r, "loss": loss, "wall_t": time.time()}
                ) + "\n")
        log(f"round {r}: loss={loss:.5f}")
        if (r + 1) % args.block_size == 0:
            commit(r + 1, params, sos)

    hb.beat(round_number=args.rounds, status="done")
    if pid == 0 and args.out is not None:
        Path(args.out).write_text(json.dumps({
            "mode": "hostchaos",
            "start_round": start_round,
            "rounds": executed,
            "losses": losses,
            "topology": {
                "process_count": info["process_count"],
                "hosts": args.hosts,
                "host_ids": hosts_list,
                "devices": len(jax.devices()),
                "mesh_shape": list(mesh_shape(mesh)),
            },
        }, indent=2))
        log(f"wrote {args.out}")
    return 0


def _spawn(args: argparse.Namespace, mode_args: list[str], out: str | None,
           hosts: int, num_processes: int, port: int) -> list[subprocess.Popen]:
    procs = []
    for pid in range(num_processes):
        cmd = [
            sys.executable, str(Path(__file__).resolve()), "worker",
            "--process-id", str(pid),
            "--num-processes", str(num_processes),
            "--coordinator", f"localhost:{port}",
            "--hosts", str(hosts),
            *mode_args,
        ]
        if out is not None and pid == 0:
            cmd += ["--out", out]
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, pid)))
    return procs


def _reap(procs: list[subprocess.Popen], grace_s: float = 5.0) -> None:
    """Terminate AND reap every still-running worker.  Kill-without-wait (the
    old failure path) leaves zombies holding the rendezvous port: the next
    parity run on the machine then dies in jax.distributed bring-up.  SIGTERM
    first (workers flush logs), SIGKILL after the grace, ``wait()`` always —
    no child of the launcher may outlive this call."""
    for q in procs:
        if q.poll() is None:
            q.terminate()
    deadline = time.time() + grace_s
    for q in procs:
        if q.poll() is not None:
            continue
        try:
            q.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            q.kill()
            q.wait()


def _wait(procs: list[subprocess.Popen], timeout_s: float) -> None:
    # Poll ALL workers, not procs[0] first: a fast crash in worker 1 while
    # worker 0 blocks in the jax.distributed rendezvous must surface as the
    # real non-zero exit code immediately, not as a full-timeout "timed out"
    # after the peer-less rendezvous finally expires.  Any failure path reaps
    # the survivors BEFORE raising: a failed parity run must not leave orphan
    # processes holding the rendezvous port.
    deadline = time.time() + timeout_s
    pending = list(procs)
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is None:
                continue
            if rc != 0:
                _reap(procs)
                raise SystemExit(f"worker exited rc={rc}")
            pending.remove(p)
        if pending:
            if time.time() > deadline:
                _reap(procs)
                raise SystemExit(f"worker timed out after {timeout_s:.0f}s")
            time.sleep(0.2)


def run_smoke(args: argparse.Namespace) -> int:
    """2-process hierarchical run vs single-process 1-D reference: the losses
    and final params must match to float tolerance — the trajectory-parity
    acceptance bar of the multi-host path."""
    import numpy as np

    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    mode_args = [
        "--job", "smoke", "--clients", str(args.clients),
        "--capacity", str(args.capacity), "--batch-size", str(args.batch_size),
        "--rounds", str(args.rounds), "--model", args.model,
        "--seed", str(args.seed),
        "--devices-per-process", str(args.devices_per_process),
    ]
    if args.client_chunk is not None:
        mode_args += ["--client-chunk", str(args.client_chunk)]

    multi_out = str(tmp / "multihost_smoke_multi.json")
    t0 = time.time()
    print(f"# spawning {args.num_processes}-process hierarchical run "
          f"(hosts={args.num_processes}, gloo CPU collectives)", flush=True)
    procs = _spawn(args, mode_args, multi_out, hosts=args.num_processes,
                   num_processes=args.num_processes, port=args.port)
    _wait(procs, args.timeout)

    # Single-process 1-D reference over the SAME global device count: one
    # worker, hosts=1, no jax.distributed — the classic flat-psum program.
    ref_out = str(tmp / "multihost_smoke_ref.json")
    print("# running single-process 1-D reference", flush=True)
    ref_args = argparse.Namespace(**vars(args))
    ref_args.devices_per_process = (
        args.devices_per_process * args.num_processes
    )
    procs = _spawn(ref_args, mode_args, ref_out, hosts=1,
                   num_processes=1, port=args.port + 1)
    _wait(procs, args.timeout)

    multi = json.loads(Path(multi_out).read_text())
    ref = json.loads(Path(ref_out).read_text())
    p_multi = np.load(multi_out + ".params.npy")
    p_ref = np.load(ref_out + ".params.npy")
    loss_delta = max(
        abs(a - b) for a, b in zip(multi["losses"], ref["losses"])
    )
    param_delta = float(np.abs(p_multi - p_ref).max())
    verdict = {
        "losses_multi": multi["losses"],
        "losses_ref": ref["losses"],
        "max_loss_delta": loss_delta,
        "max_param_delta": param_delta,
        "tolerance": SMOKE_TOL,
        "topology": multi["topology"],
        "walltime_s": round(time.time() - t0, 1),
    }
    print(json.dumps(verdict, indent=2))
    assert multi["topology"]["process_count"] == args.num_processes, multi
    assert loss_delta <= SMOKE_TOL, (
        f"trajectory diverged: max loss delta {loss_delta} > {SMOKE_TOL}"
    )
    assert param_delta <= SMOKE_TOL, (
        f"params diverged: max delta {param_delta} > {SMOKE_TOL}"
    )
    print("multihost-smoke OK: 2-process hierarchical aggregation == "
          "single-process 1-D mesh to float tolerance")
    return 0


def run_bench(args: argparse.Namespace) -> int:
    """The 100k+ streamed-clients artifact: chunked streaming x multi-process,
    rounds/sec + clients/sec, topology block, honest CPU basis."""
    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    mode_args = [
        "--job", "bench", "--clients", str(args.clients),
        "--capacity", str(args.capacity), "--batch-size", str(args.batch_size),
        "--rounds", str(args.rounds), "--model", args.model,
        "--seed", str(args.seed),
        "--devices-per-process", str(args.devices_per_process),
        "--client-chunk", str(args.client_chunk if args.client_chunk else 250),
    ]
    worker_out = str(tmp / "multihost_bench_worker.json")
    t0 = time.time()
    print(f"# spawning {args.num_processes}-process bench at "
          f"{args.clients} clients", flush=True)
    procs = _spawn(args, mode_args, worker_out, hosts=args.num_processes,
                   num_processes=args.num_processes, port=args.port)
    _wait(procs, args.timeout)

    worker = json.loads(Path(worker_out).read_text())
    times = worker["round_times_s"]
    median = sorted(times)[len(times) // 2]
    record = {
        "metric": "multihost_fedavg_round_walltime",
        "unit": "s",
        "value": median,
        "per_round_s": times,
        "rounds_per_sec": round(1.0 / median, 4),
        "clients_per_sec": round(args.clients / median, 1),
        "num_clients": args.clients,
        "samples_per_client": args.capacity,
        "client_chunk": args.client_chunk if args.client_chunk else 250,
        "model": args.model,
        "losses": worker["losses"],
        "topology": worker["topology"],
        "platform": "cpu",
        "basis": (
            "multi-process jax.distributed over loopback (gloo CPU "
            "collectives), virtual XLA host devices per process; measures the "
            "hierarchical round PROGRAM — chunked streaming, host-local psum "
            "+ one cross-host psum, multi-controller dispatch — at population "
            "scale on CPU, not TPU silicon. The reference flagship tops out "
            "at 1000 clients (BASELINE.md); this is the 100x population jump."
        ),
        "harness": "scripts/multihost_harness.py bench",
        "walltime_s": round(time.time() - t0, 1),
    }
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = out_dir / f"multihost_{stamp}_{args.clients // 1000}k.json"
    path.write_text(json.dumps(record, indent=2))
    print(json.dumps(record, indent=2))
    print(f"# artifact written to {path}")
    return 0


def _spawn_hostchaos(
    args: argparse.Namespace,
    host_ids: list[int],
    port: int,
    *,
    rounds: int,
    hb_dir: Path,
    ckpt_dir: Path,
    resume: bool,
    plan_path: Path | None,
    out: Path | None,
    progress: Path | None,
) -> list[subprocess.Popen]:
    """Spawn one hostchaos worker per LOGICAL host id.  Process ids renumber
    0..n-1 every phase (jax.distributed needs a dense range); logical host ids
    survive reshapes — they are what the fault plan targets, what heartbeats
    and commit markers are keyed by, and what lets a restarted host rejoin as
    itself."""
    procs = []
    n = len(host_ids)
    for pid, host in enumerate(host_ids):
        cmd = [
            sys.executable, str(Path(__file__).resolve()), "worker",
            "--job", "hostchaos",
            "--process-id", str(pid),
            "--num-processes", str(n),
            "--coordinator", f"localhost:{port}",
            "--hosts", str(n),
            "--clients", str(args.clients),
            "--capacity", str(args.capacity),
            "--batch-size", str(args.batch_size),
            "--rounds", str(rounds),
            "--model", args.model,
            "--seed", str(args.seed),
            "--devices-per-process", str(args.devices_per_process),
            "--block-size", str(args.block_size),
            "--watchdog-deadline", str(args.watchdog_deadline),
            "--compile-grace", str(args.compile_grace),
            "--host-id", str(host),
            "--hosts-list", ",".join(str(h) for h in host_ids),
            "--hb-dir", str(hb_dir),
            "--ckpt-dir", str(ckpt_dir),
        ]
        if args.client_chunk is not None:
            cmd += ["--client-chunk", str(args.client_chunk)]
        if resume:
            cmd += ["--resume"]
        if plan_path is not None:
            cmd += ["--fault-plan", str(plan_path)]
        if out is not None and pid == 0:
            cmd += ["--out", str(out)]
        if progress is not None and pid == 0:
            cmd += ["--progress", str(progress)]
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, pid)))
    return procs


def _read_progress(path: Path) -> list[dict]:
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # torn tail line from a killed writer
    return out


def _fresh_dir(path: Path) -> Path:
    if path.exists():
        shutil.rmtree(path)
    path.mkdir(parents=True)
    return path


def run_hostchaos(args: argparse.Namespace) -> int:
    """The kill-and-recover drill: seeded plan fails one of >=2 hosts
    mid-round; the supervisor detects it, reaps the survivors, re-forms the
    mesh over the surviving host set, resumes from the newest generation
    committed by all participants, optionally rejoins the failed host, and
    writes the ``runs/hostchaos_*.json`` evidence artifact (MTTR, rounds
    lost <= one block, post-recovery parity vs an unfailed shrunk-mesh run,
    zero orphans)."""
    from nanofed_tpu.faults.plan import FaultPlan
    from nanofed_tpu.observability.telemetry import RunTelemetry
    from nanofed_tpu.parallel.resilience import (
        HostMonitor,
        no_orphans,
        resilience_metrics,
    )
    from nanofed_tpu.persistence import GenerationStore

    if args.num_processes < 2:
        raise SystemExit("hostchaos needs --num-processes >= 2 (someone must "
                         "survive to recover)")
    P, R, B = args.num_processes, args.rounds, args.block_size
    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    hb_a = _fresh_dir(tmp / "hb_a")
    hb_c = _fresh_dir(tmp / "hb_c")
    hb_d = _fresh_dir(tmp / "hb_d")
    hb_e = _fresh_dir(tmp / "hb_e")
    ckpt = _fresh_dir(tmp / "ckpt")
    ref_ckpt = tmp / "ckpt_ref"
    if ref_ckpt.exists():
        shutil.rmtree(ref_ckpt)

    if args.plan:
        plan = FaultPlan.load(args.plan)
    else:
        plan = FaultPlan.generate(
            args.seed, [], R, hosts=P,
            host_crash_count=1 if args.host_fault == "crash" else 0,
            host_stall_count=1 if args.host_fault == "stall" else 0,
        )
    host_events = [e for e in plan.events
                   if e.kind in ("host_crash", "host_stall")]
    if not host_events:
        raise SystemExit("the hostchaos plan contains no host_crash/"
                         "host_stall event — nothing to drill")
    if len(host_events) > 1:
        # Phase C re-feeds the plan to the recovered mesh (surviving hosts'
        # remaining dcn events stay live), so a second terminal event would
        # kill a survivor mid-recovery with nobody supervising.  One terminal
        # fault per drill; run the harness again for the next one.
        raise SystemExit(
            f"the hostchaos drill handles ONE terminal host fault per run; "
            f"this plan has {len(host_events)} "
            f"({[e.to_dict() for e in host_events]}) — split it across runs"
        )
    max_dcn = max(
        (e.seconds for e in plan.events if e.kind == "dcn_degrade"),
        default=0.0,
    )
    if max_dcn >= args.watchdog_deadline:
        # The degraded host widens its OWN deadline by the injected delay,
        # but its peers cannot know the plan: their collectives absorb the
        # delay under the base deadline.  The documented contract is that a
        # degraded-but-alive link must NOT be misread as a dead peer — which
        # requires sizing the deadline above the worst planned delay.
        raise SystemExit(
            f"plan injects dcn_degrade of {max_dcn}s but "
            f"--watchdog-deadline is {args.watchdog_deadline}s: peers would "
            "misread the degraded link as a dead host — raise the deadline "
            "above the worst planned delay"
        )
    plan_path = tmp / "hostchaos_plan.json"
    plan.save(plan_path)

    metrics = resilience_metrics()
    if args.telemetry_dir is None:
        # Ours to wipe.  An OPERATOR-supplied dir is never rmtree'd — they may
        # point it at runs/ next to prior artifacts; records just append.
        telemetry_dir = _fresh_dir(tmp / "telemetry")
    else:
        telemetry_dir = Path(args.telemetry_dir)
        telemetry_dir.mkdir(parents=True, exist_ok=True)
    tel = RunTelemetry(telemetry_dir)
    all_pids: list[int] = []
    t0 = time.time()
    hosts = list(range(P))

    # ---- phase A: full mesh under the plan, run until the failure ----------
    print(f"# hostchaos: {P}-host mesh, plan: "
          + ", ".join(f"{e.kind}@r{e.round} host {e.host}"
                      for e in host_events), flush=True)
    progress_a = tmp / "progress_a.jsonl"
    progress_a.unlink(missing_ok=True)
    procs = _spawn_hostchaos(
        args, hosts, args.port, rounds=R, hb_dir=hb_a, ckpt_dir=ckpt,
        resume=False, plan_path=plan_path, out=tmp / "hc_a.json",
        progress=progress_a,
    )
    all_pids += [p.pid for p in procs]
    monitor = HostMonitor(hb_a, stall_timeout_s=args.stall_timeout)

    def _hb_status(host: int) -> str:
        try:
            return str(json.loads(
                (hb_a / f"host_{host}.hb.json").read_text()
            ).get("status", "?"))
        except (OSError, json.JSONDecodeError, ValueError):
            return "?"

    victim: int | None = None
    kind: str | None = None
    deadline = time.time() + args.timeout
    exits: dict[int, int] = {}
    exit_order: list[int] = []  # indices in the order their exits were seen
    while victim is None:
        for i, p in enumerate(procs):
            rc = p.poll()
            if rc is not None and i not in exits:
                exits[i] = rc
                exit_order.append(i)
                if rc == HOST_CRASH_RC:
                    victim, kind = hosts[i], "host_crash"
                    metrics["host_failures"].inc(kind=kind)
        if victim is None:
            stalled = monitor.stalled()
            if stalled:
                victim, kind = stalled[0].host, "host_stall"
        if victim is None and any(
            rc == PEER_FAILURE_RC for rc in exits.values()
        ):
            # At least one worker exited BLAMING a peer (watchdog / gloo
            # error).  A blaming worker is never the victim; neither is one
            # whose last heartbeat declared peer_failure (it may have been
            # killed mid-exit).  Once exactly one blameless worker remains —
            # still alive (a true stall) or collaterally killed when the
            # coordination service's leader went down — it is the victim.
            blaming = {
                i for i in range(len(procs))
                if exits.get(i) == PEER_FAILURE_RC
                or _hb_status(hosts[i]) == "peer_failure"
            }
            candidates = [i for i in range(len(procs)) if i not in blaming]
            all_blamers_exited = all(
                i in exits for i in range(len(procs)) if i in blaming
            )
            if len(candidates) == 1 and all_blamers_exited:
                i = candidates[0]
                victim = hosts[i]
                # Died BEFORE the first blame → it crashed on its own; died
                # after (or still silently alive) → the stall the blamers
                # timed out on.
                first_blame_pos = min(
                    exit_order.index(j) for j in blaming if j in exits
                ) if any(j in exits for j in blaming) else len(exit_order)
                died_first = (
                    i in exits and exit_order.index(i) < first_blame_pos
                )
                kind = "host_crash" if died_first else "host_stall"
                metrics["host_failures"].inc(kind=kind)
        if victim is None and len(exits) == len(procs):
            if all(rc == 0 for rc in exits.values()):
                _reap(procs)
                raise SystemExit(
                    "hostchaos: every worker completed without the planned "
                    "failure firing — raise --rounds or fix the plan"
                )
            # Every process exited.  Attribute only to a worker that failed
            # on its OWN account (non-zero, non-blaming): if every exit
            # blames a peer, the failure is systemic (e.g. a round-0 gloo
            # bring-up error hit everyone) and naming a victim would fabricate
            # a host_crash, exclude a healthy host, and mask the real cause.
            organic = [
                i for i in exit_order
                if exits[i] not in (0, PEER_FAILURE_RC)
            ]
            if not organic:
                _reap(procs)
                raise SystemExit(
                    f"hostchaos: every worker exited blaming a peer "
                    f"(exit codes {dict(sorted(exits.items()))}) — systemic "
                    "failure, no victim attributable; check the worker logs"
                )
            victim = hosts[organic[0]]
            kind = "host_crash"
            metrics["host_failures"].inc(kind=kind)
        if victim is None and time.time() > deadline:
            _reap(procs)
            raise SystemExit(f"hostchaos: no failure detected within "
                             f"{args.timeout:.0f}s")
        if victim is None:
            time.sleep(0.2)
    t_detect = time.time()
    victim_hb = hb_a / f"host_{victim}.hb.json"
    last_beat_wall = None
    victim_round = None
    try:
        payload = json.loads(victim_hb.read_text())
        last_beat_wall = float(payload.get("wall_t", 0)) or None
        victim_round = payload.get("round")
    except (OSError, json.JSONDecodeError, ValueError):
        pass
    detection_s = (
        round(t_detect - last_beat_wall, 3) if last_beat_wall else None
    )
    # Kill and REAP everyone — survivors included: the old mesh is dead, and
    # an orphan blocked in gloo would hold the rendezvous port forever.
    # (Every detection path above already counted the failure by kind.)
    _reap(procs)
    plan_round = next(
        (e.round for e in host_events if e.host == victim), victim_round
    )
    fail_round = plan_round if plan_round is not None else 0
    print(f"# failure detected: {kind} on host {victim} (round {fail_round}, "
          f"detection {detection_s}s) — reaped {len(procs)} workers",
          flush=True)
    tel.record(
        "host_failure", kind=kind, host=victim, round=fail_round,
        detection_s=detection_s,
        detail=f"exit codes {exits}" if exits else "heartbeat frozen",
    )

    # Reference snapshot BEFORE the recovered run extends the store: the
    # unfailed shrunk-mesh run must start from the identical recovery point.
    shutil.copytree(ckpt, ref_ckpt)
    rec = GenerationStore(ckpt).latest_complete()
    resumed_round = rec.round_number if rec is not None else 0
    resumed_gen = rec.generation if rec is not None else None
    rounds_lost = fail_round - resumed_round
    print(f"# recovery point: generation {resumed_gen} (round "
          f"{resumed_round}); rounds lost = {rounds_lost} (block size {B})",
          flush=True)

    # ---- phase C: re-form over the survivors, resume, finish the run -------
    survivors = [h for h in hosts if h != victim]
    metrics["mesh_reshapes"].inc()
    progress_c = tmp / "progress_c.jsonl"
    progress_c.unlink(missing_ok=True)
    procs = _spawn_hostchaos(
        args, survivors, args.port + 7, rounds=R, hb_dir=hb_c, ckpt_dir=ckpt,
        resume=True, plan_path=plan_path, out=tmp / "hc_c.json",
        progress=progress_c,
    )
    all_pids += [p.pid for p in procs]
    _wait(procs, args.timeout)
    recovered = json.loads((tmp / "hc_c.json").read_text())
    prog_c = _read_progress(progress_c)
    if not prog_c:
        raise SystemExit("hostchaos: recovered run reported no rounds")
    mttr_s = round(prog_c[0]["wall_t"] - t_detect, 3)
    metrics["recovery_seconds"].observe(mttr_s)
    print(f"# mesh re-formed over hosts {survivors}: first post-recovery "
          f"round done {mttr_s}s after detection (MTTR)", flush=True)
    tel.record(
        "recovery", recovery_s=mttr_s, resumed_generation=resumed_gen,
        resumed_round=resumed_round, rounds_lost=rounds_lost,
        hosts_before=P, hosts_after=len(survivors), reshape=True,
        rejoin=False,
    )

    # ---- phase D (optional): the failed host rejoins at a generation
    # boundary, mesh re-grows to the full host set --------------------------
    rejoin_block = None
    if args.rejoin_rounds > 0:
        metrics["mesh_reshapes"].inc()
        total = R + args.rejoin_rounds
        procs = _spawn_hostchaos(
            args, hosts, args.port + 13, rounds=total, hb_dir=hb_d,
            ckpt_dir=ckpt, resume=True, plan_path=None,
            out=tmp / "hc_d.json", progress=tmp / "progress_d.jsonl",
        )
        all_pids += [p.pid for p in procs]
        _wait(procs, args.timeout)
        rejoined = json.loads((tmp / "hc_d.json").read_text())
        rejoin_block = {
            "hosts": hosts,
            "resumed_round": rejoined["start_round"],
            "rounds": rejoined["rounds"],
            "losses": rejoined["losses"],
        }
        assert rejoined["rounds"] and rejoined["rounds"][-1] == total - 1, (
            f"rejoined mesh did not reach round {total - 1}: {rejoined}"
        )
        print(f"# host {victim} rejoined at round {rejoined['start_round']}: "
              f"full {P}-host mesh ran to round {total - 1}", flush=True)
        tel.record(
            "recovery", resumed_generation=rejoined["start_round"] // B,
            resumed_round=rejoined["start_round"], rounds_lost=0,
            hosts_before=len(survivors), hosts_after=P, reshape=True,
            rejoin=True,
        )

    # ---- phase E: the parity reference — an UNFAILED run on the same
    # shrunk mesh from the same recovery point ------------------------------
    procs = _spawn_hostchaos(
        args, survivors, args.port + 19, rounds=R, hb_dir=hb_e,
        ckpt_dir=ref_ckpt, resume=True, plan_path=None,
        out=tmp / "hc_e.json", progress=None,
    )
    all_pids += [p.pid for p in procs]
    _wait(procs, args.timeout)
    reference = json.loads((tmp / "hc_e.json").read_text())

    loss_delta = max(
        (abs(a - b) for a, b in
         zip(recovered["losses"], reference["losses"])),
        default=float("inf"),
    )
    orphans = no_orphans(all_pids)
    artifact = {
        "record_type": "hostchaos",
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "seed": args.seed,
        "plan": json.loads(plan.to_json()),
        "rounds": R,
        "block_size": B,
        "clients": args.clients,
        "model": args.model,
        "topology": {
            "hosts_before": P,
            "hosts_after": len(survivors),
            "devices_per_process": args.devices_per_process,
            "mesh_before": [P, args.devices_per_process, 1],
            "mesh_after": [len(survivors), args.devices_per_process, 1],
        },
        "failure": {
            "kind": kind,
            "host": victim,
            "round": fail_round,
            "detection_s": detection_s,
            "stall_timeout_s": args.stall_timeout,
            "watchdog_deadline_s": args.watchdog_deadline,
            "worker_exit_codes": {str(hosts[i]): rc
                                  for i, rc in sorted(exits.items())},
        },
        "recovery": {
            "mttr_s": mttr_s,
            "resumed_generation": resumed_gen,
            "resumed_round": resumed_round,
            "rounds_lost": rounds_lost,
            "at_most_one_block": rounds_lost <= B,
        },
        "pre_failure_losses": [p["loss"] for p in _read_progress(progress_a)],
        "recovered": {
            "rounds": recovered["rounds"], "losses": recovered["losses"],
        },
        "reference_unfailed_shrunk": {
            "rounds": reference["rounds"], "losses": reference["losses"],
        },
        "parity": {
            "max_loss_delta": loss_delta,
            "tolerance": args.parity_tol,
            "ok": loss_delta <= args.parity_tol,
        },
        "rejoin": rejoin_block,
        "orphans": orphans,
        "platform": "cpu",
        "basis": (
            "multi-process jax.distributed over loopback (gloo CPU "
            "collectives), virtual XLA host devices per process; the drill "
            "measures the RECOVERY MACHINERY — detection, reap, mesh "
            "re-formation, generation resume — not TPU silicon.  MTTR "
            "includes process respawn + jax bring-up + recompile on the "
            "shrunk mesh."
        ),
        "harness": "scripts/multihost_harness.py hostchaos",
        "walltime_s": round(time.time() - t0, 1),
    }
    tel.close()

    assert rounds_lost <= B, (
        f"at-most-one-block violated: lost {rounds_lost} rounds > block {B}"
    )
    assert loss_delta <= args.parity_tol, (
        f"post-recovery trajectory diverged from the unfailed shrunk-mesh "
        f"run: max loss delta {loss_delta} > {args.parity_tol}"
    )
    assert not orphans, f"orphan worker processes survived the run: {orphans}"
    assert recovered["rounds"][-1] == R - 1, recovered

    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = out_dir / f"hostchaos_{stamp}_{P}h.json"
    path.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact, indent=2))
    print(f"# artifact written to {path}")
    print(f"# telemetry: {telemetry_dir} (digest: python -m nanofed_tpu.cli "
          f"metrics-summary {telemetry_dir})")
    print(f"hostchaos OK: {kind} on host {victim} at round {fail_round} -> "
          f"recovered on {len(survivors)} host(s) in {mttr_s}s, "
          f"{rounds_lost} round(s) re-run (<= {B}), parity delta "
          f"{loss_delta:.2e}, zero orphans")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "mode", choices=["smoke", "bench", "hostchaos", "worker"],
        help="smoke: 2-process parity vs 1-D reference; bench: 100k-client "
        "throughput artifact; hostchaos: seeded kill-and-recover drill with "
        "elastic mesh re-formation; worker: internal (one jax.distributed "
        "process)",
    )
    parser.add_argument("--clients", type=int, default=None)
    parser.add_argument("--capacity", type=int, default=8,
                        help="packed samples per client")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=3,
                        help="timed rounds (one extra warm-up round compiles)")
    parser.add_argument("--model", default="digits_mlp")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--client-chunk", type=int, default=None)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--devices-per-process", type=int, default=4)
    parser.add_argument("--hosts", type=int, default=1,
                        help="(worker) hosts-axis size of the mesh")
    parser.add_argument("--process-id", type=int, default=0)
    parser.add_argument("--coordinator", default="localhost:12421")
    parser.add_argument("--port", type=int, default=12421)
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="per-phase worker timeout (tier-1-safe)")
    parser.add_argument("--job", choices=["smoke", "bench", "hostchaos"],
                        default="smoke",
                        help="(worker) which launcher job this worker serves "
                        "— a FULL flag name: an abbreviated --mod* would "
                        "prefix-match argparse's --model and corrupt it")
    parser.add_argument("--out", default=None, help="(worker) result JSON path")
    parser.add_argument("--out-dir", default="runs")
    parser.add_argument("--tmp-dir", default="/tmp/nanofed_multihost")
    # hostchaos: supervisor knobs (fault selection, detection windows, parity)
    parser.add_argument("--plan", default=None,
                        help="(hostchaos) fault-plan JSON; default: generate "
                        "one host fault from --seed")
    parser.add_argument("--host-fault", choices=["crash", "stall"],
                        default="crash",
                        help="(hostchaos) which host fault the generated plan "
                        "draws")
    parser.add_argument("--block-size", type=int, default=2,
                        help="rounds per checkpoint generation (the at-most-"
                        "one-block loss unit)")
    parser.add_argument("--stall-timeout", type=float, default=15.0,
                        help="(hostchaos) heartbeat age that flags a host as "
                        "stalled")
    parser.add_argument("--watchdog-deadline", type=float, default=20.0,
                        help="cross-host dispatch deadline (the bounded "
                        "detection window for a dead/stalled peer)")
    parser.add_argument("--compile-grace", type=float, default=90.0,
                        help="extra watchdog allowance for the first dispatch "
                        "(trace+compile must not read as a dead peer)")
    parser.add_argument("--parity-tol", type=float, default=SMOKE_TOL,
                        help="(hostchaos) max post-recovery loss delta vs the "
                        "unfailed shrunk-mesh reference")
    parser.add_argument("--rejoin-rounds", type=int, default=2,
                        help="(hostchaos) extra rounds after the failed host "
                        "rejoins the mesh (0 disables the rejoin phase)")
    parser.add_argument("--telemetry-dir", default=None,
                        help="(hostchaos) where the supervisor writes "
                        "telemetry.jsonl (default: <tmp-dir>/telemetry)")
    # hostchaos: worker-side identity + wiring (set by the supervisor)
    parser.add_argument("--fault-plan", default=None,
                        help="(worker) fault-plan JSON path")
    parser.add_argument("--host-id", type=int, default=0,
                        help="(worker) LOGICAL host id — stable across "
                        "reshapes, unlike the dense process id")
    parser.add_argument("--hosts-list", default="0",
                        help="(worker) comma-separated logical host ids of "
                        "the current mesh (the commit-marker participant set)")
    parser.add_argument("--hb-dir", default="/tmp/nanofed_multihost/hb")
    parser.add_argument("--ckpt-dir", default="/tmp/nanofed_multihost/ckpt")
    parser.add_argument("--progress", default=None,
                        help="(worker) per-round progress JSONL path")
    parser.add_argument("--resume", action="store_true",
                        help="(worker) resume from the newest complete "
                        "generation in --ckpt-dir")
    args = parser.parse_args(argv)

    if args.clients is None:
        args.clients = 100_000 if args.mode == "bench" else 16
    if args.mode == "worker":
        return run_worker(args)
    if args.mode == "smoke":
        return run_smoke(args)
    if args.mode == "hostchaos":
        return run_hostchaos(args)
    return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
