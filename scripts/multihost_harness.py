"""Multi-process pod-scale federation harness (ROADMAP item 1).

One file, two jobs, both driven by REAL ``jax.distributed`` processes on the
CPU backend (gloo collectives — see ``parallel.mesh.initialize_distributed``),
so the whole hosts-axis path is testable without a pod:

* ``smoke`` (``make multihost-smoke``, the non-blocking CI job): a 2-process
  run of the HIERARCHICAL 3-axis round program — per-host data sharding via
  :func:`~nanofed_tpu.parallel.shard_host_local_data` (no process ever holds
  the full population), host-local ``psum`` over ``clients`` then ONE
  cross-host ``psum`` over ``hosts`` — asserted for trajectory parity
  (per-round losses AND final params, float tolerance) against a
  single-process 1-D mesh over the same virtual device count running the
  byte-identical workload.

* ``bench``: the scale jump — ``--clients 100000`` (default) streamed through
  ``client_chunk`` chunking x multi-process, producing a
  ``runs/multihost_*.json`` artifact with rounds/sec and clients/sec plus the
  topology block (``process_count``/``hosts``/``mesh_shape``) the BENCH
  conventions require.  The basis is stated honestly: virtual CPU devices and
  gloo-over-loopback measure the PROGRAM (hierarchical collectives, chunked
  streaming, multi-controller dispatch) at population scale, not TPU silicon.

Launcher (default entry) spawns the worker processes of itself; workers rendez-
vous through ``jax.distributed`` on a loopback coordinator.  Every knob rides
argv so the launcher and workers cannot drift.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

SMOKE_TOL = 5e-5  # hierarchical vs flat psum: re-association only (~1e-7 seen)


def _worker_env(args: argparse.Namespace, process_id: int) -> dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices_per_process}"
    )
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    env["NANOFED_MH_PROCESS_ID"] = str(process_id)
    return env


def client_rows(client_ids, capacity: int, feat: tuple[int, ...], seed: int):
    """Deterministic synthetic data for a RANGE of global client ids — the same
    rows regardless of which process (or how many) materializes them, which is
    what makes the multi-process run byte-comparable to the single-process
    reference.  Linearly-separable-ish classes so a few rounds visibly learn."""
    import numpy as np

    xs, ys = [], []
    for cid in client_ids:
        rng = np.random.default_rng(seed * 1_000_003 + int(cid))
        y = rng.integers(0, 10, size=capacity)
        x = rng.normal(0, 1, size=(capacity, *feat)).astype(np.float32)
        x[..., 0, 0, 0] += y  # class signal in one coordinate
        xs.append(x.astype(np.float32))
        ys.append(y.astype(np.int32))
    mask = np.ones((len(xs), capacity), np.float32)
    return np.stack(xs), np.stack(ys), mask


def run_worker(args: argparse.Namespace) -> int:
    """One jax.distributed process: build the hosts-axis mesh, shard THIS
    host's client rows, run the round program, report through files."""
    t0 = time.time()
    import jax

    from nanofed_tpu.parallel import initialize_distributed

    if args.num_processes > 1:
        info = initialize_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    else:
        info = {"process_index": 0, "process_count": 1}

    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.core.types import ClientData
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        build_round_step,
        client_shard_count,
        host_client_slice,
        init_server_state,
        make_mesh,
        mesh_shape,
        pad_client_count,
        param_sharding,
        shard_host_local_data,
    )
    from nanofed_tpu.trainer import TrainingConfig, stack_rngs

    devices = jax.devices()
    pid = info["process_index"]

    def log(msg: str) -> None:
        print(f"[{time.time() - t0:6.1f}s p{pid}] {msg}", file=sys.stderr,
              flush=True)

    log(f"up: {len(devices)} global devices across "
        f"{info['process_count']} process(es)")

    if args.hosts > 1:
        shape = (args.hosts, len(devices) // args.hosts, 1)
    else:
        shape = None  # the 1-D reference mesh
    mesh = make_mesh(shape=shape)
    n_shards = client_shard_count(mesh)

    model = get_model(args.model)
    feat = tuple(model.input_shape)
    padded = pad_client_count(args.clients, n_shards)
    start, stop = host_client_slice(padded, mesh)
    log(f"mesh {mesh_shape(mesh)}: padded {padded} clients, "
        f"this process holds rows [{start}, {stop})")

    # Per-host data sharding: ONLY this process's rows ever materialize here.
    ids = np.arange(start, stop)
    x, y, mask = client_rows(ids, args.capacity, feat, args.seed)
    mask[ids >= args.clients] = 0.0  # padding rows carry zero weight
    local = ClientData(x=x, y=y, mask=mask)
    num_samples_local = mask.sum(axis=1)
    data = shard_host_local_data(local, mesh, padded)
    log(f"data resident: {x.nbytes / 1e6:.1f} MB/process on device")

    training = TrainingConfig(
        batch_size=args.batch_size, local_epochs=1, learning_rate=0.1
    )
    strategy = fedavg_strategy()
    params_host = model.init(jax.random.key(args.seed))
    params = jax.device_put(params_host, param_sharding(mesh, params_host))
    sos = jax.device_put(
        init_server_state(strategy, params_host),
        param_sharding(mesh, init_server_state(strategy, params_host)),
    )
    step = build_round_step(
        model.apply, training, mesh, strategy,
        client_chunk=args.client_chunk, params_like=params,
        donate=True,
    )

    # Replicated round inputs (weights, per-round key stacks) are pure
    # functions of (client id, seed, round), so every process COMPUTES them as
    # a tiny jitted program with replicated out_shardings instead of shipping
    # host arrays — a committed process-local array cannot be device_put onto
    # a multi-process sharding, and nothing needs to move anyway.
    del num_samples_local  # identical info rides the computed weights below
    from functools import partial

    from nanofed_tpu.parallel import replicated_sharding

    repl = replicated_sharding(mesh)
    weights = jax.jit(
        lambda: compute_weights(jnp.where(
            jnp.arange(padded) < args.clients, float(args.capacity), 0.0
        )),
        out_shardings=repl,
    )()

    # r rides as a TRACED scalar (fold_in accepts one): one compile serves
    # every round — static_argnums here would recompile the key stack per r,
    # polluting the timed round walltimes.
    @partial(jax.jit, out_shardings=repl)
    def round_rngs(r):
        return stack_rngs(
            jax.random.fold_in(jax.random.key(args.seed), r), padded
        )

    losses: list[float] = []
    round_times: list[float] = []
    for r in range(args.rounds + 1):  # +1: round 0 pays the compile (warm-up)
        rngs = round_rngs(r)
        t = time.perf_counter()
        res = step(params, sos, data, weights, rngs)
        params, sos = res.params, res.server_opt_state
        jax.block_until_ready(params)
        dt = time.perf_counter() - t
        loss = float(res.metrics["loss"])
        losses.append(loss)
        if r > 0:
            round_times.append(dt)
        log(f"round {r}: loss={loss:.5f} ({dt:.2f}s"
            + (", incl. compile)" if r == 0 else ")"))

    result = {
        "mode": args.job,
        "losses": losses,
        "round_times_s": [round(x, 4) for x in round_times],
        "topology": {
            "process_count": info["process_count"],
            "hosts": args.hosts,
            "devices": len(devices),
            "mesh_shape": list(mesh_shape(mesh)),
        },
    }
    if pid == 0 and args.out is not None:
        flat = np.concatenate([
            np.asarray(jax.device_get(leaf)).ravel()
            for leaf in jax.tree.leaves(params)
        ])
        np.save(args.out + ".params.npy", flat)
        Path(args.out).write_text(json.dumps(result, indent=2))
        log(f"wrote {args.out}")
    return 0


def _spawn(args: argparse.Namespace, mode_args: list[str], out: str | None,
           hosts: int, num_processes: int, port: int) -> list[subprocess.Popen]:
    procs = []
    for pid in range(num_processes):
        cmd = [
            sys.executable, str(Path(__file__).resolve()), "worker",
            "--process-id", str(pid),
            "--num-processes", str(num_processes),
            "--coordinator", f"localhost:{port}",
            "--hosts", str(hosts),
            *mode_args,
        ]
        if out is not None and pid == 0:
            cmd += ["--out", out]
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, pid)))
    return procs


def _wait(procs: list[subprocess.Popen], timeout_s: float) -> None:
    # Poll ALL workers, not procs[0] first: a fast crash in worker 1 while
    # worker 0 blocks in the jax.distributed rendezvous must surface as the
    # real non-zero exit code immediately, not as a full-timeout "timed out"
    # after the peer-less rendezvous finally expires.
    deadline = time.time() + timeout_s
    pending = list(procs)
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is None:
                continue
            if rc != 0:
                for q in procs:
                    q.kill()
                raise SystemExit(f"worker exited rc={rc}")
            pending.remove(p)
        if pending:
            if time.time() > deadline:
                for q in procs:
                    q.kill()
                raise SystemExit(f"worker timed out after {timeout_s:.0f}s")
            time.sleep(0.2)


def run_smoke(args: argparse.Namespace) -> int:
    """2-process hierarchical run vs single-process 1-D reference: the losses
    and final params must match to float tolerance — the trajectory-parity
    acceptance bar of the multi-host path."""
    import numpy as np

    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    mode_args = [
        "--job", "smoke", "--clients", str(args.clients),
        "--capacity", str(args.capacity), "--batch-size", str(args.batch_size),
        "--rounds", str(args.rounds), "--model", args.model,
        "--seed", str(args.seed),
        "--devices-per-process", str(args.devices_per_process),
    ]
    if args.client_chunk is not None:
        mode_args += ["--client-chunk", str(args.client_chunk)]

    multi_out = str(tmp / "multihost_smoke_multi.json")
    t0 = time.time()
    print(f"# spawning {args.num_processes}-process hierarchical run "
          f"(hosts={args.num_processes}, gloo CPU collectives)", flush=True)
    procs = _spawn(args, mode_args, multi_out, hosts=args.num_processes,
                   num_processes=args.num_processes, port=args.port)
    _wait(procs, args.timeout)

    # Single-process 1-D reference over the SAME global device count: one
    # worker, hosts=1, no jax.distributed — the classic flat-psum program.
    ref_out = str(tmp / "multihost_smoke_ref.json")
    print("# running single-process 1-D reference", flush=True)
    ref_args = argparse.Namespace(**vars(args))
    ref_args.devices_per_process = (
        args.devices_per_process * args.num_processes
    )
    procs = _spawn(ref_args, mode_args, ref_out, hosts=1,
                   num_processes=1, port=args.port + 1)
    _wait(procs, args.timeout)

    multi = json.loads(Path(multi_out).read_text())
    ref = json.loads(Path(ref_out).read_text())
    p_multi = np.load(multi_out + ".params.npy")
    p_ref = np.load(ref_out + ".params.npy")
    loss_delta = max(
        abs(a - b) for a, b in zip(multi["losses"], ref["losses"])
    )
    param_delta = float(np.abs(p_multi - p_ref).max())
    verdict = {
        "losses_multi": multi["losses"],
        "losses_ref": ref["losses"],
        "max_loss_delta": loss_delta,
        "max_param_delta": param_delta,
        "tolerance": SMOKE_TOL,
        "topology": multi["topology"],
        "walltime_s": round(time.time() - t0, 1),
    }
    print(json.dumps(verdict, indent=2))
    assert multi["topology"]["process_count"] == args.num_processes, multi
    assert loss_delta <= SMOKE_TOL, (
        f"trajectory diverged: max loss delta {loss_delta} > {SMOKE_TOL}"
    )
    assert param_delta <= SMOKE_TOL, (
        f"params diverged: max delta {param_delta} > {SMOKE_TOL}"
    )
    print("multihost-smoke OK: 2-process hierarchical aggregation == "
          "single-process 1-D mesh to float tolerance")
    return 0


def run_bench(args: argparse.Namespace) -> int:
    """The 100k+ streamed-clients artifact: chunked streaming x multi-process,
    rounds/sec + clients/sec, topology block, honest CPU basis."""
    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    mode_args = [
        "--job", "bench", "--clients", str(args.clients),
        "--capacity", str(args.capacity), "--batch-size", str(args.batch_size),
        "--rounds", str(args.rounds), "--model", args.model,
        "--seed", str(args.seed),
        "--devices-per-process", str(args.devices_per_process),
        "--client-chunk", str(args.client_chunk if args.client_chunk else 250),
    ]
    worker_out = str(tmp / "multihost_bench_worker.json")
    t0 = time.time()
    print(f"# spawning {args.num_processes}-process bench at "
          f"{args.clients} clients", flush=True)
    procs = _spawn(args, mode_args, worker_out, hosts=args.num_processes,
                   num_processes=args.num_processes, port=args.port)
    _wait(procs, args.timeout)

    worker = json.loads(Path(worker_out).read_text())
    times = worker["round_times_s"]
    median = sorted(times)[len(times) // 2]
    record = {
        "metric": "multihost_fedavg_round_walltime",
        "unit": "s",
        "value": median,
        "per_round_s": times,
        "rounds_per_sec": round(1.0 / median, 4),
        "clients_per_sec": round(args.clients / median, 1),
        "num_clients": args.clients,
        "samples_per_client": args.capacity,
        "client_chunk": args.client_chunk if args.client_chunk else 250,
        "model": args.model,
        "losses": worker["losses"],
        "topology": worker["topology"],
        "platform": "cpu",
        "basis": (
            "multi-process jax.distributed over loopback (gloo CPU "
            "collectives), virtual XLA host devices per process; measures the "
            "hierarchical round PROGRAM — chunked streaming, host-local psum "
            "+ one cross-host psum, multi-controller dispatch — at population "
            "scale on CPU, not TPU silicon. The reference flagship tops out "
            "at 1000 clients (BASELINE.md); this is the 100x population jump."
        ),
        "harness": "scripts/multihost_harness.py bench",
        "walltime_s": round(time.time() - t0, 1),
    }
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = out_dir / f"multihost_{stamp}_{args.clients // 1000}k.json"
    path.write_text(json.dumps(record, indent=2))
    print(json.dumps(record, indent=2))
    print(f"# artifact written to {path}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "mode", choices=["smoke", "bench", "worker"],
        help="smoke: 2-process parity vs 1-D reference; bench: 100k-client "
        "throughput artifact; worker: internal (one jax.distributed process)",
    )
    parser.add_argument("--clients", type=int, default=None)
    parser.add_argument("--capacity", type=int, default=8,
                        help="packed samples per client")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=3,
                        help="timed rounds (one extra warm-up round compiles)")
    parser.add_argument("--model", default="digits_mlp")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--client-chunk", type=int, default=None)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--devices-per-process", type=int, default=4)
    parser.add_argument("--hosts", type=int, default=1,
                        help="(worker) hosts-axis size of the mesh")
    parser.add_argument("--process-id", type=int, default=0)
    parser.add_argument("--coordinator", default="localhost:12421")
    parser.add_argument("--port", type=int, default=12421)
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="per-phase worker timeout (tier-1-safe)")
    parser.add_argument("--job", choices=["smoke", "bench"], default="smoke",
                        help="(worker) which launcher job this worker serves "
                        "— a FULL flag name: an abbreviated --mod* would "
                        "prefix-match argparse's --model and corrupt it")
    parser.add_argument("--out", default=None, help="(worker) result JSON path")
    parser.add_argument("--out-dir", default="runs")
    parser.add_argument("--tmp-dir", default="/tmp/nanofed_multihost")
    args = parser.parse_args(argv)

    if args.clients is None:
        args.clients = 16 if args.mode == "smoke" else 100_000
    if args.mode == "worker":
        return run_worker(args)
    if args.mode == "smoke":
        return run_smoke(args)
    return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
