"""Multi-process pod-scale federation harness (ROADMAP item 1).

One file, two jobs, both driven by REAL ``jax.distributed`` processes on the
CPU backend (gloo collectives — see ``parallel.mesh.initialize_distributed``),
so the whole hosts-axis path is testable without a pod:

* ``smoke`` (``make multihost-smoke``, the non-blocking CI job): a 2-process
  run of the HIERARCHICAL 3-axis round program — per-host data sharding via
  :func:`~nanofed_tpu.parallel.shard_host_local_data` (no process ever holds
  the full population), host-local ``psum`` over ``clients`` then ONE
  cross-host ``psum`` over ``hosts`` — asserted for trajectory parity
  (per-round losses AND final params, float tolerance) against a
  single-process 1-D mesh over the same virtual device count running the
  byte-identical workload.

* ``bench``: the scale jump — ``--clients 100000`` (default) streamed through
  ``client_chunk`` chunking x multi-process, producing a
  ``runs/multihost_*.json`` artifact with rounds/sec and clients/sec plus the
  topology block (``process_count``/``hosts``/``mesh_shape``) the BENCH
  conventions require.  The basis is stated honestly: virtual CPU devices and
  gloo-over-loopback measure the PROGRAM (hierarchical collectives, chunked
  streaming, multi-controller dispatch) at population scale, not TPU silicon.

* ``hostchaos`` (``make hostchaos-smoke``): the host fault-tolerance drill.
  A SUPERVISOR spawns the worker mesh under a seeded fault plan
  (``host_crash``/``host_stall``/``dcn_degrade`` — ``nanofed_tpu.faults``),
  the workers heartbeat (``parallel.resilience.Heartbeat``), bracket every
  cross-host dispatch with a ``CollectiveWatchdog`` deadline, and checkpoint
  at block boundaries under generation numbers with commit markers
  (``persistence.GenerationStore``).  When the plan kills or stalls a host,
  the supervisor detects it (process exit / frozen heartbeat), kills and
  REAPS every survivor, re-forms the mesh over the surviving host set (the
  shrunk hosts axis, cohort quotas, and data sharding all re-derive through
  ``MeshLayout``), resumes from the newest generation committed by ALL
  participants (at most one block of rounds re-run), and optionally lets the
  failed host REJOIN at the next generation boundary.  The run ends with a
  ``runs/hostchaos_*.json`` artifact: MTTR, rounds lost, post-recovery loss
  parity vs an unfailed run on the same shrunk mesh from the same recovery
  point, and a zero-orphans check over every pid ever spawned.

* ``federate`` (``make federation-smoke``): ONE STACK — the wire tier drains
  straight into the hierarchical mesh reduce.  Every mesh host runs an
  ``HTTPServer`` + ``DeviceIngestBuffer`` front end; the ``loadgen`` swarm
  drives the wire population against the listeners (VirtualClock arrival
  schedule, real sockets, real submit latencies); each round is host-local
  partial drains (the buffer's batched ``coefs @ buffer`` reduce, drained
  UNNORMALIZED) joined by ONE cross-host psum
  (``communication.federation.build_cross_host_row_psum`` on a hosts-only
  mesh — one device per process, one gloo stream per beat — with the FedAvg
  apply landing host-side via ``apply_summed_row``), with a stop-vote
  control lane riding the same collective so hosts reach round-count
  consensus without a side channel.  With ``--kill-round`` a seeded plan
  crashes one host mid-campaign: its wire clients reroute to survivors LIVE
  (retry/rotation/dedup), the supervisor re-forms the mesh over the
  survivors from the newest committed generation, re-drives the dead host's
  population, and asserts ZERO lost submits across the whole campaign.
  Artifact: ``runs/federation_*.json``.

Launcher (default entry) spawns the worker processes of itself; workers rendez-
vous through ``jax.distributed`` on a loopback coordinator.  Every knob rides
argv so the launcher and workers cannot drift.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # the hostchaos supervisor imports nanofed_tpu
    sys.path.insert(0, str(REPO))

SMOKE_TOL = 5e-5  # hierarchical vs flat psum: re-association only (~1e-7 seen)

#: Worker exit code when the collective watchdog (or a gloo/distributed error)
#: surfaced a PEER's failure — distinct from the planned victim's own death
#: (HOST_CRASH_RC, imported so the supervisor's rc match can never drift from
#: what the injector actually exits with; host_injector is pure stdlib).
PEER_FAILURE_RC = 32
from nanofed_tpu.faults.host_injector import (  # noqa: E402
    HOST_CRASH_EXIT_CODE as HOST_CRASH_RC,
)


def _worker_env(args: argparse.Namespace, process_id: int) -> dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices_per_process}"
    )
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    env["NANOFED_MH_PROCESS_ID"] = str(process_id)
    return env


def client_rows(client_ids, capacity: int, feat: tuple[int, ...], seed: int):
    """Deterministic synthetic data for a RANGE of global client ids — the same
    rows regardless of which process (or how many) materializes them, which is
    what makes the multi-process run byte-comparable to the single-process
    reference.  Linearly-separable-ish classes so a few rounds visibly learn."""
    import numpy as np

    xs, ys = [], []
    for cid in client_ids:
        rng = np.random.default_rng(seed * 1_000_003 + int(cid))
        y = rng.integers(0, 10, size=capacity)
        x = rng.normal(0, 1, size=(capacity, *feat)).astype(np.float32)
        x[..., 0, 0, 0] += y  # class signal in one coordinate
        xs.append(x.astype(np.float32))
        ys.append(y.astype(np.int32))
    mask = np.ones((len(xs), capacity), np.float32)
    return np.stack(xs), np.stack(ys), mask


def run_worker(args: argparse.Namespace) -> int:
    """One jax.distributed process: build the hosts-axis mesh, shard THIS
    host's client rows, run the round program, report through files."""
    t0 = time.time()
    import jax

    from nanofed_tpu.parallel import initialize_distributed

    if args.num_processes > 1:
        info = initialize_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    else:
        info = {"process_index": 0, "process_count": 1}

    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.core.types import ClientData
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        build_round_step,
        client_shard_count,
        host_client_slice,
        init_server_state,
        make_mesh,
        mesh_shape,
        pad_client_count,
        param_sharding,
        shard_host_local_data,
    )
    from nanofed_tpu.trainer import TrainingConfig, stack_rngs

    devices = jax.devices()
    pid = info["process_index"]

    def log(msg: str) -> None:
        print(f"[{time.time() - t0:6.1f}s p{pid}] {msg}", file=sys.stderr,
              flush=True)

    log(f"up: {len(devices)} global devices across "
        f"{info['process_count']} process(es)")

    if args.job == "federate":
        return _federate_worker(args, info, log)

    if args.hosts > 1:
        shape = (args.hosts, len(devices) // args.hosts, 1)
    else:
        shape = None  # the 1-D reference mesh
    mesh = make_mesh(shape=shape)
    n_shards = client_shard_count(mesh)

    model = get_model(args.model)
    feat = tuple(model.input_shape)
    padded = pad_client_count(args.clients, n_shards)
    start, stop = host_client_slice(padded, mesh)
    log(f"mesh {mesh_shape(mesh)}: padded {padded} clients, "
        f"this process holds rows [{start}, {stop})")

    # Per-host data sharding: ONLY this process's rows ever materialize here.
    ids = np.arange(start, stop)
    x, y, mask = client_rows(ids, args.capacity, feat, args.seed)
    mask[ids >= args.clients] = 0.0  # padding rows carry zero weight
    local = ClientData(x=x, y=y, mask=mask)
    num_samples_local = mask.sum(axis=1)
    data = shard_host_local_data(local, mesh, padded)
    log(f"data resident: {x.nbytes / 1e6:.1f} MB/process on device")

    training = TrainingConfig(
        batch_size=args.batch_size, local_epochs=1, learning_rate=0.1
    )
    strategy = fedavg_strategy()
    params_host = model.init(jax.random.key(args.seed))
    sos_host = init_server_state(strategy, params_host)
    start_round = 0
    if args.job == "hostchaos" and args.resume:
        from nanofed_tpu.persistence import GenerationStore

        rec = GenerationStore(args.ckpt_dir).latest_complete()
        if rec is not None:
            # Newest generation committed by ALL its participants: the only
            # legal multi-host recovery point (at-most-one-block loss).
            params_host, sos_host = rec.params, rec.server_state
            start_round = rec.round_number
            log(f"resumed generation {rec.generation} at round {start_round} "
                f"(committed by hosts {list(rec.hosts)})")
        else:
            log("resume requested but no complete generation yet — fresh start")
    params = jax.device_put(params_host, param_sharding(mesh, params_host))
    sos = jax.device_put(sos_host, param_sharding(mesh, sos_host))
    step = build_round_step(
        model.apply, training, mesh, strategy,
        client_chunk=args.client_chunk, params_like=params,
        donate=True,
    )

    # Replicated round inputs (weights, per-round key stacks) are pure
    # functions of (client id, seed, round), so every process COMPUTES them as
    # a tiny jitted program with replicated out_shardings instead of shipping
    # host arrays — a committed process-local array cannot be device_put onto
    # a multi-process sharding, and nothing needs to move anyway.
    del num_samples_local  # identical info rides the computed weights below
    from functools import partial

    from nanofed_tpu.parallel import replicated_sharding

    repl = replicated_sharding(mesh)
    weights = jax.jit(
        lambda: compute_weights(jnp.where(
            jnp.arange(padded) < args.clients, float(args.capacity), 0.0
        )),
        out_shardings=repl,
    )()

    # r rides as a TRACED scalar (fold_in accepts one): one compile serves
    # every round — static_argnums here would recompile the key stack per r,
    # polluting the timed round walltimes.
    @partial(jax.jit, out_shardings=repl)
    def round_rngs(r):
        return stack_rngs(
            jax.random.fold_in(jax.random.key(args.seed), r), padded
        )

    if args.job == "hostchaos":
        return _hostchaos_rounds(
            args, info, log, mesh, step, params, sos, data, weights,
            round_rngs, start_round,
        )

    losses: list[float] = []
    round_times: list[float] = []
    for r in range(args.rounds + 1):  # +1: round 0 pays the compile (warm-up)
        rngs = round_rngs(r)
        t = time.perf_counter()
        res = step(params, sos, data, weights, rngs)
        params, sos = res.params, res.server_opt_state
        jax.block_until_ready(params)
        dt = time.perf_counter() - t
        loss = float(res.metrics["loss"])
        losses.append(loss)
        if r > 0:
            round_times.append(dt)
        log(f"round {r}: loss={loss:.5f} ({dt:.2f}s"
            + (", incl. compile)" if r == 0 else ")"))

    result = {
        "mode": args.job,
        "losses": losses,
        "round_times_s": [round(x, 4) for x in round_times],
        "topology": {
            "process_count": info["process_count"],
            "hosts": args.hosts,
            "devices": len(devices),
            "mesh_shape": list(mesh_shape(mesh)),
        },
    }
    if pid == 0 and args.out is not None:
        flat = np.concatenate([
            np.asarray(jax.device_get(leaf)).ravel()
            for leaf in jax.tree.leaves(params)
        ])
        np.save(args.out + ".params.npy", flat)
        Path(args.out).write_text(json.dumps(result, indent=2))
        log(f"wrote {args.out}")
    return 0


def _hostchaos_rounds(
    args: argparse.Namespace,
    info: dict,
    log,
    mesh,
    step,
    params,
    sos,
    data,
    weights,
    round_rngs,
    start_round: int,
) -> int:
    """The fault-tolerant worker round loop: chaos injection at the host
    boundary, heartbeats, a watchdog deadline around every dispatch, and
    generation checkpoints at block boundaries.  The jitted round program is
    byte-identical to the smoke/bench jobs — chaos and resilience live
    entirely on the host side of the dispatch."""
    import jax
    import numpy as np

    from nanofed_tpu.faults import ChaosSchedule, FaultPlan, HostChaosInjector
    from nanofed_tpu.parallel import (
        CollectiveWatchdog,
        Heartbeat,
        HostFailure,
        mesh_shape,
    )
    from nanofed_tpu.persistence import GenerationStore

    host = args.host_id
    hosts_list = [int(h) for h in args.hosts_list.split(",")]
    injector = None
    if args.fault_plan:
        injector = HostChaosInjector(
            ChaosSchedule(FaultPlan.load(args.fault_plan)), host=host
        )
    hb = Heartbeat(args.hb_dir, host)
    store = GenerationStore(args.ckpt_dir, host=host)
    watchdog = CollectiveWatchdog(args.watchdog_deadline)
    progress = Path(args.progress) if args.progress else None
    pid = info["process_index"]

    def dispatch(params, sos, rngs):
        res = step(params, sos, data, weights, rngs)
        # Block INSIDE the watchdog bracket: the hang a dead peer causes
        # lives in the collective the result depends on.
        jax.block_until_ready((res.params, res.server_opt_state, res.metrics))
        return res

    def commit(rounds_done: int, params, sos) -> None:
        gen = rounds_done // args.block_size
        p_host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
        s_host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), sos)
        store.commit(gen, rounds_done, p_host, s_host, hosts=hosts_list)
        hb.beat(round_number=rounds_done, generation=gen, status="committed")
        log(f"committed generation {gen} at round {rounds_done}")

    losses: list[float] = []
    executed: list[int] = []
    first_dispatch = True
    for r in range(start_round, args.rounds):
        if injector is not None:
            injector.maybe_fail(r)  # may os._exit (crash) or park (stall)
            delay = injector.dcn_delay_s(r)
            if delay:
                log(f"chaos: dcn_degrade {delay:.3f}s before round {r}")
                time.sleep(delay)
        else:
            delay = 0.0
        hb.beat(round_number=r, generation=r // args.block_size,
                status="dispatch")
        rngs = round_rngs(r)
        # The first dispatched round pays trace+compile; the deadline must
        # not misread a slow compile (or a planned-degraded DCN link) as a
        # dead peer.
        grace = delay + (args.compile_grace if first_dispatch else 0.0)
        try:
            res = watchdog.run(
                dispatch, params, sos, rngs,
                round_number=r, dcn_grace_s=grace,
                # Keep beating while blocked on the collective: a waiting
                # peer is alive — only the genuinely stalled host freezes.
                tick=lambda: hb.beat(
                    round_number=r, generation=r // args.block_size,
                    status="dispatch",
                ),
            )
        except HostFailure as exc:
            log(f"watchdog: {exc}")
            hb.beat(round_number=r, status="peer_failure")
            # os._exit, not sys.exit: the interpreter's atexit runs JAX's
            # distributed teardown, which BARRIERS on the very peer that just
            # failed — the clean exit would hang as hard as the collective.
            os._exit(PEER_FAILURE_RC)
        except Exception as exc:  # gloo/coordination error: a peer is gone
            log(f"dispatch failed (peer loss?): {type(exc).__name__}: {exc}")
            hb.beat(round_number=r, status="peer_failure")
            os._exit(PEER_FAILURE_RC)
        first_dispatch = False
        params, sos = res.params, res.server_opt_state
        loss = float(res.metrics["loss"])
        losses.append(loss)
        executed.append(r)
        hb.beat(round_number=r + 1, generation=(r + 1) // args.block_size,
                status="running")
        if progress is not None and pid == 0:
            with progress.open("a") as f:
                f.write(json.dumps(
                    {"round": r, "loss": loss, "wall_t": time.time()}
                ) + "\n")
        log(f"round {r}: loss={loss:.5f}")
        if (r + 1) % args.block_size == 0:
            commit(r + 1, params, sos)

    hb.beat(round_number=args.rounds, status="done")
    if pid == 0 and args.out is not None:
        Path(args.out).write_text(json.dumps({
            "mode": "hostchaos",
            "start_round": start_round,
            "rounds": executed,
            "losses": losses,
            "topology": {
                "process_count": info["process_count"],
                "hosts": args.hosts,
                "host_ids": hosts_list,
                "devices": len(jax.devices()),
                "mesh_shape": list(mesh_shape(mesh)),
            },
        }, indent=2))
        log(f"wrote {args.out}")
    return 0


def _federate_worker(args: argparse.Namespace, info: dict, log) -> int:
    """One federate mesh host: a live HTTP listener + device ingest buffer
    front end, drained HOST-LOCALLY each round (the buffer's batched
    ``coefs @ buffer`` reduce is the host-local aggregation stage), then ONE
    cross-host psum over ``hosts`` (``communication.federation``) applies the
    global FedAvg step.  The psum row carries a stop-vote lane: workers agree
    on the final round THROUGH the collective they already run — a worker
    that exited on a local condition alone would deadlock its peers' next
    psum.  The collective runs in an executor thread so the listener keeps
    accepting (and a swarm keeps rerouting INTO this host) while gloo blocks."""
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.flatten_util import ravel_pytree

    from nanofed_tpu.communication.federation import (
        apply_summed_row,
        assemble_host_rows,
        build_cross_host_row_psum,
        host_partial_row,
    )
    from nanofed_tpu.communication.http_server import HTTPServer
    from nanofed_tpu.faults import ChaosSchedule, FaultPlan, HostChaosInjector
    from nanofed_tpu.ingest import IngestConfig
    from nanofed_tpu.models import get_model
    from nanofed_tpu.observability.registry import MetricsRegistry
    from nanofed_tpu.orchestration.engine import (
        RoundLedger,
        completion_required,
    )
    from nanofed_tpu.parallel import (
        CollectiveWatchdog,
        Heartbeat,
        HostFailure,
        make_mesh,
        mesh_shape,
        replicated_sharding,
    )
    from nanofed_tpu.persistence import GenerationStore

    host = args.host_id
    hosts_list = [int(h) for h in args.hosts_list.split(",")]
    # Hosts-only mesh: ONE device per process.  A populated clients axis
    # would split the psum into one replica group per client column — several
    # concurrent gloo streams per round — and concurrent streams cross in
    # gloo's async slot sequencing (op.preamble.length <= op.nbytes aborts,
    # observed at 4 processes).  One device per host ⇒ one replica group ⇒
    # one gloo stream per beat.  The host-local stage needs no mesh at all:
    # it IS the ingest buffer's batched drain on this process's devices.
    mesh = make_mesh(
        devices=[
            jax.local_devices(process_index=p)[0]
            for p in range(jax.process_count())
        ],
        shape=(args.hosts, 1, 1),
    )
    model = get_model(args.model)
    flat0, unravel = ravel_pytree(model.init(jax.random.key(args.seed)))
    flat_size = int(flat0.size)

    def to_tree(flat: "np.ndarray"):
        return jax.tree.map(np.asarray, unravel(jnp.asarray(flat)))

    psum_fn = build_cross_host_row_psum(mesh)

    injector = None
    if args.fault_plan:
        injector = HostChaosInjector(
            ChaosSchedule(FaultPlan.load(args.fault_plan)), host=host
        )
    hb = Heartbeat(args.hb_dir, host)
    store = GenerationStore(args.ckpt_dir, host=host)
    watchdog = CollectiveWatchdog(args.watchdog_deadline)
    stop_file = Path(args.stop_file) if args.stop_file else None

    flat = np.asarray(flat0, np.float32)
    start_round = 0
    if args.resume:
        rec = store.latest_complete()
        if rec is not None:
            flat = np.asarray(ravel_pytree(rec.params)[0], np.float32)
            start_round = rec.round_number
            log(f"resumed generation {rec.generation} at round {start_round} "
                f"(committed by hosts {list(rec.hosts)})")
        else:
            log("resume requested but no complete generation — fresh start")

    # Warm dispatch: compiles the cross-host program AND doubles as the
    # bring-up barrier — a listener only opens once every peer reached this
    # collective (zero-mass rows change nothing; the mass floor keeps it
    # finite).
    warm = host_partial_row(None, 0.0, flat_size, extra=(0.0,))
    jax.block_until_ready(psum_fn(assemble_host_rows(mesh, warm)))
    # The warm psum is a barrier, so every host's anchor is within collective-
    # completion skew (ms on loopback) of its peers'.  Round deadlines derive
    # from this shared epoch — NOT from each host's own round start — so
    # dispatch skew across hosts stays bounded by one beat period plus drain
    # variance.  Load-bearing: XLA's CPU collectives carry a fixed internal
    # 30 s gloo timeout (CollectiveThunk::DefaultCollectiveTimeout), and a
    # host that reaches the psum a full unanchored round-timeout before a
    # quiet peer trips it, aborting the fleet mid-campaign with a torn-pair
    # gloo error instead of a clean round.
    anchor = time.monotonic()
    anchor_wall = time.time()  # forensic: the same instant on the wall clock
    log(f"cross-host reduce compiled on mesh {mesh_shape(mesh)} "
        "(bring-up barrier passed)")

    registry = MetricsRegistry()
    telemetry = None
    if args.telemetry_dir:
        from nanofed_tpu.observability import RunTelemetry

        # One stream per worker, merged by `nanofed-tpu trace`: the
        # clock_sync record pins this host's wall clock to the barrier
        # epoch every host just exited simultaneously — the offsets the
        # timeline merger subtracts ARE the differences of these stamps.
        telemetry = RunTelemetry(
            Path(args.telemetry_dir) / f"host_{host}", registry=registry
        )
        telemetry.record(
            "clock_sync", host=host, anchor_wall=round(anchor_wall, 6),
            process_id=info["process_index"],
        )
    ledger = RoundLedger(registry, telemetry=telemetry, track_dropouts=True)
    required = completion_required(args.round_quota, args.min_completion_rate)
    n_hosts = len(hosts_list)
    progress = Path(args.progress) if args.progress else None

    async def _serve() -> dict:
        server = HTTPServer(
            port=args.wire_port + host,
            registry=registry,
            max_inflight=512,
            # >= 1 is load-bearing: at window 0 publish_model CLEARS the
            # ingest buffer every round, silently dropping submits that were
            # accepted but not yet drained.
            staleness_window=max(1, args.staleness_window),
            ingest=IngestConfig(capacity=args.ingest_capacity),
            tracer=None if telemetry is None else telemetry.tracer,
        )
        await server.start()
        await server.publish_model(to_tree(flat), start_round)
        if args.ready_file:
            ready = Path(args.ready_file)
            tmp_path = ready.with_suffix(".tmp")
            tmp_path.write_text(json.dumps({
                "host": host,
                "url": f"http://127.0.0.1:{args.wire_port + host}",
                "round": start_round,
            }))
            tmp_path.replace(ready)  # atomic: the supervisor never sees torn
        log(f"listener up on :{args.wire_port + host} at round {start_round}")

        loop = asyncio.get_running_loop()
        base = flat
        rounds_meta: list[dict] = []
        clients_seen: set[str] = set()
        rerouted_total = 0
        r = start_round
        while True:
            if injector is not None:
                injector.maybe_fail(r)  # the planned host_crash: os._exit
                delay = injector.dcn_delay_s(r)
                if delay:
                    await asyncio.sleep(delay)
            hb.beat(round_number=r, status="collecting")
            t_round = time.perf_counter()
            start_wall = time.time()  # forensic: timeline lane placement
            pipeline = server._ingest_pipeline
            decode_before = (
                pipeline.decode_busy_seconds() if pipeline is not None else 0.0
            )
            # Shared beat: every host's round-r deadline is the same offset
            # from the warm-psum epoch, and the beat is STRICT — a full
            # quota never dispatches early.  Both halves are load-bearing:
            # hosts must enter the psum near-simultaneously (XLA CPU
            # collectives carry a fixed internal 30 s gloo timeout), and
            # back-to-back collective bundles fired sub-second by a hot host
            # race gloo's async slot sequencing (observed as op.preamble
            # size-mismatch aborts when a 100k swarm concentrated on one
            # listener).  The quota gates the LEDGER outcome, not dispatch.
            deadline = anchor + (r - start_round + 1) * args.round_timeout_s
            stop_seen = None
            while True:
                if stop_file is not None and stop_file.exists():
                    # The supervisor writes the stop file only after every
                    # swarm submit landed: the buffer is quiescent after a
                    # short grace — drain whatever is left and vote stop.
                    if stop_seen is None:
                        stop_seen = time.monotonic()
                    elif time.monotonic() - stop_seen > 0.5:
                        break
                if time.monotonic() > deadline:
                    break
                await asyncio.sleep(0.02)
            # Critical-path attribution: decode runs on pool threads DURING
            # this wait, so the beat wait splits into decode (the pool's busy
            # seconds this round, clamped to the window) and wire_wait (the
            # remainder — genuinely waiting on the wire).  With the
            # sequential drain/collective/apply/publish stages below, the six
            # segments tile the round walltime.
            wait_measured = time.perf_counter() - t_round
            decode_busy = (
                (pipeline.decode_busy_seconds() if pipeline is not None
                 else 0.0) - decode_before
            )
            seg_decode = min(max(0.0, decode_busy), wait_measured)
            t_drain = time.perf_counter()
            out, mass, metas = await server.drain_ingest_fedavg_partial()
            seg_drain = time.perf_counter() - t_drain
            want_stop = (
                (stop_file is not None and stop_file.exists())
                or (r + 1) >= args.rounds
            )
            row = host_partial_row(
                None if out is None else np.asarray(out), mass, flat_size,
                extra=(1.0 if want_stop else 0.0,),
            )
            hb.beat(round_number=r, status="dispatch")

            dispatch_t: dict = {}

            def dispatch(row=row, base=base):
                # One collective, nothing else on the wire: the psum'd row
                # comes back and the FedAvg apply happens in numpy — bitwise
                # identical on every host (ring all-reduce results are
                # rank-identical), so no broadcast/materialization stream
                # ever coexists with the psum.  Timed in two marks: the
                # blocked collective vs the host-side FedAvg apply.
                t0 = time.perf_counter()
                total_dev = psum_fn(assemble_host_rows(mesh, row))
                jax.block_until_ready(total_dev)
                t1 = time.perf_counter()
                applied = apply_summed_row(base, np.asarray(total_dev),
                                           flat_size)
                dispatch_t["collective"] = t1 - t0
                dispatch_t["apply"] = time.perf_counter() - t1
                return applied

            try:
                # Executor thread: the event loop — and with it the wire
                # listener — stays live while gloo blocks on the psum.
                new_flat, tail = await loop.run_in_executor(
                    None,
                    lambda: watchdog.run(
                        dispatch, round_number=r,
                        tick=lambda: hb.beat(round_number=r,
                                             status="dispatch"),
                    ),
                )
            except HostFailure as exc:
                log(f"watchdog: {exc}")
                hb.beat(round_number=r, status="peer_failure")
                # os._exit, not sys.exit: atexit would barrier on the dead
                # peer (see _hostchaos_rounds).
                os._exit(PEER_FAILURE_RC)
            except Exception as exc:  # gloo/coordination error: a peer died
                log(f"dispatch failed (peer loss?): "
                    f"{type(exc).__name__}: {exc}")
                hb.beat(round_number=r, status="peer_failure")
                os._exit(PEER_FAILURE_RC)
            global_mass = float(tail[0])
            stop_votes = float(tail[1])
            if global_mass > 0.0:
                base = new_flat
                # Strict-beat pacing means the quota no longer gates WHEN a
                # round fires — it gates how the ledger scores the beat: a
                # drain below completion_required() still advances the model
                # (the mass-weighted reduce is exact at any cohort size) but
                # is charged DEGRADED so under-filled beats are visible in
                # nanofed_rounds_total without stalling the collective.
                status = ("COMPLETED" if len(metas) >= required
                          else "DEGRADED")
            else:
                status = "FAILED"  # every host drained empty; params keep
            rerouted = sum(
                1 for m in metas
                if not str(m.client_id).startswith(f"h{host}_")
            )
            rerouted_total += rerouted
            clients_seen.update(str(m.client_id) for m in metas)
            sentinel = want_stop and not metas and global_mass <= 0.0
            round_r = r
            r += 1
            # Publish BEFORE charging the beat: the publish is the round's
            # last critical-path segment, so the charged walltime (and the
            # segments that tile it) must include it.
            t_publish = time.perf_counter()
            await server.publish_model(to_tree(base), r)
            seg_publish = time.perf_counter() - t_publish
            dt = time.perf_counter() - t_round
            hb.beat(round_number=r, status="running")
            if not sentinel:
                segments = {
                    "wire_wait": max(0.0, wait_measured - seg_decode),
                    "decode": seg_decode,
                    "drain": seg_drain,
                    "collective": dispatch_t.get("collective", 0.0),
                    "apply": dispatch_t.get("apply", 0.0),
                    "publish": seg_publish,
                }
                ledger.charge(
                    status=status, num_clients=len(metas), duration_s=dt,
                    expected=args.round_quota, segments=segments,
                    telemetry_fields={
                        "round": round_r, "host": host, "status": status,
                        "duration_s": round(dt, 6),
                        "start_wall": round(start_wall, 6),
                        "drained": len(metas),
                        "mass": round(float(mass), 3),
                        "rerouted_in": rerouted,
                        # Every consumed submit's trace id — the join key the
                        # trace resolver uses to link wire submits to the
                        # round that consumed them ("" = untraced submit).
                        "traces": [m.trace for m in metas],
                    },
                )
                rounds_meta.append({
                    "round": round_r, "drained": len(metas),
                    "mass": round(float(mass), 3),
                    "global_mass": round(global_mass, 3),
                    "rerouted_in": rerouted,
                    "duration_s": round(dt, 4), "status": status,
                })
                if progress is not None:
                    with progress.open("a") as f:
                        f.write(json.dumps({
                            "round": round_r, "drained": len(metas),
                            "mass": round(float(mass), 3),
                            "rerouted_in": rerouted,
                            "duration_s": round(dt, 4),
                            "wall_t": time.time(),
                        }) + "\n")
                log(f"round {round_r}: drained {len(metas)} "
                    f"(mass {mass:.1f}, {rerouted} rerouted in) global mass "
                    f"{global_mass:.1f} [{status}] {dt:.2f}s")
            if r % args.block_size == 0 and not sentinel:
                store.commit(r // args.block_size, r, to_tree(base), {},
                             hosts=hosts_list)
                log(f"committed generation {r // args.block_size} "
                    f"at round {r}")
            if stop_votes >= n_hosts - 0.5:
                log(f"stop consensus at round {r} "
                    f"({stop_votes:.0f}/{n_hosts} votes)")
                break

        if r % args.block_size != 0:
            store.commit(r // args.block_size + 1, r, to_tree(base), {},
                         hosts=hosts_list)
        server.stop_training()
        await asyncio.sleep(0.2)  # let /status pollers observe the stop
        hb.beat(round_number=r, status="done")
        result = {
            "mode": "federate",
            "host": host,
            "start_round": start_round,
            "end_round": r,
            "rounds": rounds_meta,
            "clients_distinct": len(clients_seen),
            "rerouted_in_total": rerouted_total,
            "topology": {
                "process_count": info["process_count"],
                "hosts": args.hosts,
                "host_ids": hosts_list,
                "devices": jax.device_count(),
                "mesh_shape": list(mesh_shape(mesh)),
            },
        }
        await server.stop()
        if telemetry is not None:
            telemetry.close()  # appends the final metrics_snapshot record
        return result

    result = asyncio.run(_serve())
    if args.out is not None:
        Path(args.out).write_text(json.dumps(result, indent=2))
        log(f"wrote {args.out}")
    return 0


def _spawn(args: argparse.Namespace, mode_args: list[str], out: str | None,
           hosts: int, num_processes: int, port: int) -> list[subprocess.Popen]:
    procs = []
    for pid in range(num_processes):
        cmd = [
            sys.executable, str(Path(__file__).resolve()), "worker",
            "--process-id", str(pid),
            "--num-processes", str(num_processes),
            "--coordinator", f"localhost:{port}",
            "--hosts", str(hosts),
            *mode_args,
        ]
        if out is not None and pid == 0:
            cmd += ["--out", out]
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, pid)))
    return procs


def _reap(procs: list[subprocess.Popen], grace_s: float = 5.0) -> None:
    """Terminate AND reap every still-running worker.  Kill-without-wait (the
    old failure path) leaves zombies holding the rendezvous port: the next
    parity run on the machine then dies in jax.distributed bring-up.  SIGTERM
    first (workers flush logs), SIGKILL after the grace, ``wait()`` always —
    no child of the launcher may outlive this call."""
    for q in procs:
        if q.poll() is None:
            q.terminate()
    deadline = time.time() + grace_s
    for q in procs:
        if q.poll() is not None:
            continue
        try:
            q.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            q.kill()
            q.wait()


def _wait(procs: list[subprocess.Popen], timeout_s: float) -> None:
    # Poll ALL workers, not procs[0] first: a fast crash in worker 1 while
    # worker 0 blocks in the jax.distributed rendezvous must surface as the
    # real non-zero exit code immediately, not as a full-timeout "timed out"
    # after the peer-less rendezvous finally expires.  Any failure path reaps
    # the survivors BEFORE raising: a failed parity run must not leave orphan
    # processes holding the rendezvous port.
    deadline = time.time() + timeout_s
    pending = list(procs)
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is None:
                continue
            if rc != 0:
                _reap(procs)
                raise SystemExit(f"worker exited rc={rc}")
            pending.remove(p)
        if pending:
            if time.time() > deadline:
                _reap(procs)
                raise SystemExit(f"worker timed out after {timeout_s:.0f}s")
            time.sleep(0.2)


def run_smoke(args: argparse.Namespace) -> int:
    """2-process hierarchical run vs single-process 1-D reference: the losses
    and final params must match to float tolerance — the trajectory-parity
    acceptance bar of the multi-host path."""
    import numpy as np

    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    mode_args = [
        "--job", "smoke", "--clients", str(args.clients),
        "--capacity", str(args.capacity), "--batch-size", str(args.batch_size),
        "--rounds", str(args.rounds), "--model", args.model,
        "--seed", str(args.seed),
        "--devices-per-process", str(args.devices_per_process),
    ]
    if args.client_chunk is not None:
        mode_args += ["--client-chunk", str(args.client_chunk)]

    multi_out = str(tmp / "multihost_smoke_multi.json")
    t0 = time.time()
    print(f"# spawning {args.num_processes}-process hierarchical run "
          f"(hosts={args.num_processes}, gloo CPU collectives)", flush=True)
    procs = _spawn(args, mode_args, multi_out, hosts=args.num_processes,
                   num_processes=args.num_processes, port=args.port)
    _wait(procs, args.timeout)

    # Single-process 1-D reference over the SAME global device count: one
    # worker, hosts=1, no jax.distributed — the classic flat-psum program.
    ref_out = str(tmp / "multihost_smoke_ref.json")
    print("# running single-process 1-D reference", flush=True)
    ref_args = argparse.Namespace(**vars(args))
    ref_args.devices_per_process = (
        args.devices_per_process * args.num_processes
    )
    procs = _spawn(ref_args, mode_args, ref_out, hosts=1,
                   num_processes=1, port=args.port + 1)
    _wait(procs, args.timeout)

    multi = json.loads(Path(multi_out).read_text())
    ref = json.loads(Path(ref_out).read_text())
    p_multi = np.load(multi_out + ".params.npy")
    p_ref = np.load(ref_out + ".params.npy")
    loss_delta = max(
        abs(a - b) for a, b in zip(multi["losses"], ref["losses"])
    )
    param_delta = float(np.abs(p_multi - p_ref).max())
    verdict = {
        "losses_multi": multi["losses"],
        "losses_ref": ref["losses"],
        "max_loss_delta": loss_delta,
        "max_param_delta": param_delta,
        "tolerance": SMOKE_TOL,
        "topology": multi["topology"],
        "walltime_s": round(time.time() - t0, 1),
    }
    print(json.dumps(verdict, indent=2))
    assert multi["topology"]["process_count"] == args.num_processes, multi
    assert loss_delta <= SMOKE_TOL, (
        f"trajectory diverged: max loss delta {loss_delta} > {SMOKE_TOL}"
    )
    assert param_delta <= SMOKE_TOL, (
        f"params diverged: max delta {param_delta} > {SMOKE_TOL}"
    )
    print("multihost-smoke OK: 2-process hierarchical aggregation == "
          "single-process 1-D mesh to float tolerance")
    return 0


def run_bench(args: argparse.Namespace) -> int:
    """The 100k+ streamed-clients artifact: chunked streaming x multi-process,
    rounds/sec + clients/sec, topology block, honest CPU basis."""
    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    mode_args = [
        "--job", "bench", "--clients", str(args.clients),
        "--capacity", str(args.capacity), "--batch-size", str(args.batch_size),
        "--rounds", str(args.rounds), "--model", args.model,
        "--seed", str(args.seed),
        "--devices-per-process", str(args.devices_per_process),
        "--client-chunk", str(args.client_chunk if args.client_chunk else 250),
    ]
    worker_out = str(tmp / "multihost_bench_worker.json")
    t0 = time.time()
    print(f"# spawning {args.num_processes}-process bench at "
          f"{args.clients} clients", flush=True)
    procs = _spawn(args, mode_args, worker_out, hosts=args.num_processes,
                   num_processes=args.num_processes, port=args.port)
    _wait(procs, args.timeout)

    worker = json.loads(Path(worker_out).read_text())
    times = worker["round_times_s"]
    median = sorted(times)[len(times) // 2]
    record = {
        "metric": "multihost_fedavg_round_walltime",
        "unit": "s",
        "value": median,
        "per_round_s": times,
        "rounds_per_sec": round(1.0 / median, 4),
        "clients_per_sec": round(args.clients / median, 1),
        "num_clients": args.clients,
        "samples_per_client": args.capacity,
        "client_chunk": args.client_chunk if args.client_chunk else 250,
        "model": args.model,
        "losses": worker["losses"],
        "topology": worker["topology"],
        "platform": "cpu",
        "basis": (
            "multi-process jax.distributed over loopback (gloo CPU "
            "collectives), virtual XLA host devices per process; measures the "
            "hierarchical round PROGRAM — chunked streaming, host-local psum "
            "+ one cross-host psum, multi-controller dispatch — at population "
            "scale on CPU, not TPU silicon. The reference flagship tops out "
            "at 1000 clients (BASELINE.md); this is the 100x population jump."
        ),
        "harness": "scripts/multihost_harness.py bench",
        "walltime_s": round(time.time() - t0, 1),
    }
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = out_dir / f"multihost_{stamp}_{args.clients // 1000}k.json"
    path.write_text(json.dumps(record, indent=2))
    print(json.dumps(record, indent=2))
    print(f"# artifact written to {path}")
    return 0


def _spawn_hostchaos(
    args: argparse.Namespace,
    host_ids: list[int],
    port: int,
    *,
    rounds: int,
    hb_dir: Path,
    ckpt_dir: Path,
    resume: bool,
    plan_path: Path | None,
    out: Path | None,
    progress: Path | None,
) -> list[subprocess.Popen]:
    """Spawn one hostchaos worker per LOGICAL host id.  Process ids renumber
    0..n-1 every phase (jax.distributed needs a dense range); logical host ids
    survive reshapes — they are what the fault plan targets, what heartbeats
    and commit markers are keyed by, and what lets a restarted host rejoin as
    itself."""
    procs = []
    n = len(host_ids)
    for pid, host in enumerate(host_ids):
        cmd = [
            sys.executable, str(Path(__file__).resolve()), "worker",
            "--job", "hostchaos",
            "--process-id", str(pid),
            "--num-processes", str(n),
            "--coordinator", f"localhost:{port}",
            "--hosts", str(n),
            "--clients", str(args.clients),
            "--capacity", str(args.capacity),
            "--batch-size", str(args.batch_size),
            "--rounds", str(rounds),
            "--model", args.model,
            "--seed", str(args.seed),
            "--devices-per-process", str(args.devices_per_process),
            "--block-size", str(args.block_size),
            "--watchdog-deadline", str(args.watchdog_deadline),
            "--compile-grace", str(args.compile_grace),
            "--host-id", str(host),
            "--hosts-list", ",".join(str(h) for h in host_ids),
            "--hb-dir", str(hb_dir),
            "--ckpt-dir", str(ckpt_dir),
        ]
        if args.client_chunk is not None:
            cmd += ["--client-chunk", str(args.client_chunk)]
        if resume:
            cmd += ["--resume"]
        if plan_path is not None:
            cmd += ["--fault-plan", str(plan_path)]
        if out is not None and pid == 0:
            cmd += ["--out", str(out)]
        if progress is not None and pid == 0:
            cmd += ["--progress", str(progress)]
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, pid)))
    return procs


def _read_progress(path: Path) -> list[dict]:
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # torn tail line from a killed writer
    return out


def _fresh_dir(path: Path) -> Path:
    if path.exists():
        shutil.rmtree(path)
    path.mkdir(parents=True)
    return path


def run_hostchaos(args: argparse.Namespace) -> int:
    """The kill-and-recover drill: seeded plan fails one of >=2 hosts
    mid-round; the supervisor detects it, reaps the survivors, re-forms the
    mesh over the surviving host set, resumes from the newest generation
    committed by all participants, optionally rejoins the failed host, and
    writes the ``runs/hostchaos_*.json`` evidence artifact (MTTR, rounds
    lost <= one block, post-recovery parity vs an unfailed shrunk-mesh run,
    zero orphans)."""
    from nanofed_tpu.faults.plan import FaultPlan
    from nanofed_tpu.observability.telemetry import RunTelemetry
    from nanofed_tpu.observability.tracing import (
        FLIGHT_RECORDER_FILENAME,
        FlightRecorder,
        mttr_decomposition,
    )
    from nanofed_tpu.parallel.resilience import (
        HostMonitor,
        no_orphans,
        resilience_metrics,
    )
    from nanofed_tpu.persistence import GenerationStore

    if args.num_processes < 2:
        raise SystemExit("hostchaos needs --num-processes >= 2 (someone must "
                         "survive to recover)")
    P, R, B = args.num_processes, args.rounds, args.block_size
    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    hb_a = _fresh_dir(tmp / "hb_a")
    hb_c = _fresh_dir(tmp / "hb_c")
    hb_d = _fresh_dir(tmp / "hb_d")
    hb_e = _fresh_dir(tmp / "hb_e")
    ckpt = _fresh_dir(tmp / "ckpt")
    ref_ckpt = tmp / "ckpt_ref"
    if ref_ckpt.exists():
        shutil.rmtree(ref_ckpt)

    if args.plan:
        plan = FaultPlan.load(args.plan)
    else:
        plan = FaultPlan.generate(
            args.seed, [], R, hosts=P,
            host_crash_count=1 if args.host_fault == "crash" else 0,
            host_stall_count=1 if args.host_fault == "stall" else 0,
        )
    host_events = [e for e in plan.events
                   if e.kind in ("host_crash", "host_stall")]
    if not host_events:
        raise SystemExit("the hostchaos plan contains no host_crash/"
                         "host_stall event — nothing to drill")
    if len(host_events) > 1:
        # Phase C re-feeds the plan to the recovered mesh (surviving hosts'
        # remaining dcn events stay live), so a second terminal event would
        # kill a survivor mid-recovery with nobody supervising.  One terminal
        # fault per drill; run the harness again for the next one.
        raise SystemExit(
            f"the hostchaos drill handles ONE terminal host fault per run; "
            f"this plan has {len(host_events)} "
            f"({[e.to_dict() for e in host_events]}) — split it across runs"
        )
    max_dcn = max(
        (e.seconds for e in plan.events if e.kind == "dcn_degrade"),
        default=0.0,
    )
    if max_dcn >= args.watchdog_deadline:
        # The degraded host widens its OWN deadline by the injected delay,
        # but its peers cannot know the plan: their collectives absorb the
        # delay under the base deadline.  The documented contract is that a
        # degraded-but-alive link must NOT be misread as a dead peer — which
        # requires sizing the deadline above the worst planned delay.
        raise SystemExit(
            f"plan injects dcn_degrade of {max_dcn}s but "
            f"--watchdog-deadline is {args.watchdog_deadline}s: peers would "
            "misread the degraded link as a dead host — raise the deadline "
            "above the worst planned delay"
        )
    plan_path = tmp / "hostchaos_plan.json"
    plan.save(plan_path)

    metrics = resilience_metrics()
    if args.telemetry_dir is None:
        # Ours to wipe.  An OPERATOR-supplied dir is never rmtree'd — they may
        # point it at runs/ next to prior artifacts; records just append.
        telemetry_dir = _fresh_dir(tmp / "telemetry")
    else:
        telemetry_dir = Path(args.telemetry_dir)
        telemetry_dir.mkdir(parents=True, exist_ok=True)
    tel = RunTelemetry(telemetry_dir)
    # Bounded crash forensics: marks accumulate in-process and are dumped at
    # the reap — dump() create-if-missing and never raises, so a forensics
    # failure can never abort the recovery it is documenting.
    recorder = FlightRecorder(name="hostchaos-supervisor")
    all_pids: list[int] = []
    t0 = time.time()
    hosts = list(range(P))

    # ---- phase A: full mesh under the plan, run until the failure ----------
    print(f"# hostchaos: {P}-host mesh, plan: "
          + ", ".join(f"{e.kind}@r{e.round} host {e.host}"
                      for e in host_events), flush=True)
    progress_a = tmp / "progress_a.jsonl"
    progress_a.unlink(missing_ok=True)
    procs = _spawn_hostchaos(
        args, hosts, args.port, rounds=R, hb_dir=hb_a, ckpt_dir=ckpt,
        resume=False, plan_path=plan_path, out=tmp / "hc_a.json",
        progress=progress_a,
    )
    all_pids += [p.pid for p in procs]
    monitor = HostMonitor(hb_a, stall_timeout_s=args.stall_timeout)

    def _hb_status(host: int) -> str:
        try:
            return str(json.loads(
                (hb_a / f"host_{host}.hb.json").read_text()
            ).get("status", "?"))
        except (OSError, json.JSONDecodeError, ValueError):
            return "?"

    victim: int | None = None
    kind: str | None = None
    deadline = time.time() + args.timeout
    exits: dict[int, int] = {}
    exit_order: list[int] = []  # indices in the order their exits were seen
    while victim is None:
        for i, p in enumerate(procs):
            rc = p.poll()
            if rc is not None and i not in exits:
                exits[i] = rc
                exit_order.append(i)
                if rc == HOST_CRASH_RC:
                    victim, kind = hosts[i], "host_crash"
                    metrics["host_failures"].inc(kind=kind)
        if victim is None:
            stalled = monitor.stalled()
            if stalled:
                victim, kind = stalled[0].host, "host_stall"
        if victim is None and any(
            rc == PEER_FAILURE_RC for rc in exits.values()
        ):
            # At least one worker exited BLAMING a peer (watchdog / gloo
            # error).  A blaming worker is never the victim; neither is one
            # whose last heartbeat declared peer_failure (it may have been
            # killed mid-exit).  Once exactly one blameless worker remains —
            # still alive (a true stall) or collaterally killed when the
            # coordination service's leader went down — it is the victim.
            blaming = {
                i for i in range(len(procs))
                if exits.get(i) == PEER_FAILURE_RC
                or _hb_status(hosts[i]) == "peer_failure"
            }
            candidates = [i for i in range(len(procs)) if i not in blaming]
            all_blamers_exited = all(
                i in exits for i in range(len(procs)) if i in blaming
            )
            if len(candidates) == 1 and all_blamers_exited:
                i = candidates[0]
                victim = hosts[i]
                # Died BEFORE the first blame → it crashed on its own; died
                # after (or still silently alive) → the stall the blamers
                # timed out on.
                first_blame_pos = min(
                    exit_order.index(j) for j in blaming if j in exits
                ) if any(j in exits for j in blaming) else len(exit_order)
                died_first = (
                    i in exits and exit_order.index(i) < first_blame_pos
                )
                kind = "host_crash" if died_first else "host_stall"
                metrics["host_failures"].inc(kind=kind)
        if victim is None and len(exits) == len(procs):
            if all(rc == 0 for rc in exits.values()):
                _reap(procs)
                raise SystemExit(
                    "hostchaos: every worker completed without the planned "
                    "failure firing — raise --rounds or fix the plan"
                )
            # Every process exited.  Attribute only to a worker that failed
            # on its OWN account (non-zero, non-blaming): if every exit
            # blames a peer, the failure is systemic (e.g. a round-0 gloo
            # bring-up error hit everyone) and naming a victim would fabricate
            # a host_crash, exclude a healthy host, and mask the real cause.
            organic = [
                i for i in exit_order
                if exits[i] not in (0, PEER_FAILURE_RC)
            ]
            if not organic:
                _reap(procs)
                raise SystemExit(
                    f"hostchaos: every worker exited blaming a peer "
                    f"(exit codes {dict(sorted(exits.items()))}) — systemic "
                    "failure, no victim attributable; check the worker logs"
                )
            victim = hosts[organic[0]]
            kind = "host_crash"
            metrics["host_failures"].inc(kind=kind)
        if victim is None and time.time() > deadline:
            _reap(procs)
            raise SystemExit(f"hostchaos: no failure detected within "
                             f"{args.timeout:.0f}s")
        if victim is None:
            time.sleep(0.2)
    t_detect = time.time()
    recorder.note("kill_detected", host=victim, fault=kind)
    victim_hb = hb_a / f"host_{victim}.hb.json"
    last_beat_wall = None
    victim_round = None
    try:
        payload = json.loads(victim_hb.read_text())
        last_beat_wall = float(payload.get("wall_t", 0)) or None
        victim_round = payload.get("round")
    except (OSError, json.JSONDecodeError, ValueError):
        pass
    detection_s = (
        round(t_detect - last_beat_wall, 3) if last_beat_wall else None
    )
    # Kill and REAP everyone — survivors included: the old mesh is dead, and
    # an orphan blocked in gloo would hold the rendezvous port forever.
    # (Every detection path above already counted the failure by kind.)
    _reap(procs)
    recorder.note("reaped", victim=victim, fault=kind)
    dump_path = recorder.dump(
        telemetry_dir / FLIGHT_RECORDER_FILENAME,
        extra={"victim": victim, "kind": kind},
    )
    plan_round = next(
        (e.round for e in host_events if e.host == victim), victim_round
    )
    fail_round = plan_round if plan_round is not None else 0
    print(f"# failure detected: {kind} on host {victim} (round {fail_round}, "
          f"detection {detection_s}s) — reaped {len(procs)} workers",
          flush=True)
    tel.record(
        "host_failure", kind=kind, host=victim, round=fail_round,
        detection_s=detection_s,
        detail=f"exit codes {exits}" if exits else "heartbeat frozen",
    )

    # Reference snapshot BEFORE the recovered run extends the store: the
    # unfailed shrunk-mesh run must start from the identical recovery point.
    shutil.copytree(ckpt, ref_ckpt)
    rec = GenerationStore(ckpt).latest_complete()
    resumed_round = rec.round_number if rec is not None else 0
    resumed_gen = rec.generation if rec is not None else None
    rounds_lost = fail_round - resumed_round
    print(f"# recovery point: generation {resumed_gen} (round "
          f"{resumed_round}); rounds lost = {rounds_lost} (block size {B})",
          flush=True)

    # ---- phase C: re-form over the survivors, resume, finish the run -------
    survivors = [h for h in hosts if h != victim]
    metrics["mesh_reshapes"].inc()
    progress_c = tmp / "progress_c.jsonl"
    progress_c.unlink(missing_ok=True)
    procs = _spawn_hostchaos(
        args, survivors, args.port + 7, rounds=R, hb_dir=hb_c, ckpt_dir=ckpt,
        resume=True, plan_path=plan_path, out=tmp / "hc_c.json",
        progress=progress_c,
    )
    all_pids += [p.pid for p in procs]
    respawn_mark = recorder.note("respawned", hosts=survivors)
    _wait(procs, args.timeout)
    # S2: the telemetry dir (and the supervisor's stream in it) must survive
    # the crash + reap — a recovery drill whose evidence vanished proves
    # nothing.
    assert telemetry_dir.exists() and tel.path.exists(), (
        f"telemetry did not survive the worker crash: dir={telemetry_dir} "
        f"stream={tel.path}"
    )
    recovered = json.loads((tmp / "hc_c.json").read_text())
    prog_c = _read_progress(progress_c)
    if not prog_c:
        raise SystemExit("hostchaos: recovered run reported no rounds")
    mttr_s = round(prog_c[0]["wall_t"] - t_detect, 3)
    metrics["recovery_seconds"].observe(mttr_s)
    # Retroactive mark: map the first post-recovery round's wall clock onto
    # the recorder's monotonic axis via the respawn mark (both clocks were
    # read in this process).
    recorder.note(
        "first_progress", wall=round(prog_c[0]["wall_t"], 6),
        t_mono=round(
            respawn_mark["t_mono"]
            + max(0.0, prog_c[0]["wall_t"] - respawn_mark["t_wall"]),
            6,
        ),
    )
    mttr_phases = mttr_decomposition(recorder.snapshot(), [
        ("kill_detected", None),
        ("reaped", "reap"),
        ("respawned", "respawn"),
        ("first_progress", "recompile"),
    ])
    if detection_s is not None:
        # Detection is measured from the victim's LAST heartbeat, which
        # predates every recorder mark — prepend it rather than difference it.
        mttr_phases = {"detect": detection_s, **mttr_phases}
    recorder.dump(
        telemetry_dir / FLIGHT_RECORDER_FILENAME,
        extra={"victim": victim, "kind": kind, "mttr_phases": mttr_phases},
    )
    print(f"# mesh re-formed over hosts {survivors}: first post-recovery "
          f"round done {mttr_s}s after detection (MTTR: {mttr_phases})",
          flush=True)
    tel.record(
        "recovery", recovery_s=mttr_s, resumed_generation=resumed_gen,
        resumed_round=resumed_round, rounds_lost=rounds_lost,
        hosts_before=P, hosts_after=len(survivors), reshape=True,
        rejoin=False, mttr_phases=mttr_phases,
        flight_recorder=None if dump_path is None else str(dump_path),
    )

    # ---- phase D (optional): the failed host rejoins at a generation
    # boundary, mesh re-grows to the full host set --------------------------
    rejoin_block = None
    if args.rejoin_rounds > 0:
        metrics["mesh_reshapes"].inc()
        total = R + args.rejoin_rounds
        procs = _spawn_hostchaos(
            args, hosts, args.port + 13, rounds=total, hb_dir=hb_d,
            ckpt_dir=ckpt, resume=True, plan_path=None,
            out=tmp / "hc_d.json", progress=tmp / "progress_d.jsonl",
        )
        all_pids += [p.pid for p in procs]
        _wait(procs, args.timeout)
        rejoined = json.loads((tmp / "hc_d.json").read_text())
        rejoin_block = {
            "hosts": hosts,
            "resumed_round": rejoined["start_round"],
            "rounds": rejoined["rounds"],
            "losses": rejoined["losses"],
        }
        assert rejoined["rounds"] and rejoined["rounds"][-1] == total - 1, (
            f"rejoined mesh did not reach round {total - 1}: {rejoined}"
        )
        print(f"# host {victim} rejoined at round {rejoined['start_round']}: "
              f"full {P}-host mesh ran to round {total - 1}", flush=True)
        tel.record(
            "recovery", resumed_generation=rejoined["start_round"] // B,
            resumed_round=rejoined["start_round"], rounds_lost=0,
            hosts_before=len(survivors), hosts_after=P, reshape=True,
            rejoin=True,
        )

    # ---- phase E: the parity reference — an UNFAILED run on the same
    # shrunk mesh from the same recovery point ------------------------------
    procs = _spawn_hostchaos(
        args, survivors, args.port + 19, rounds=R, hb_dir=hb_e,
        ckpt_dir=ref_ckpt, resume=True, plan_path=None,
        out=tmp / "hc_e.json", progress=None,
    )
    all_pids += [p.pid for p in procs]
    _wait(procs, args.timeout)
    reference = json.loads((tmp / "hc_e.json").read_text())

    loss_delta = max(
        (abs(a - b) for a, b in
         zip(recovered["losses"], reference["losses"])),
        default=float("inf"),
    )
    orphans = no_orphans(all_pids)
    artifact = {
        "record_type": "hostchaos",
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "seed": args.seed,
        "plan": json.loads(plan.to_json()),
        "rounds": R,
        "block_size": B,
        "clients": args.clients,
        "model": args.model,
        "topology": {
            "hosts_before": P,
            "hosts_after": len(survivors),
            "devices_per_process": args.devices_per_process,
            "mesh_before": [P, args.devices_per_process, 1],
            "mesh_after": [len(survivors), args.devices_per_process, 1],
        },
        "failure": {
            "kind": kind,
            "host": victim,
            "round": fail_round,
            "detection_s": detection_s,
            "stall_timeout_s": args.stall_timeout,
            "watchdog_deadline_s": args.watchdog_deadline,
            "worker_exit_codes": {str(hosts[i]): rc
                                  for i, rc in sorted(exits.items())},
        },
        "recovery": {
            "mttr_s": mttr_s,
            "resumed_generation": resumed_gen,
            "resumed_round": resumed_round,
            "rounds_lost": rounds_lost,
            "at_most_one_block": rounds_lost <= B,
        },
        "pre_failure_losses": [p["loss"] for p in _read_progress(progress_a)],
        "recovered": {
            "rounds": recovered["rounds"], "losses": recovered["losses"],
        },
        "reference_unfailed_shrunk": {
            "rounds": reference["rounds"], "losses": reference["losses"],
        },
        "parity": {
            "max_loss_delta": loss_delta,
            "tolerance": args.parity_tol,
            "ok": loss_delta <= args.parity_tol,
        },
        "rejoin": rejoin_block,
        "orphans": orphans,
        "platform": "cpu",
        "basis": (
            "multi-process jax.distributed over loopback (gloo CPU "
            "collectives), virtual XLA host devices per process; the drill "
            "measures the RECOVERY MACHINERY — detection, reap, mesh "
            "re-formation, generation resume — not TPU silicon.  MTTR "
            "includes process respawn + jax bring-up + recompile on the "
            "shrunk mesh."
        ),
        "harness": "scripts/multihost_harness.py hostchaos",
        "walltime_s": round(time.time() - t0, 1),
    }
    tel.close()

    assert rounds_lost <= B, (
        f"at-most-one-block violated: lost {rounds_lost} rounds > block {B}"
    )
    assert loss_delta <= args.parity_tol, (
        f"post-recovery trajectory diverged from the unfailed shrunk-mesh "
        f"run: max loss delta {loss_delta} > {args.parity_tol}"
    )
    assert not orphans, f"orphan worker processes survived the run: {orphans}"
    assert recovered["rounds"][-1] == R - 1, recovered

    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = out_dir / f"hostchaos_{stamp}_{P}h.json"
    path.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact, indent=2))
    print(f"# artifact written to {path}")
    print(f"# telemetry: {telemetry_dir} (digest: python -m nanofed_tpu.cli "
          f"metrics-summary {telemetry_dir})")
    print(f"hostchaos OK: {kind} on host {victim} at round {fail_round} -> "
          f"recovered on {len(survivors)} host(s) in {mttr_s}s, "
          f"{rounds_lost} round(s) re-run (<= {B}), parity delta "
          f"{loss_delta:.2e}, zero orphans")
    return 0


def _spawn_federate(
    args: argparse.Namespace,
    host_ids: list[int],
    port: int,
    *,
    phase: str,
    hb_dir: Path,
    ckpt_dir: Path,
    resume: bool,
    plan_path: Path | None,
    stop_file: Path,
    tmp: Path,
    telemetry_dir: Path | None = None,
) -> list[subprocess.Popen]:
    """One federate worker per LOGICAL host id (dense process ids per phase,
    stable host ids across the kill — same convention as hostchaos).  Every
    worker gets its own ready/progress/result files: the supervisor reads
    per-host round stats even from a phase that ends in a reap."""
    procs = []
    n = len(host_ids)
    for pid, host in enumerate(host_ids):
        cmd = [
            sys.executable, str(Path(__file__).resolve()), "worker",
            "--job", "federate",
            "--process-id", str(pid),
            "--num-processes", str(n),
            "--coordinator", f"localhost:{port}",
            "--hosts", str(n),
            "--rounds", str(args.max_rounds),
            "--model", args.model,
            "--seed", str(args.seed),
            "--devices-per-process", str(args.devices_per_process),
            "--block-size", str(args.block_size),
            "--watchdog-deadline", str(args.federate_watchdog),
            "--host-id", str(host),
            "--hosts-list", ",".join(str(h) for h in host_ids),
            "--hb-dir", str(hb_dir),
            "--ckpt-dir", str(ckpt_dir),
            "--wire-port", str(args.wire_port),
            "--ingest-capacity", str(args.ingest_capacity),
            "--staleness-window", str(args.staleness_window),
            "--round-quota", str(args.round_quota),
            "--min-completion-rate", str(args.min_completion_rate),
            "--round-timeout-s", str(args.round_timeout_s),
            "--stop-file", str(stop_file),
            "--ready-file", str(tmp / f"fed_ready_h{host}.json"),
            "--progress", str(tmp / f"fed_progress_{phase}_h{host}.jsonl"),
            "--out", str(tmp / f"fed_result_{phase}_h{host}.json"),
        ]
        if resume:
            cmd += ["--resume"]
        if plan_path is not None:
            cmd += ["--fault-plan", str(plan_path)]
        if telemetry_dir is not None:
            # Each worker appends its own stream under host_<h>/ — one
            # telemetry.jsonl per process, merged by `nanofed-tpu trace`.
            cmd += ["--telemetry-dir", str(telemetry_dir)]
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, pid)))
    return procs


def run_federate(args: argparse.Namespace) -> int:
    """ONE STACK: wire clients drain straight into the hierarchical mesh
    reduce.  W jax.distributed mesh hosts each run an HTTP listener + device
    ingest buffer; the loadgen swarm drives the wire population against them
    (VirtualClock schedule, real sockets); each round is host-local drains +
    ONE cross-host psum.  With ``--kill-round`` a seeded plan crashes one
    host mid-campaign: its wire clients reroute to survivors live
    (retry/rotation/dedup), the mesh re-forms over the survivors from the
    newest committed generation, and the dead host's population re-drives —
    zero lost submits, asserted."""
    import asyncio

    import numpy as np

    from nanofed_tpu.communication.retry import RetryPolicy
    from nanofed_tpu.faults.plan import FaultEvent, FaultPlan
    from nanofed_tpu.loadgen.swarm import SwarmConfig, latency_digest, run_swarm
    from nanofed_tpu.observability.critical_path import federation_timeline
    from nanofed_tpu.observability.telemetry import RunTelemetry
    from nanofed_tpu.observability.tracing import (
        FLIGHT_RECORDER_FILENAME,
        FlightRecorder,
        mttr_decomposition,
    )
    from nanofed_tpu.parallel.resilience import no_orphans
    from nanofed_tpu.persistence import GenerationStore
    from nanofed_tpu.utils.clock import VirtualClock

    if args.num_processes < 2:
        raise SystemExit("federate needs --num-processes >= 2 (one wire "
                         "listener per mesh host)")
    P = args.num_processes
    tmp = Path(args.tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    hb_dir = _fresh_dir(tmp / "fed_hb")
    ckpt = _fresh_dir(tmp / "fed_ckpt")
    stop_file = tmp / "federate_stop"
    stop_file.unlink(missing_ok=True)
    for stale in list(tmp.glob("fed_result_*.json")) + list(
        tmp.glob("fed_progress_*.jsonl")
    ):
        stale.unlink()

    hosts = list(range(P))
    counts = [args.clients // P + (1 if i < args.clients % P else 0)
              for i in range(P)]
    urls = [f"http://127.0.0.1:{args.wire_port + h}" for h in hosts]

    kill = args.kill_round is not None
    victim = args.kill_host if args.kill_host is not None else P - 1
    plan = None
    plan_path = None
    if kill:
        plan = FaultPlan(seed=args.seed, events=(
            FaultEvent(kind="host_crash", round=args.kill_round, host=victim),
        ))
        plan_path = tmp / "federate_plan.json"
        plan.save(plan_path)

    # Canned payload base = the same deterministic init the workers publish,
    # so the servers' delta reconstruction lands on base + noise exactly.
    import jax

    from nanofed_tpu.models import get_model

    base_params = jax.tree.map(
        np.asarray, get_model(args.model).init(jax.random.key(args.seed))
    )

    if args.telemetry_dir is None:
        telemetry_dir = _fresh_dir(tmp / "fed_telemetry")
    else:
        telemetry_dir = Path(args.telemetry_dir)
        telemetry_dir.mkdir(parents=True, exist_ok=True)

    # Crash flight recorder: every supervisor lifecycle mark lands in this
    # bounded ring; on reaping a crashed host the ring dumps next to the
    # telemetry (dump() creates missing dirs and never raises — a forensics
    # failure must not break the recovery it documents), and the marks
    # decompose the recovery's MTTR into named phases.
    recorder = FlightRecorder(name="federate-supervisor")

    all_pids: list[int] = []
    t0 = time.time()

    def _retry(seed: int) -> RetryPolicy:
        # Generous on purpose: backoffs ride the VirtualClock (~no real
        # time), and zero lost submits means no client may exhaust while a
        # reroute target is still alive.
        return RetryPolicy(max_attempts=64, base_backoff_s=0.05,
                           max_backoff_s=1.0, multiplier=1.5,
                           budget_s=None, seed=seed)

    def _wait_ready(procs: list, live_hosts: list[int]) -> None:
        deadline = time.time() + args.timeout
        paths = {h: tmp / f"fed_ready_h{h}.json" for h in live_hosts}
        ready: set[int] = set()
        while len(ready) < len(paths):
            for h, p in paths.items():
                if h not in ready and p.exists():
                    ready.add(h)
            for q in procs:
                rc = q.poll()
                if rc is not None:
                    _reap(procs)
                    raise SystemExit(
                        f"federate worker exited rc={rc} during bring-up"
                    )
            if time.time() > deadline:
                _reap(procs)
                raise SystemExit("federate workers not ready within "
                                 f"{args.timeout:.0f}s")
            time.sleep(0.1)

    async def _drive(procs: list, live_hosts: list[int], jobs: list,
                     expect_kill: bool) -> tuple[list, dict]:
        """Run the sub-swarms concurrently with a worker monitor.  The
        monitor's stop decisions are what keep 'zero lost submits' true: a
        pending submit aimed at a doomed fleet is terminated early (and
        re-driven next phase), never left to exhaust its retries as a
        failure."""
        stop_event = asyncio.Event()
        clock = VirtualClock()
        state: dict = {"t_kill": None, "unexpected": None}

        async def monitor() -> None:
            while not stop_event.is_set():
                rcs = [q.poll() for q in procs]
                for h, rc in zip(live_hosts, rcs):
                    if rc is None:
                        continue
                    if rc == HOST_CRASH_RC and expect_kill and h == victim:
                        if state["t_kill"] is None:
                            state["t_kill"] = time.time()
                            recorder.note("kill_detected", host=h, rc=rc)
                            print(f"# host {h} killed by plan (rc={rc}); "
                                  "wire clients rerouting to survivors for "
                                  f"{args.reroute_grace:.1f}s", flush=True)
                    elif rc == PEER_FAILURE_RC and expect_kill:
                        # A survivor's watchdog fired before the grace ended:
                        # stop the swarm now — pending submits terminate
                        # early instead of failing against a dead fleet.
                        stop_event.set()
                        return
                    else:
                        state["unexpected"] = (h, rc)
                        stop_event.set()
                        return
                if state["t_kill"] is not None and (
                    time.time() - state["t_kill"] >= args.reroute_grace
                ):
                    # Reroutes demonstrated live; the remaining population
                    # re-drives against the recovered mesh in phase C.
                    recorder.note("grace_elapsed",
                                  grace_s=args.reroute_grace)
                    stop_event.set()
                    return
                if all(rc is not None for rc in rcs):
                    stop_event.set()
                    return
                await asyncio.sleep(0.2)  # REAL time: process liveness poll

        mon = asyncio.ensure_future(monitor())
        try:
            results = await asyncio.gather(*(
                run_swarm(url, base_params, cfg, clock=clock,
                          stop=stop_event, client_indices=idx)
                for url, cfg, idx in jobs
            ))
        finally:
            stop_event.set()
            mon.cancel()
            try:
                await mon
            except (asyncio.CancelledError, Exception):
                pass
        return results, state

    def _cfg(owner: int, phase_salt: int, failover: tuple[str, ...],
             n_clients: int) -> "SwarmConfig":
        return SwarmConfig(
            num_clients=n_clients,
            submits_per_client=args.submits_per_client,
            arrival="uniform",
            arrival_rate=args.arrival_rate,
            seed=args.seed + 17 * owner + phase_salt,
            retry=_retry(args.seed + 31 * owner + phase_salt),
            client_prefix=f"h{owner}",
            failover_urls=failover,
            connector_limit=256,
            canned_payloads=4,
        )

    # ---- phase A: full mesh, full population -------------------------------
    for h in hosts:
        (tmp / f"fed_ready_h{h}.json").unlink(missing_ok=True)
    print(f"# federate: {P} mesh hosts x wire listeners, {args.clients} wire "
          "clients"
          + (f"; planned host_crash on host {victim} at round "
             f"{args.kill_round}" if kill else ""), flush=True)
    procs = _spawn_federate(
        args, hosts, args.port, phase="a", hb_dir=hb_dir, ckpt_dir=ckpt,
        resume=False, plan_path=plan_path, stop_file=stop_file, tmp=tmp,
        telemetry_dir=telemetry_dir,
    )
    all_pids += [p.pid for p in procs]
    recorder.note("spawned", phase="a", hosts=hosts)
    _wait_ready(procs, hosts)
    recorder.note("fleet_ready", phase="a", hosts=hosts)
    print("# all listeners ready; releasing the swarm", flush=True)

    jobs_a = [
        (urls[h],
         _cfg(h, 0, tuple(urls[j] for j in hosts if j != h), counts[h]),
         None)
        for h in hosts
    ]
    results_a, state_a = asyncio.run(_drive(procs, hosts, jobs_a, kill))
    swarm_a = dict(zip(hosts, results_a))
    if state_a["unexpected"] is not None:
        _reap(procs)
        raise SystemExit(
            f"federate worker host {state_a['unexpected'][0]} exited "
            f"rc={state_a['unexpected'][1]} mid-campaign"
        )

    results_c: dict[int, object] = {}
    survivors = hosts
    recovery = None
    if not kill:
        stop_file.write_text("stop\n")
        _wait(procs, args.timeout)
    else:
        if state_a["t_kill"] is None:
            _reap(procs)
            raise SystemExit("kill was planned but the victim never died — "
                             "lower --kill-round or raise the population")
        # The survivors are blocked in a psum the dead victim will never
        # join: phase A is over for them.  Reap and re-form.
        _reap(procs)
        recorder.note("reaped", victim=victim, phase="a")
        # Dump the ring NEXT TO the telemetry the moment the crashed host is
        # reaped: dump() creates missing parents and never raises, so this
        # cannot break the recovery it documents.
        dump_path = recorder.dump(
            telemetry_dir / FLIGHT_RECORDER_FILENAME,
            extra={"victim": victim, "kill_round": args.kill_round},
        )
        survivors = [h for h in hosts if h != victim]
        rec = GenerationStore(ckpt).latest_complete()
        resumed_round = rec.round_number if rec is not None else 0
        recovery = {
            "victim": victim,
            "kill_round": args.kill_round,
            "reroute_grace_s": args.reroute_grace,
            "resumed_generation": rec.generation if rec is not None else None,
            "resumed_round": resumed_round,
            "hosts_after": len(survivors),
            "flight_recorder": None if dump_path is None else str(dump_path),
        }
        print(f"# phase C: re-forming over hosts {survivors}, resuming at "
              f"round {resumed_round}; re-driving the dead host's "
              f"{counts[victim]} wire clients", flush=True)

        for h in survivors:
            (tmp / f"fed_ready_h{h}.json").unlink(missing_ok=True)
        procs = _spawn_federate(
            args, survivors, args.port + 7, phase="c", hb_dir=hb_dir,
            ckpt_dir=ckpt, resume=True, plan_path=None, stop_file=stop_file,
            tmp=tmp, telemetry_dir=telemetry_dir,
        )
        all_pids += [p.pid for p in procs]
        recorder.note("respawned", phase="c", hosts=survivors)
        _wait_ready(procs, survivors)
        ready_mark = recorder.note("ready", phase="c", hosts=survivors)

        surv_urls = [urls[h] for h in survivors]
        # The victim's whole population re-drives against the survivors: its
        # listener is gone, and anything a survivor accepted after the last
        # committed generation died undrained with phase A (the same
        # at-most-one-block unit hostchaos drills).  Survivors' clients that
        # terminated early when the swarm stopped re-drive too.
        # Stripe the victim's population across the survivors (one job per
        # survivor, disjoint index stripes) instead of pointing 25k clients
        # at one primary URL: rotation-on-failure balances a CRASH, but a
        # re-drive is a planned dispatch — spread it up front.
        owners = []
        jobs_c = []
        for j, s in enumerate(survivors):
            stripe = list(range(counts[victim]))[j::len(survivors)]
            if not stripe:
                continue
            owners.append(victim)
            jobs_c.append((
                urls[s],
                _cfg(victim, 1 + j,
                     tuple(u for u in surv_urls if u != urls[s]),
                     counts[victim]),
                stripe,
            ))
        for h in survivors:
            missing = sorted(
                set(range(counts[h])) - set(swarm_a[h].completed_indices)
            )
            if missing:
                owners.append(h)
                jobs_c.append((
                    urls[h],
                    _cfg(h, 1,
                         tuple(u for u in surv_urls if u != urls[h]),
                         counts[h]),
                    missing,
                ))
        results, state_c = asyncio.run(_drive(procs, survivors, jobs_c, False))
        if state_c["unexpected"] is not None:
            _reap(procs)
            raise SystemExit(
                f"federate worker host {state_c['unexpected'][0]} exited "
                f"rc={state_c['unexpected'][1]} during recovery"
            )
        results_c = {}
        for owner, res in zip(owners, results):
            prev = results_c.get(owner)
            if prev is None:
                results_c[owner] = res
            else:
                # The victim's population runs as one stripe per survivor:
                # fold the stripes back into one per-owner ledger.
                prev.latencies_s += res.latencies_s
                prev.accepted += res.accepted
                prev.duplicates += res.duplicates
                prev.rejected_429 += res.rejected_429
                prev.retries += res.retries
                prev.stale_refreshes += res.stale_refreshes
                prev.failed += res.failed
                prev.terminated_early += res.terminated_early
                prev.reroutes += res.reroutes
                prev.completed_indices += res.completed_indices
        stop_file.write_text("stop\n")
        _wait(procs, args.timeout)
        # MTTR decomposition: "recompile" ends at the recovered fleet's first
        # drained round.  That mark is only observable from the phase-C
        # progress streams after the fact, so it is noted retroactively —
        # its wall stamp mapped onto the monotonic axis via the ready mark.
        first_wall = None
        for h in survivors:
            lines = _read_progress(tmp / f"fed_progress_c_h{h}.jsonl")
            if lines:
                w = lines[0].get("wall_t")
                if w is not None and (first_wall is None or w < first_wall):
                    first_wall = float(w)
        if first_wall is not None:
            recorder.note(
                "first_progress", wall=round(first_wall, 6),
                t_mono=round(
                    ready_mark["t_mono"]
                    + max(0.0, first_wall - ready_mark["t_wall"]), 6,
                ),
            )
        mttr_phases = mttr_decomposition(recorder.snapshot(), [
            ("kill_detected", None),
            ("grace_elapsed", "reroute_grace"),
            ("reaped", "reap"),
            ("respawned", "respawn"),
            ("ready", "bring_up"),
            ("first_progress", "recompile"),
        ])
        recovery["mttr_phases"] = mttr_phases
        recovery["recovery_s"] = round(sum(mttr_phases.values()), 3)
        # Re-dump with the recovery marks included: the reap-time dump froze
        # the crash context; this one appends the phases that followed.
        recorder.dump(
            telemetry_dir / FLIGHT_RECORDER_FILENAME,
            extra={"victim": victim, "kill_round": args.kill_round,
                   "mttr_phases": mttr_phases},
        )

    # ---- accounting + assertions ------------------------------------------
    all_results = list(swarm_a.values()) + list(results_c.values())
    latencies = [x for r in all_results for x in r.latencies_s]
    digest = latency_digest(latencies)
    failed = sum(r.failed for r in all_results)
    reroutes = sum(r.reroutes for r in all_results)
    accepted = sum(r.accepted for r in all_results)
    duplicates = sum(r.duplicates for r in all_results)
    terminated = sum(r.terminated_early for r in all_results)

    lost: dict[int, int] = {}
    for h in hosts:
        done = set(swarm_a[h].completed_indices)
        if h in results_c:
            done |= set(results_c[h].completed_indices)
        missing_n = counts[h] - len(done & set(range(counts[h])))
        if missing_n:
            lost[h] = missing_n

    progress_lines: list[dict] = []
    per_host_phase_a: dict[int, int] = {}
    for phase in ("a", "c"):
        for h in hosts:
            lines = _read_progress(tmp / f"fed_progress_{phase}_h{h}.jsonl")
            if phase == "a":
                per_host_phase_a[h] = len(lines)
            progress_lines += lines
    durations = sorted(ln["duration_s"] for ln in progress_lines)
    median_round = durations[len(durations) // 2] if durations else None
    drained_total = sum(ln["drained"] for ln in progress_lines)
    rerouted_drained = sum(ln.get("rerouted_in", 0) for ln in progress_lines)
    orphans = no_orphans(all_pids)

    assert failed == 0, (
        f"lost submits: {failed} logical submits never got a 200 "
        f"(per-host: {[(h, swarm_a[h].failed) for h in hosts]})"
    )
    assert not lost, (
        f"clients never completed across phases (host -> count): {lost}"
    )
    assert all(per_host_phase_a[h] > 0 for h in hosts), (
        f"a host drained no rounds in phase A: {per_host_phase_a}"
    )
    if kill:
        assert reroutes > 0, (
            "the kill fired but no wire client rerouted — the grace window "
            "closed before any submit hit the dead listener"
        )
        assert rerouted_drained > 0, (
            "no rerouted client's update was ever drained by another host"
        )
    assert not orphans, f"orphan worker processes survived the run: {orphans}"
    if kill:
        # The telemetry dir — including the dead host's stream — must
        # survive a worker crash: the merged timeline is exactly the
        # artifact a post-mortem needs, so losing it to the reap path
        # would defeat the flight recorder's purpose.
        worker_streams = list(telemetry_dir.glob("host_*/telemetry.jsonl"))
        assert telemetry_dir.exists() and len(worker_streams) >= P, (
            f"telemetry did not survive the crash: {telemetry_dir} has "
            f"{len(worker_streams)} worker streams, expected >= {P}"
        )

    # Merged-timeline digest (clock-aligned at the bring-up-barrier epoch):
    # the per-round critical-path table and the submit->round trace
    # resolution ride the artifact — the evidence a reader checks first.
    timeline = federation_timeline(telemetry_dir)

    artifact = {
        "record_type": "federation",
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "seed": args.seed,
        "model": args.model,
        "wire_clients": args.clients,
        "submits_per_client": args.submits_per_client,
        "per_host_clients": counts,
        "topology": {
            "hosts": P,
            "devices_per_process": args.devices_per_process,
            # Hosts-only reduce mesh: one device per process, so the round's
            # cross-host psum compiles to one all-reduce with one replica
            # group (one gloo stream per beat).
            "mesh_shape": [P, 1, 1],
            "wire_ports": [args.wire_port + h for h in hosts],
            "survivors": survivors,
        },
        "rounds": {
            "drained_rounds": len(progress_lines),
            "median_round_s": median_round,
            "rounds_per_sec": (
                round(1.0 / median_round, 4) if median_round else None
            ),
            "round_quota": args.round_quota,
            "min_completion_rate": args.min_completion_rate,
            "updates_aggregated": drained_total,
        },
        "wire": {
            "accepted": accepted,
            "duplicates": duplicates,
            "failed": failed,
            "terminated_early_redriven": terminated,
            "reroutes": reroutes,
            "rerouted_updates_drained": rerouted_drained,
            "submit_latency": digest,
        },
        "chaos": (
            {"plan": json.loads(plan.to_json()), **recovery}
            if kill else None
        ),
        "critical_path": {
            "rounds": timeline["rounds"],
            "segments": timeline.get("segments"),
            "coverage": timeline.get("coverage"),
        },
        "trace_resolution": timeline["trace_resolution"],
        "zero_lost_submits": True,
        "orphans": orphans,
        "platform": "cpu",
        "basis": (
            "multi-process jax.distributed over loopback (gloo CPU "
            "collectives) with a REAL aiohttp wire tier: each mesh host runs "
            "an HTTP listener + device ingest buffer, drains host-locally "
            "(the buffer's batched coefs @ buffer reduce), and joins ONE "
            "cross-host psum per round.  The swarm's arrival schedule and "
            "backoffs ride a VirtualClock; submit latencies are real "
            "wall-clock against live sockets.  Measures the fused "
            "wire-to-mesh PROGRAM and protocol at population scale, not TPU "
            "silicon."
        ),
        "harness": "scripts/multihost_harness.py federate",
        "walltime_s": round(time.time() - t0, 1),
    }
    tel = RunTelemetry(telemetry_dir)
    tel.record(
        "federation",
        wire_clients=args.clients,
        hosts=P,
        survivors=len(survivors),
        rounds=len(progress_lines),
        rounds_per_sec=artifact["rounds"]["rounds_per_sec"],
        p99_submit_s=digest["p99_s"],
        accepted=accepted,
        duplicates=duplicates,
        failed=failed,
        reroutes=reroutes,
        rerouted_updates_drained=rerouted_drained,
        terminated_early_redriven=terminated,
        zero_lost_submits=True,
        host_killed=victim if kill else None,
        kill_round=args.kill_round,
    )
    if kill:
        tel.record(
            "host_failure", kind="host_crash", host=victim,
            round=args.kill_round,
        )
        tel.record("recovery", **recovery)
    tel.close()

    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = out_dir / f"{args.artifact_prefix}_{stamp}_{P}h.json"
    path.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact, indent=2))
    print(f"# artifact written to {path}")
    print(f"# telemetry: {telemetry_dir} (digest: python -m nanofed_tpu.cli "
          f"metrics-summary {telemetry_dir})")
    print(f"# merged timeline: python -m nanofed_tpu.cli trace "
          f"{telemetry_dir} --chrome-out /tmp/nanofed_timeline.json")
    print(f"federate OK: {args.clients} wire clients over {P} hosts, "
          f"{len(progress_lines)} drained rounds, p99 submit "
          f"{digest['p99_s']}s, {reroutes} reroutes, zero lost submits")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "mode", choices=["smoke", "bench", "hostchaos", "federate", "worker"],
        help="smoke: 2-process parity vs 1-D reference; bench: 100k-client "
        "throughput artifact; hostchaos: seeded kill-and-recover drill with "
        "elastic mesh re-formation; federate: wire swarm drains straight "
        "into the hierarchical mesh reduce (listener per host, one "
        "cross-host psum per round, optional mid-campaign host kill); "
        "worker: internal (one jax.distributed process)",
    )
    parser.add_argument("--clients", type=int, default=None)
    parser.add_argument("--capacity", type=int, default=8,
                        help="packed samples per client")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=3,
                        help="timed rounds (one extra warm-up round compiles)")
    parser.add_argument("--model", default="digits_mlp")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--client-chunk", type=int, default=None)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--devices-per-process", type=int, default=4)
    parser.add_argument("--hosts", type=int, default=1,
                        help="(worker) hosts-axis size of the mesh")
    parser.add_argument("--process-id", type=int, default=0)
    parser.add_argument("--coordinator", default="localhost:12421")
    parser.add_argument("--port", type=int, default=12421)
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="per-phase worker timeout (tier-1-safe)")
    parser.add_argument("--job",
                        choices=["smoke", "bench", "hostchaos", "federate"],
                        default="smoke",
                        help="(worker) which launcher job this worker serves "
                        "— a FULL flag name: an abbreviated --mod* would "
                        "prefix-match argparse's --model and corrupt it")
    parser.add_argument("--out", default=None, help="(worker) result JSON path")
    parser.add_argument("--out-dir", default="runs")
    parser.add_argument("--tmp-dir", default="/tmp/nanofed_multihost")
    # hostchaos: supervisor knobs (fault selection, detection windows, parity)
    parser.add_argument("--plan", default=None,
                        help="(hostchaos) fault-plan JSON; default: generate "
                        "one host fault from --seed")
    parser.add_argument("--host-fault", choices=["crash", "stall"],
                        default="crash",
                        help="(hostchaos) which host fault the generated plan "
                        "draws")
    parser.add_argument("--block-size", type=int, default=2,
                        help="rounds per checkpoint generation (the at-most-"
                        "one-block loss unit)")
    parser.add_argument("--stall-timeout", type=float, default=15.0,
                        help="(hostchaos) heartbeat age that flags a host as "
                        "stalled")
    parser.add_argument("--watchdog-deadline", type=float, default=20.0,
                        help="cross-host dispatch deadline (the bounded "
                        "detection window for a dead/stalled peer)")
    parser.add_argument("--compile-grace", type=float, default=90.0,
                        help="extra watchdog allowance for the first dispatch "
                        "(trace+compile must not read as a dead peer)")
    parser.add_argument("--parity-tol", type=float, default=SMOKE_TOL,
                        help="(hostchaos) max post-recovery loss delta vs the "
                        "unfailed shrunk-mesh reference")
    parser.add_argument("--rejoin-rounds", type=int, default=2,
                        help="(hostchaos) extra rounds after the failed host "
                        "rejoins the mesh (0 disables the rejoin phase)")
    parser.add_argument("--telemetry-dir", default=None,
                        help="(hostchaos/federate) where the supervisor "
                        "writes telemetry.jsonl (default under --tmp-dir)")
    # hostchaos: worker-side identity + wiring (set by the supervisor)
    parser.add_argument("--fault-plan", default=None,
                        help="(worker) fault-plan JSON path")
    parser.add_argument("--host-id", type=int, default=0,
                        help="(worker) LOGICAL host id — stable across "
                        "reshapes, unlike the dense process id")
    parser.add_argument("--hosts-list", default="0",
                        help="(worker) comma-separated logical host ids of "
                        "the current mesh (the commit-marker participant set)")
    parser.add_argument("--hb-dir", default="/tmp/nanofed_multihost/hb")
    parser.add_argument("--ckpt-dir", default="/tmp/nanofed_multihost/ckpt")
    parser.add_argument("--progress", default=None,
                        help="(worker) per-round progress JSONL path")
    parser.add_argument("--resume", action="store_true",
                        help="(worker) resume from the newest complete "
                        "generation in --ckpt-dir")
    # federate: wire tier + round pacing (supervisor) and listener wiring
    # (worker, set by the supervisor)
    parser.add_argument("--wire-port", type=int, default=18480,
                        help="(federate) base HTTP port; host h listens on "
                        "wire-port + h")
    parser.add_argument("--round-quota", type=int, default=1024,
                        help="(federate) accepted updates a host waits for "
                        "before draining its round")
    parser.add_argument("--min-completion-rate", type=float, default=1.0,
                        help="(federate) fraction of --round-quota that "
                        "counts the round COMPLETED in the ledger")
    parser.add_argument("--round-timeout-s", type=float, default=10.0,
                        help="(federate) round beat period: deadlines are "
                        "shared offsets from the bring-up-barrier epoch, so "
                        "hosts dispatch the cross-host psum near-"
                        "simultaneously regardless of quota skew; must stay "
                        "well under XLA's fixed 30s gloo collective timeout")
    parser.add_argument("--ingest-capacity", type=int, default=8192,
                        help="(federate) DeviceIngestBuffer slots per host — "
                        "size for the failover worst case: one survivor "
                        "absorbs a dead host's whole undrained population")
    parser.add_argument("--staleness-window", type=int, default=8,
                        help="(federate) server staleness window; the worker "
                        "floors it at 1 (window 0 clears accepted-but-"
                        "undrained submits on every publish)")
    parser.add_argument("--submits-per-client", type=int, default=1)
    parser.add_argument("--arrival-rate", type=float, default=4000.0,
                        help="(federate) swarm arrivals/s per host on the "
                        "virtual clock")
    parser.add_argument("--max-rounds", type=int, default=10_000,
                        help="(federate) worker round ceiling; the campaign "
                        "normally ends by stop-file consensus when the "
                        "swarm is drained")
    parser.add_argument("--kill-round", type=int, default=None,
                        help="(federate) plan a host_crash at this round; "
                        "omit for a no-chaos campaign")
    parser.add_argument("--kill-host", type=int, default=None,
                        help="(federate) logical host the plan kills "
                        "(default: the last host)")
    parser.add_argument("--reroute-grace", type=float, default=6.0,
                        help="(federate) real seconds of live rerouting to "
                        "survivors after the kill before the swarm pauses "
                        "for mesh re-formation")
    parser.add_argument("--federate-watchdog", type=float, default=240.0,
                        help="(federate) cross-host dispatch deadline — "
                        "generous: round cadence is swarm-driven")
    parser.add_argument("--artifact-prefix", default="federation",
                        help="(federate) artifact filename prefix under "
                        "--out-dir")
    parser.add_argument("--stop-file", default=None,
                        help="(worker) path whose existence votes to stop "
                        "the campaign")
    parser.add_argument("--ready-file", default=None,
                        help="(worker) JSON written once the wire listener "
                        "is up and the mesh barrier has passed")
    args = parser.parse_args(argv)

    if args.clients is None:
        if args.mode == "bench":
            args.clients = 100_000
        elif args.mode == "federate":
            args.clients = 2000
        else:
            args.clients = 16
    if args.mode == "worker":
        return run_worker(args)
    if args.mode == "smoke":
        return run_smoke(args)
    if args.mode == "hostchaos":
        return run_hostchaos(args)
    if args.mode == "federate":
        return run_federate(args)
    return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
