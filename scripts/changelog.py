#!/usr/bin/env python
"""Conventional-commit changelog generator.

Capability parity with the reference's release tooling (``scripts/changelog.py`` in
camille-004/nanofed): groups commits since the last tag (or a given range) by
conventional-commit type and emits a markdown section ready to paste into CHANGELOG.md.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys
from collections import defaultdict
from datetime import date

SECTIONS = {
    "feat": "Features",
    "fix": "Bug Fixes",
    "perf": "Performance",
    "refactor": "Refactoring",
    "docs": "Documentation",
    "test": "Tests",
    "build": "Build",
    "ci": "CI",
    "chore": "Chores",
}
_PATTERN = re.compile(
    r"^(?P<type>[a-z]+)(?:\((?P<scope>[^)]*)\))?(?P<bang>!)?:\s*(?P<desc>.+)$"
)


def git_log(rev_range: str | None) -> list[tuple[str, str]]:
    cmd = ["git", "log", "--pretty=format:%h%x00%s"]
    if rev_range:
        cmd.append(rev_range)
    out = subprocess.run(cmd, capture_output=True, text=True, check=True).stdout
    return [tuple(line.split("\x00", 1)) for line in out.splitlines() if "\x00" in line]


def last_tag() -> str | None:
    proc = subprocess.run(
        ["git", "describe", "--tags", "--abbrev=0"], capture_output=True, text=True
    )
    return proc.stdout.strip() or None


def build_changelog(version: str, rev_range: str | None) -> str:
    grouped: dict[str, list[str]] = defaultdict(list)
    breaking: list[str] = []
    for sha, subject in git_log(rev_range):
        m = _PATTERN.match(subject)
        if not m:
            grouped["other"].append(f"- {subject} ({sha})")
            continue
        scope = f"**{m['scope']}**: " if m["scope"] else ""
        entry = f"- {scope}{m['desc']} ({sha})"
        if m["bang"]:
            breaking.append(entry)
        grouped[m["type"]].append(entry)

    lines = [f"## {version} ({date.today().isoformat()})", ""]
    if breaking:
        lines += ["### BREAKING CHANGES", "", *breaking, ""]
    for key, title in SECTIONS.items():
        if grouped.get(key):
            lines += [f"### {title}", "", *grouped[key], ""]
    if grouped.get("other"):
        lines += ["### Other", "", *grouped["other"], ""]
    return "\n".join(lines)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("version", help="version heading, e.g. v0.2.0")
    parser.add_argument(
        "--since", default=None,
        help="start ref (default: last tag; full history if none)",
    )
    args = parser.parse_args()
    since = args.since if args.since is not None else last_tag()
    rev_range = f"{since}..HEAD" if since else None
    print(build_changelog(args.version, rev_range))
    return 0


if __name__ == "__main__":
    sys.exit(main())
