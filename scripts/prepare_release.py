#!/usr/bin/env python
"""Prepare a release: bump the version everywhere, refresh the changelog, template the
release notes, and sanity-check the tree.

Capability parity with the reference's ``scripts/prepare_release.py`` (version bump +
release-notes templating driven by the changelog), re-built for this repo's layout
(pyproject.toml + ``nanofed_tpu.__version__`` + CHANGELOG.md + docs/releases/).

Usage:
    python scripts/prepare_release.py 0.2.0            # do it
    python scripts/prepare_release.py 0.2.0 --dry-run  # show the plan only

Then review, commit, and run ``scripts/release.sh`` to tag and push (the tag triggers
``.github/workflows/release.yml``).
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys
from datetime import date
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
VERSION_RE = re.compile(r"^\d+\.\d+\.\d+(?:[a-z]+\d*)?$")

FILES = {
    REPO / "pyproject.toml": re.compile(r'^(version = ")([^"]+)(")$', re.M),
    REPO / "nanofed_tpu" / "__init__.py": re.compile(r'^(__version__ = ")([^"]+)(")$', re.M),
}


def current_version() -> str:
    text = (REPO / "pyproject.toml").read_text()
    m = FILES[REPO / "pyproject.toml"].search(text)
    if not m:
        raise SystemExit("could not find version in pyproject.toml")
    return m.group(2)


def bump(new: str, dry: bool) -> None:
    for path, pattern in FILES.items():
        text = path.read_text()
        updated, n = pattern.subn(rf"\g<1>{new}\g<3>", text)
        if n != 1:
            raise SystemExit(f"{path}: expected exactly one version line, found {n}")
        print(f"  {path.relative_to(REPO)}: -> {new}")
        if not dry:
            path.write_text(updated)


def changelog_section(new: str) -> str:
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "changelog.py"), new],
        capture_output=True, text=True, cwd=REPO,
    )
    if out.returncode != 0:
        print(f"  changelog generation failed: {out.stderr.strip()}", file=sys.stderr)
        return f"## {new} ({date.today().isoformat()})\n\n_(no conventional commits found)_\n"
    return out.stdout


def update_changelog(section: str, dry: bool) -> None:
    path = REPO / "CHANGELOG.md"
    existing = path.read_text() if path.exists() else "# Changelog\n\n"
    head, _, tail = existing.partition("\n## ")
    body = head.rstrip() + "\n\n" + section.rstrip() + "\n"
    if tail:
        body += "\n## " + tail
    print(f"  CHANGELOG.md: prepended {len(section.splitlines())} lines")
    if not dry:
        path.write_text(body)


def release_notes(new: str, section: str, dry: bool) -> None:
    notes_dir = REPO / "docs" / "releases"
    notes = (
        f"# nanofed-tpu {new}\n\nReleased {date.today().isoformat()}.\n\n"
        + section
        + "\n## Install\n\n```bash\npip install nanofed-tpu=="
        + new
        + "\n```\n"
    )
    print(f"  docs/releases/{new}.md: templated")
    if not dry:
        notes_dir.mkdir(parents=True, exist_ok=True)
        (notes_dir / f"{new}.md").write_text(notes)


def sanity_checks() -> None:
    dirty = subprocess.run(["git", "status", "--porcelain"], capture_output=True,
                           text=True, cwd=REPO).stdout.strip()
    if dirty:
        print("  WARNING: working tree is dirty — release commits should be clean")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("version", help="new semantic version, e.g. 0.2.0")
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args()
    if not VERSION_RE.match(args.version):
        raise SystemExit(f"not a semantic version: {args.version!r}")

    old = current_version()
    print(f"prepare release {old} -> {args.version}" + (" (dry run)" if args.dry_run else ""))
    sanity_checks()
    bump(args.version, args.dry_run)
    section = changelog_section(args.version)
    update_changelog(section, args.dry_run)
    release_notes(args.version, section, args.dry_run)
    print("done. review, commit, then: scripts/release.sh")
    return 0


if __name__ == "__main__":
    sys.exit(main())
