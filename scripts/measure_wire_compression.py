#!/usr/bin/env python
"""Measure the q8-delta wire codec on the flagship model: bytes on the wire and
reconstruction error, using a REAL trained round delta (not synthetic noise — deflate
ratios lie on random data).

Writes ``runs/wire_compression_<tag>.json``:
  - payload bytes: full-params npz (the baseline wire format) vs q8-delta, and the
    reference's JSON-float-list encoding size for the same params (its actual wire
    format, ``nanofed/communication/http/server.py:140-149``) computed locally
  - reconstruction error of the dequantized delta vs the true delta
  - end-to-end: a 4-client digits federation run uncompressed vs q8, final accuracy

Usage:
    python scripts/measure_wire_compression.py [--round-tag r05] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--round-tag", default="r05")
    ap.add_argument("--platform", choices=["auto", "cpu"], default="auto")
    ap.add_argument("--n-devices", type=int, default=8)
    args = ap.parse_args()
    if args.platform == "cpu":
        from nanofed_tpu.utils.platform import force_cpu_mesh

        force_cpu_mesh(args.n_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanofed_tpu.communication.codec import (
        decode_delta_q8,
        decode_delta_topk8,
        encode_delta_q8,
        encode_delta_topk8,
        encode_params,
    )
    from nanofed_tpu.data import federate, load_digits_dataset, pack_eval
    from nanofed_tpu.models import get_model
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.trainer.local import make_local_fit

    t0 = time.time()

    # --- Payload sizes on the FLAGSHIP CNN with a real one-client trained delta ---
    model = get_model("mnist_cnn")
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (256, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (256,))
    from nanofed_tpu.core.types import ClientData

    data = ClientData(
        x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.ones((256,), jnp.float32)
    )
    fit = make_local_fit(
        model.apply, TrainingConfig(batch_size=64, local_epochs=2, learning_rate=0.1)
    )
    result = fit(params, data, jax.random.key(1))
    delta = jax.tree.map(
        lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
        result.params, params,
    )

    npz_full = len(encode_params(result.params))
    q8 = encode_delta_q8(delta, seed=0)
    q8_bytes = len(q8)
    topk8_bytes = {
        f"fraction={f}": len(encode_delta_topk8(delta, fraction=f, seed=0))
        for f in (0.05, 0.01)
    }
    # The reference's actual wire format for the same params: JSON float lists.
    json_bytes = len(json.dumps(
        jax.tree.map(lambda a: np.asarray(a).tolist(), result.params)
    ).encode())

    dq = decode_delta_q8(q8, like=delta)
    flat_err = np.concatenate([
        np.abs(a - b).ravel() for a, b in zip(jax.tree.leaves(dq), jax.tree.leaves(delta))
    ])
    flat_mag = np.concatenate([np.abs(a).ravel() for a in jax.tree.leaves(delta)])
    n_params = int(sum(np.asarray(l).size for l in jax.tree.leaves(params)))

    # --- End-to-end accuracy parity over the real HTTP wire path is pinned by
    # tests/integration/test_wire_compression.py; here we measure the SIMULATED
    # aggregate effect of quantizing every client's delta in a small federation ---
    train = load_digits_dataset("train")
    test = load_digits_dataset("test")
    small = get_model("digits_mlp", hidden=64)
    cd = federate(train, num_clients=8, scheme="dirichlet", batch_size=16, seed=0,
                  alpha=0.2)
    from nanofed_tpu.trainer.local import make_evaluator, stack_rngs

    evaluator = make_evaluator(small.apply, batch_size=128)
    eval_data = jax.tree.map(jnp.asarray, pack_eval(test, batch_size=128))
    sfit = make_local_fit(
        small.apply, TrainingConfig(batch_size=16, local_epochs=4, learning_rate=0.2)
    )

    def run_rounds(mode: str, rounds: int = 15) -> float:
        """mode: 'dense' | 'q8' | 'topk8' (top-5% with per-client error feedback)."""
        gp = small.init(jax.random.key(0))
        counts = np.asarray(cd.mask).sum(axis=1)
        w = counts / counts.sum()
        residuals = [None] * 8
        for r in range(rounds):
            rngs = stack_rngs(jax.random.fold_in(jax.random.key(1), r), 8)
            agg = None
            for i in range(8):
                one = jax.tree.map(lambda a: jnp.asarray(a[i]), cd)
                res = sfit(gp, one, rngs[i])
                d = jax.tree.map(
                    lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
                    res.params, gp,
                )
                if mode == "q8":
                    d = decode_delta_q8(encode_delta_q8(d, seed=r * 8 + i), like=d)
                elif mode == "topk8":
                    if residuals[i] is not None:
                        d = jax.tree.map(np.add, d, residuals[i])
                    sent = decode_delta_topk8(
                        encode_delta_topk8(d, fraction=0.05, seed=r * 8 + i), like=d
                    )
                    residuals[i] = jax.tree.map(
                        lambda a, b: a - np.asarray(b, np.float32), d, sent
                    )
                    d = jax.tree.map(lambda s: np.asarray(s, np.float32), sent)
                contrib = jax.tree.map(lambda z, wi=w[i]: wi * z, d)
                agg = contrib if agg is None else jax.tree.map(np.add, agg, contrib)
            gp = jax.tree.map(lambda g, a: np.asarray(g, np.float32) + a, gp, agg)
        return float(evaluator(jax.tree.map(jnp.asarray, gp), eval_data)["accuracy"])

    acc_plain = run_rounds("dense")
    acc_q8 = run_rounds("q8")
    acc_topk8 = run_rounds("topk8")

    artifact = {
        "artifact": f"wire_compression_{args.round_tag}",
        "benchmark": "q8-delta update compression (stochastic int8, QSGD-style) on "
                     "the flagship CNN's real trained round delta",
        "model": "mnist_cnn", "num_params": n_params,
        "payload_bytes": {
            "reference_json_float_lists": json_bytes,
            "npz_full_params": npz_full,
            "q8_delta": q8_bytes,
            "topk8_delta": topk8_bytes,
        },
        "compression_vs_npz": round(npz_full / q8_bytes, 2),
        "compression_vs_reference_json": round(json_bytes / q8_bytes, 2),
        "topk8_compression_vs_npz": {
            k: round(npz_full / v, 1) for k, v in topk8_bytes.items()
        },
        "reconstruction": {
            "max_abs_error": float(flat_err.max()),
            "mean_abs_error": float(flat_err.mean()),
            "mean_abs_delta": float(flat_mag.mean()),
            "relative_mean_error": float(flat_err.mean() / max(flat_mag.mean(), 1e-12)),
        },
        "accuracy_parity_federation": {
            "config": "digits_mlp(64), 8 clients Dirichlet(0.2), 4 local epochs, "
                      "lr 0.2, 15 rounds, every client delta compressed each round "
                      "(topk8: fraction=0.05 with per-client error feedback)",
            "final_accuracy_uncompressed": round(acc_plain, 4),
            "final_accuracy_q8": round(acc_q8, 4),
            "final_accuracy_topk8_ef": round(acc_topk8, 4),
            "accuracy_delta_q8": round(acc_q8 - acc_plain, 4),
            "accuracy_delta_topk8": round(acc_topk8 - acc_plain, 4),
        },
        "platform": str(jax.devices()[0].platform),
        "elapsed_s": round(time.time() - t0, 1),
    }
    out = REPO / "runs" / f"wire_compression_{args.round_tag}.json"
    out.write_text(json.dumps(artifact, indent=2))
    print(json.dumps(artifact, indent=2))
    print(f"\nartifact written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
