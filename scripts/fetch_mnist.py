#!/usr/bin/env python
"""Fetch the real MNIST IDX files (the reference gets them via torchvision,
``nanofed/data/mnist.py:9-40``; this framework reads the IDX files directly —
``nanofed_tpu.data.load_mnist``).

Downloads the four gzip'd IDX files from the first reachable mirror, validates their
STRUCTURE (IDX magic numbers, record counts, 28x28 dims — verifiable offline, unlike
embedded hashes), records each file's SHA-256 into ``checksums.json`` next to the data
for reproducibility, and leaves them where ``load_mnist(data_dir=...)`` expects them.

Usage:
    python scripts/fetch_mnist.py --out data/mnist
    python scripts/fetch_mnist.py --out data/mnist --verify-only   # re-check existing

Zero-egress environments: this script cannot run there (it reports the failure
clearly); use pre-placed IDX/npz files instead, or the bundled sklearn digits dataset
(``nanofed_tpu.data.load_digits_dataset``) as the offline real-data benchmark.
"""

from __future__ import annotations

import argparse
import gzip
import hashlib
import json
import struct
import sys
import urllib.error
import urllib.request
from pathlib import Path

MIRRORS = [
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
]

# file name -> (idx magic, record count)
FILES = {
    "train-images-idx3-ubyte.gz": (2051, 60_000),
    "train-labels-idx1-ubyte.gz": (2049, 60_000),
    "t10k-images-idx3-ubyte.gz": (2051, 10_000),
    "t10k-labels-idx1-ubyte.gz": (2049, 10_000),
}


def validate_idx(path: Path, expect_magic: int, expect_count: int) -> None:
    """Structural validation of a gzip'd IDX file; raises ValueError on mismatch."""
    with gzip.open(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        if magic != expect_magic:
            raise ValueError(f"{path.name}: bad IDX magic {magic} (want {expect_magic})")
        count = struct.unpack(">I", f.read(4))[0]
        if count != expect_count:
            raise ValueError(f"{path.name}: {count} records (want {expect_count})")
        if expect_magic == 2051:  # images: check 28x28 dims and payload size
            rows, cols = struct.unpack(">II", f.read(8))
            if (rows, cols) != (28, 28):
                raise ValueError(f"{path.name}: {rows}x{cols} images (want 28x28)")
            payload = f.read()
            if len(payload) != count * 28 * 28:
                raise ValueError(f"{path.name}: truncated payload")
        else:
            payload = f.read()
            if len(payload) != count:
                raise ValueError(f"{path.name}: truncated payload")


def sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def fetch(name: str, out: Path) -> None:
    last_err: Exception | None = None
    for mirror in MIRRORS:
        url = mirror + name
        try:
            print(f"  {url} ...", flush=True)
            with urllib.request.urlopen(url, timeout=30) as resp:
                out.write_bytes(resp.read())
            return
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            last_err = e
            print(f"    failed: {e}", file=sys.stderr)
    raise SystemExit(
        f"could not download {name} from any mirror (zero-egress environment?): {last_err}"
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="data/mnist", help="target directory for IDX files")
    ap.add_argument("--verify-only", action="store_true", help="only validate existing files")
    args = ap.parse_args()

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    sums: dict[str, str] = {}
    for name, (magic, count) in FILES.items():
        path = out / name
        if not path.exists():
            if args.verify_only:
                print(f"MISSING {path}")
                return 1
            fetch(name, path)
        validate_idx(path, magic, count)
        sums[name] = sha256(path)
        print(f"  ok {name}  sha256={sums[name][:16]}…  ({count} records)")
    (out / "checksums.json").write_text(json.dumps(sums, indent=2))
    print(f"MNIST ready under {out} (checksums.json written)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
