#!/usr/bin/env python
"""Record the closed-loop online-retuning evidence artifact.

The claim under test: the autotuner's AOT ranking can be WRONG about measured
walltime, and the online retuner corrects it mid-run.  On CPU the bytes-
accessed ordering prefers ``client_chunk=1`` (streaming clients one at a time
minimizes materialized bytes), but executing that stream is a sequential XLA
loop — measurably slower per round than the vectorized ``client_chunk=None``
program on the same workload.

The script runs the real lifecycle, no synthetic numbers anywhere:

1. AOT sweep over {client_chunk None vs 1} x {rounds_per_block 2} — the AOT
   winner is the chunked candidate.
2. A probe run measures the ALTERNATIVE (``client_chunk=None``) for real:
   a coordinator pinned to it trains, and its per-round walltimes (first
   block dropped — it pays the compile) seed the retuner's measured table.
3. The closed loop: ``Coordinator.from_autotune`` starts on the AOT winner
   with ``retune_every=2``; the first blocks measure the incumbent, the
   retuner re-ranks on measured walltime, the swap lands at a block
   boundary, and the remaining rounds run the corrected program.
4. ``write_back()`` stamps the measured numbers into the cached autotune
   entry, so the next run on this host starts from measured reality.

Writes ``runs/retune_r17_<stamp>.json``.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

NUM_ROUNDS = 16
RPB = 2
# Two blocks before the first decision: the incumbent's first block pays its
# compile, the second is steady state — so the run itself holds a compile-free
# incumbent measurement to compare the post-swap rounds against.
RETUNE_EVERY = 4
PROBE_ROUNDS = 6


def read_jsonl(path: Path) -> list[dict]:
    return [json.loads(line) for line in path.read_text().splitlines() if line]


def telemetry_records(tel_dir: Path) -> list[dict]:
    files = sorted(tel_dir.glob("*.jsonl"))
    assert files, f"no telemetry written under {tel_dir}"
    out: list[dict] = []
    for f in files:
        out.extend(read_jsonl(f))
    return out


def main() -> int:
    import tempfile

    import jax

    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.tuning import CandidateConfig, TuningSpace, candidate_program_name

    work = Path(tempfile.mkdtemp(prefix="retune_evidence_"))
    model = get_model("digits_mlp")
    train = synthetic_classification(1024, 10, (8, 8, 1), seed=0)
    data = federate(train, num_clients=32, scheme="iid", batch_size=16, seed=0)
    training = TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1)
    space = TuningSpace(client_chunks=(None, 1), rounds_per_blocks=(RPB,),
                        model_shards=(1,), batch_sizes=(16,))
    alt = CandidateConfig(client_chunk=None, rounds_per_block=RPB,
                          model_shards=1, batch_size=16)

    # --- 2. Probe the alternative for real ------------------------------------
    # A short pinned run measures the ALTERNATIVE's steady-state rate (first
    # block dropped — it pays the program compile); this seeds the retuner's
    # measured table.  The incumbent needs no probe: the closed loop itself
    # measures it before the first decision.
    probe = Coordinator(
        model=model, train_data=data,
        config=CoordinatorConfig(num_rounds=PROBE_ROUNDS, seed=0,
                                 base_dir=work / "probe",
                                 rounds_per_block=RPB),
        training=training, client_chunk=None,
        telemetry_dir=work / "probe_tel",
    )
    for _ in probe.start_training():
        pass
    probe_rounds = [r for r in telemetry_records(work / "probe_tel")
                    if r.get("type") == "round"][RPB:]
    assert len(probe_rounds) >= 2, "probe too short to satisfy min_rounds"
    probe_n = len(probe_rounds)
    probe_walltime = sum(r["duration_s"] for r in probe_rounds)

    # --- 1 + 3. The closed loop on the AOT winner -----------------------------
    coord = Coordinator.from_autotune(
        model, data,
        CoordinatorConfig(num_rounds=NUM_ROUNDS, seed=0,
                          base_dir=work / "runs",
                          retune_every=RETUNE_EVERY),
        training, tuning_space=space,
        autotune_cache_dir=work / "cache",
        telemetry_dir=work / "tel",
    )
    result = coord.autotune_result
    aot_ranking = [
        {
            "program": candidate_program_name(o.config),
            "config": o.config.to_dict(),
            "aot_score": o.score,
            "aot_rank": i,
        }
        for i, o in enumerate(sorted(
            (o for o in result.outcomes if o.feasible),
            key=lambda o: o.score,
        ))
    ]
    incumbent = coord._retune_candidate
    assert incumbent.client_chunk == 1, (
        "expected the AOT model to pick the chunked candidate on CPU "
        f"(got {incumbent.to_dict()}) — the disagreement premise is gone"
    )
    coord.retuner.observe(alt, rounds=probe_n, walltime_s=probe_walltime)

    for _ in coord.start_training():
        pass

    # --- Harvest --------------------------------------------------------------
    records = telemetry_records(work / "tel")
    retunes = [r for r in records if r.get("type") == "retune"]
    summaries = [r for r in records if r.get("type") == "retune_summary"]
    assert summaries, "no retune_summary record — write_back never ran"
    summary = summaries[-1]
    swaps = [r for r in retunes if r.get("swap") and r.get("applied")]
    assert len(swaps) == 1, f"expected exactly one applied swap, got {swaps}"
    swap = swaps[0]
    assert swap["round"] % RPB == 0, "swap did not land on a block boundary"

    table = coord.retuner.measured_table()
    inc_name = candidate_program_name(incumbent)
    alt_name = candidate_program_name(alt)
    inc_measured = table[inc_name]
    alt_measured = table[alt_name]
    # The alternative's table row mixes the probe seed with the post-swap
    # blocks; subtract the probe to report the post-swap rounds alone.
    post_swap_rounds = alt_measured["rounds"] - probe_n
    post_swap_s_per_round = (
        alt_measured["rounds"] * alt_measured["s_per_round"] - probe_walltime
    ) / post_swap_rounds
    assert post_swap_rounds == NUM_ROUNDS - swap["round"]
    assert post_swap_s_per_round < inc_measured["s_per_round"], (
        "post-swap rounds were not faster than the incumbent's measured rate"
    )

    # Steady-state rates straight from the run's own round telemetry, so the
    # comparison is within one process (cross-phase walltimes on a 1-core
    # host drift too much to compare).  Rounds are 0-indexed; each program's
    # FIRST block pays its compile and is dropped:
    #   [0, RPB)                incumbent compile block
    #   [RPB, swap)             incumbent steady state
    #   [swap, swap+RPB)        swapped-in program's compile block
    #   [swap+RPB, NUM_ROUNDS)  post-swap steady state
    round_records = {r["round"]: r for r in records if r.get("type") == "round"}
    inc_steady_rounds = [
        round_records[i]["duration_s"] for i in range(RPB, swap["round"])
    ]
    post_steady_rounds = [
        round_records[i]["duration_s"]
        for i in range(swap["round"] + RPB, NUM_ROUNDS)
    ]
    assert inc_steady_rounds and post_steady_rounds
    inc_steady = sum(inc_steady_rounds) / len(inc_steady_rounds)
    post_swap_steady = sum(post_steady_rounds) / len(post_steady_rounds)
    assert post_swap_steady < inc_steady, (
        f"steady post-swap rounds ({post_swap_steady:.6f}s) were not faster "
        f"than the incumbent's steady block ({inc_steady:.6f}s)"
    )

    cache_entry = summary.get("cache_entry")
    assert cache_entry, "retune_summary carries no cache_entry path"
    entry = json.loads(Path(cache_entry).read_text())
    assert "measured" in entry, "write-back left no measured block in the entry"

    dev = jax.devices()[0]
    artifact = {
        "what": (
            "closed-loop online retuning on a real workload: the AOT ranking "
            "prefers the chunked round program, measured walltime prefers "
            "the vectorized one, and the retuner swaps mid-run"
        ),
        "basis": (
            f"measured wall-clock per round on platform={dev.platform!r} "
            f"device_kind={dev.device_kind!r} (jax {jax.__version__}); the "
            "AOT scores are the sweep's bytes-accessed ordering (CPU has no "
            "published peaks).  digits_mlp, 32 clients iid, synthetic "
            f"classification, {NUM_ROUNDS} rounds, rounds_per_block={RPB}, "
            f"retune_every={RETUNE_EVERY}."
        ),
        "aot_ranking": aot_ranking,
        "probe": {
            "program": alt_name,
            "rounds": probe_n,
            "walltime_s": round(probe_walltime, 6),
            "s_per_round": round(probe_walltime / probe_n, 6),
            "note": "first block dropped (compile), fed to the retuner as "
                    "the alternative's real measurement",
        },
        "disagreement": {
            "aot_winner": inc_name,
            "measured_winner": alt_name,
            "aot_says": f"{inc_name} beats {alt_name}",
            "measured_says": (
                f"{alt_name} at {post_swap_steady:.6f}s/round steady-state "
                f"beats {inc_name} at {inc_steady:.6f}s/round steady-state, "
                "both measured inside the same closed-loop run"
            ),
            "steady_state_s_per_round": {
                inc_name: round(inc_steady, 6),
                alt_name: round(post_swap_steady, 6),
            },
            "note": (
                "steady-state = the run's own per-round telemetry walltimes "
                "with each program's first (compile-paying) block dropped; "
                "the retuner's in-run measurements additionally charge the "
                "incumbent its first-block compile, which is real cost too"
            ),
        },
        "swap": {
            "round": swap["round"],
            "block_boundary": True,
            "old_program": swap.get("old_program"),
            "new_program": swap.get("new_program"),
            "delta": swap.get("delta"),
            "basis": swap.get("basis"),
        },
        "post_swap": {
            "rounds": post_swap_rounds,
            "s_per_round": round(post_swap_s_per_round, 6),
            "incumbent_s_per_round": inc_measured["s_per_round"],
            "speedup_pct": round(100.0 * (
                1.0 - post_swap_s_per_round / inc_measured["s_per_round"]
            ), 2),
            "steady_s_per_round": round(post_swap_steady, 6),
            "steady_rounds": len(post_steady_rounds),
            "steady_vs_incumbent_steady_speedup_pct": round(100.0 * (
                1.0 - post_swap_steady / inc_steady
            ), 2),
        },
        "decisions": [
            {k: r.get(k) for k in (
                "round", "swap", "applied", "old_program", "new_program",
                "measured_s_per_round", "candidate_s_per_round", "delta",
                "basis", "reason",
            ) if r.get(k) is not None}
            for r in retunes
        ],
        "write_back": {
            "cache_entry": cache_entry,
            "measured": entry["measured"],
        },
    }

    out_dir = Path(__file__).resolve().parent.parent / "runs"
    out_dir.mkdir(exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    out = out_dir / f"retune_r17_{stamp}.json"
    out.write_text(json.dumps(artifact, indent=2, sort_keys=True) + "\n")
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
